"""Benchmark: IDC patches/sec/chip on the VGG16 fine-tune step.

The north-star metric from BASELINE.json — the TPU generalization of the
reference's fine-tune Timer (dist_model_tf_vgg.py:156: TRAIN_SIZE x
epochs / wall-clock). The reference publishes no numbers (BASELINE.md),
so `vs_baseline` is the ratio against a recorded earlier measurement in
BENCH_BASELINE.json when present, else 1.0 (this run defines the
baseline).

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "patches/sec/chip", "vs_baseline": N}

Runs on whatever jax.devices() provides (one real TPU chip under the
driver; CPU elsewhere). Uses the real production train step: bfloat16
compute (MXU), fine-tune trainability mask, donated state.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.vgg import vgg16, fine_tune_mask
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform  # "tpu"/"axon" on chip, else "cpu"
    on_accelerator = platform != "cpu"
    per_chip_batch = 128 if on_accelerator else 16
    batch = per_chip_batch * n_dev
    warmup, steps = 3, (20 if on_accelerator else 3)

    mesh = meshlib.data_mesh()
    model = vgg16(num_outputs=1)
    variables = model.init(jax.random.key(0))
    opt = rmsprop(1e-4, trainable_mask=fine_tune_mask(variables.params, 15))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    step = jit_data_parallel(
        make_train_step(model, opt, binary_cross_entropy,
                        compute_dtype=jnp.bfloat16), mesh)

    rng = np.random.default_rng(0)
    imgs = rng.random((batch, 50, 50, 3)).astype(np.float32)
    labels = (rng.random(batch) > 0.5).astype(np.int32)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)

    # Block on the full state, not just the loss: the loss only needs the
    # forward pass, so blocking on it would exclude backward + update.
    key = jax.random.key(1)
    for i in range(warmup):
        key, sub = jax.random.split(key)
        state, m = step(state, x, y, sub)
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for i in range(steps):
        key, sub = jax.random.split(key)
        state, m = step(state, x, y, sub)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    patches_per_sec_per_chip = steps * batch / dt / n_dev
    baseline_path = Path(__file__).parent / "BENCH_BASELINE.json"
    vs = 1.0
    if baseline_path.exists():
        base = json.loads(baseline_path.read_text()).get("value")
        if base:
            vs = patches_per_sec_per_chip / base
    print(json.dumps({
        "metric": "IDC patches/sec/chip (VGG16 fine-tune, bf16)",
        "value": round(patches_per_sec_per_chip, 2),
        "unit": "patches/sec/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
