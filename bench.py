"""Benchmark: the three BASELINE.md north-star metrics on real hardware.

1. IDC patches/sec/chip — VGG16 fine-tune step, bf16 (the TPU
   generalization of the reference's fine-tune Timer,
   dist_model_tf_vgg.py:156: TRAIN_SIZE x epochs / wall-clock).
2. FedAvg round wall-clock per chip (fed_model.py:214 Timer / rounds).
3. Secure-FedAvg round wall-clock per chip (secure_fed_model.py:223).

Prints exactly ONE JSON line; the headline metric is (1), with (2), (3)
the sequence-parallel forward sample, and the self-checks carried as
extra keys:

    {"metric": ..., "value": N, "unit": "patches/sec/chip",
     "vs_baseline": N, "mfu": f, "step_tflops": f, "peak_tflops": f,
     "fed_round_s": f, "secure_round_s": f, "ring_fwd_t": n,
     "ring_fwd_pallas_ms": f, "ring_fwd_speedup_vs_jnp": f,
     "prefill_ms": f, "decode_ms_per_token": f,
     "decode_tokens_per_sec": f}

Measurement methodology (hard-won, round 2): on this environment's
tunneled TPU runtime, `jax.block_until_ready` can return WITHOUT waiting
for device execution, which made round 1's number a dispatch-rate
measurement (341k patches/s = 2.3x the chip's bf16 peak — impossible).
Every timed region here therefore ends with a host fetch of a scalar
that data-depends on the final state — the device cannot fake that.
The MFU self-check makes this class of error loud: FLOPs come from
XLA's post-DCE `compiled.cost_analysis()` (cross-checked against an
analytic count from the VGG topology), peak from the device kind, and
any MFU outside (0, 1] is a hard failure, not a result.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

def _peak_tflops(device) -> float | None:
    """Nominal peak dense bf16 TFLOP/s per chip — the per-backend
    roofline registry (observe/profile.py BACKEND_ROOFS, seeded from
    the table that used to live here) is the one source of truth."""
    from idc_models_tpu.observe.profile import roofline_for

    spec = roofline_for(device)
    return spec.peak_tflops if spec else None


def analytic_vgg16_step_flops(image_size: int = 50,
                              fine_tune_at: int = 15) -> float:
    """Per-patch FLOPs of the fine-tune train step: full forward + the
    live backward (only layers with Keras index >= fine_tune_at get
    gradients; XLA dead-code-eliminates the rest — the explicit analogue
    of the reference's frozen layers, dist_model_tf_vgg.py:146)."""
    from idc_models_tpu.models.vgg import _CFG, KERAS_LAYER_INDEX

    s, c_in = image_size, 3
    fwd: dict[str, float] = {}
    for block, filters, n_convs in _CFG:
        for conv in range(1, n_convs + 1):
            fwd[f"block{block}_conv{conv}"] = 2.0 * 9 * c_in * filters * s * s
            c_in = filters
        s //= 2
    head = 2.0 * 512 * 1
    live = [n for n, i in KERAS_LAYER_INDEX.items() if i >= fine_tune_at]
    # backward: dX + dW per live conv layer, each ~= its forward cost
    bwd = 2.0 * sum(fwd[n] for n in live) + 2.0 * head
    return sum(fwd.values()) + head + bwd


def _run_timed(call, state0, key0, *, warmup: int, min_seconds: float,
               start_steps: int, max_steps: int = 400, box=None):
    """Measure `call(state, rng) -> state` honestly.

    Every timed region ends with a host fetch of a scalar that
    data-depends on the final state (see module docstring: on this
    runtime `block_until_ready` can return early, so a fetch is the only
    trustworthy fence). Grows the iteration count until wall-clock >=
    min_seconds so fixed sync overhead (~50-90 ms through the tunnel)
    stays small. Returns (iters, best_seconds, box, window_seconds) —
    ALL measured windows are returned so the recorded JSON can carry the
    median next to the best and a drift-band excursion can be told from
    a real regression (ADVICE r2). Pass the returned `box` back in to
    re-measure later without touching the (donated) original state.
    """
    import jax
    import jax.numpy as jnp

    digest = jax.jit(
        lambda s: jnp.sum(s.params["head"]["kernel"].astype(jnp.float32)))
    if box is None:
        box = {"s": state0, "k": key0}

    def loop(n):
        s, k = box["s"], box["k"]
        for _ in range(n):
            k, sub = jax.random.split(k)
            s = call(s, sub)
        box["s"], box["k"] = s, k

    def fence():
        return float(digest(box["s"]))

    loop(warmup)
    fence()
    steps = start_steps
    while True:
        t0 = time.perf_counter()
        loop(steps)
        fence()
        dt = time.perf_counter() - t0
        if dt >= min_seconds or steps >= max_steps:
            break
        steps = min(max_steps, max(steps * 2,
                                   int(steps * 1.5 * min_seconds / dt)))
    # The tunneled runtime adds multi-ms jitter per window AND slow
    # multi-minute drift (observed ±10% on the same executable — the
    # chip is shared); extra windows are cheap and the best-of-4 is the
    # honest device throughput.
    dts = [dt]
    for _ in range(3):
        t0 = time.perf_counter()
        loop(steps)
        fence()
        dts.append(time.perf_counter() - t0)
    return steps, min(dts), box, dts


def _timed_train_step(model, opt, loss_fn, imgs, labels,
                      on_accelerator: bool, *, axis=None,
                      start_steps=None, pre_sharded=None):
    """The one train-step bench body every backbone/model bench shares:
    build the TrainState, jit the bf16 step with DP shardings, AOT-
    compile ONCE (post-DCE FLOPs come from that executable; re-calling
    the jitted fn would compile a second copy), then `_run_timed` with
    the honest host-fetch fence. Returns a dict incl. the compiled
    executable, the `_run_timed` box (for spaced re-measures), and
    per-step FLOPs — so a methodology fix lands in every bench at once."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate,
        shard_batch,
    )

    variables = model.init(jax.random.key(0))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    if pre_sharded is not None:
        mesh, x, y = pre_sharded
    else:
        mesh = meshlib.data_mesh()
    step = jit_data_parallel(
        make_train_step(model, opt, loss_fn, compute_dtype=jnp.bfloat16),
        mesh, axis=axis)
    if pre_sharded is None:
        x, y = shard_batch(mesh, imgs, labels)
    state = replicate(mesh, state)
    compiled = step.lower(state, x, y, jax.random.key(1)).compile()
    # ONE extraction point for XLA cost/memory accounting (ISSUE 9):
    # observe.profile.program_report — the hand-rolled cost_analysis()
    # parsing that used to live here is banned by static scan
    from idc_models_tpu.observe.profile import program_report

    flops_per_step = program_report(compiled,
                                    name="train.step").flops or 0.0
    steps, dt, box, dts = _run_timed(
        lambda s, sub: compiled(s, x, y, sub)[0], state, jax.random.key(1),
        warmup=3, min_seconds=1.0 if on_accelerator else 0.2,
        start_steps=(start_steps if start_steps is not None
                     else (20 if on_accelerator else 2)))
    return {"steps": steps, "dt": dt, "dts": dts, "box": box,
            "compiled": compiled, "x": x, "y": y,
            "flops_per_step": flops_per_step,
            "min_seconds": 1.0 if on_accelerator else 0.2}


def bench_vgg_throughput(on_accelerator: bool):
    import jax
    import jax.numpy as jnp  # noqa: F401 (dtype constants via helper)

    from idc_models_tpu.models.vgg import vgg16, fine_tune_mask
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    # the whole configuration (batch/lr/fine_tune_at/image) comes from
    # the shared configs.BENCH_TRAIN_CONFIGS table the `profile` verb
    # reads too — a re-tune moves both surfaces together (batch
    # provenance documented at the table)
    from idc_models_tpu.configs import BENCH_TRAIN_CONFIGS

    cfg = BENCH_TRAIN_CONFIGS["vgg16"]
    per_chip_batch = cfg["batch_per_chip"] if on_accelerator else 16
    batch = per_chip_batch * n_dev
    size = cfg["image_size"]

    model = vgg16(num_outputs=cfg["num_outputs"])
    opt = rmsprop(cfg["lr"], trainable_mask=fine_tune_mask(
        model.init(jax.random.key(0)).params, cfg["fine_tune_at"]))
    rng = np.random.default_rng(0)
    imgs = rng.random((batch, size, size, 3)).astype(np.float32)
    labels = (rng.random(batch) > 0.5).astype(np.int32)
    r = _timed_train_step(model, opt, binary_cross_entropy, imgs, labels,
                          on_accelerator)
    steps, dt, dts, box = r["steps"], r["dt"], r["dts"], r["box"]
    compiled, x, y = r["compiled"], r["x"], r["y"]
    flops_per_step = r["flops_per_step"]
    min_seconds = r["min_seconds"]

    def result(steps, dt, dts):
        import statistics

        med = statistics.median(dts)
        return {
            "patches_per_sec_per_chip": steps * batch / dt / n_dev,
            "median_patches_per_sec_per_chip": steps * batch / med / n_dev,
            "window_s": [round(d, 4) for d in dts],
            "batch_per_chip": per_chip_batch,
            "steps": steps,
            "flops_per_patch": (flops_per_step / batch
                                if flops_per_step else None),
            "step_tflops": (flops_per_step * steps / dt / 1e12 / n_dev
                            if flops_per_step else None),
        }

    def remeasure():
        """Re-time the SAME compiled executable (the chip's shared-load
        drift spans minutes, so a second sample spaced out by the other
        benchmarks beats more back-to-back windows).

        Holding this closure pins the VGG state + batch (~250 MB/chip)
        in HBM through the other benchmarks; the cached bench's
        32k/chip batch (~600 MB features) still fits a 16 GB chip with
        that residency — verified by full runs on the v5 lite chip. If
        a future workload gets tight, drop the second sample before
        growing batch sizes."""
        steps2, dt2, _, dts2 = _run_timed(
            lambda s, sub: compiled(s, x, y, sub)[0], None, None,
            warmup=1, min_seconds=min_seconds, start_steps=steps, box=box)
        return result(steps2, dt2, dts2)

    out = result(steps, dt, dts)
    out["remeasure"] = remeasure
    return out


def bench_vgg_cached_throughput(on_accelerator: bool):
    """Fine-tune patches/sec with the frozen-backbone feature cache
    (--cache-features): the suffix (block5 + head) train step over cached
    block4_pool activations — same parameters updated, same math, minus
    the per-step recompute of the frozen prefix."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry
    from idc_models_tpu.models.vgg import KERAS_LAYER_INDEX, vgg16
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train import feature_cache as fc
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    # batch sweep (experiments/mfu_matrix.jsonl, round 3): 32768 -> 506k,
    # 65536 -> 515k, 131072 -> 527k patches/s; features are 3x3x512 so
    # 131072/chip is ~2.4 GB HBM — verified to fit alongside the headline
    # bench's resident VGG state on the 16 GB v5 lite chip
    per_chip_batch = 131072 if on_accelerator else 16
    batch = per_chip_batch * n_dev

    mesh = meshlib.data_mesh()
    model = vgg16(num_outputs=1)
    spec = registry.get_model("vgg16")
    plan = fc.plan_feature_cache(model, KERAS_LAYER_INDEX, 15, 512, 1)
    variables = model.init(jax.random.key(0))
    sp, ss = fc.suffix_variables(plan, variables.params, variables.state)
    opt = rmsprop(1e-4, trainable_mask=spec.fine_tune_mask(sp, 15))
    state = TrainState(step=jnp.zeros((), jnp.int32), params=sp,
                       model_state=ss, opt_state=opt.init(sp))
    step = jit_data_parallel(
        make_train_step(plan.suffix_model, opt, binary_cross_entropy,
                        compute_dtype=jnp.bfloat16), mesh)

    rng = np.random.default_rng(0)
    feats = rng.random((batch, 3, 3, 512)).astype(np.float32)
    labels = (rng.random(batch) > 0.5).astype(np.int32)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, feats, labels)
    compiled = step.lower(state, x, y, jax.random.key(1)).compile()
    steps, dt, _, _ = _run_timed(
        lambda s, sub: compiled(s, x, y, sub)[0], state, jax.random.key(1),
        warmup=3, min_seconds=1.0 if on_accelerator else 0.2,
        start_steps=20 if on_accelerator else 2)
    return steps * batch / dt / n_dev


def bench_backbone_throughput(model_name: str, on_accelerator: bool):
    """Fine-tune train-step throughput for the OTHER two reference DP
    backbones (VERDICT r4 #1): MobileNetV2 at its 50x50 IDC config
    (dist_model_tf_mobile.py:119-129, fine_tune_at=100) and DenseNet201
    at its 32x32 CIFAR-10 config (dist_model_tf_dense.py:131-158,
    fine_tune_at=150). Same methodology as the VGG headline; per-chip
    batches are the measured optima from experiments/backbone_mfu.jsonl.
    Both backbones are HBM-bandwidth-bound on TPU (depthwise convs /
    tiny-spatial concat stages), so MFU is reported next to the
    bandwidth-roofline ceiling in BASELINE.md rather than against 1.0."""
    import jax

    from idc_models_tpu.models import registry
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    # the ONE bench/profile config table (configs.BENCH_TRAIN_CONFIGS;
    # measured batch optima documented there — mobile 4096: 319k p/s,
    # 8192 regresses; dense 2048: 97k reproduced twice, 1024 sat in
    # the drift band and 4096 regresses to 82k). The `profile` CLI
    # verb reads the same table so its MFU agrees with this one.
    from idc_models_tpu.configs import BENCH_TRAIN_CONFIGS

    cfg = BENCH_TRAIN_CONFIGS[model_name]
    n_dev = len(jax.devices())
    per_chip = cfg["batch_per_chip"] if on_accelerator else 8
    batch = per_chip * n_dev
    spec = registry.get_model(model_name)
    model = spec.build(cfg["num_outputs"], 3,
                       bn_frozen_below=cfg["fine_tune_at"])
    opt = rmsprop(cfg["lr"],
                  trainable_mask=spec.fine_tune_mask(
                      model.init(jax.random.key(0)).params,
                      cfg["fine_tune_at"]))
    loss_fn = (binary_cross_entropy if cfg["num_outputs"] == 1
               else sparse_categorical_cross_entropy)
    rng = np.random.default_rng(0)
    s = cfg["image_size"]
    imgs = rng.random((batch, s, s, 3)).astype(np.float32)
    labels = rng.integers(0, max(cfg["num_outputs"], 2),
                          batch).astype(np.int32)
    r = _timed_train_step(model, opt, loss_fn, imgs, labels,
                          on_accelerator)
    pps = r["steps"] * batch / r["dt"] / n_dev
    tfs = (r["flops_per_step"] * r["steps"] / r["dt"] / 1e12 / n_dev
           if r["flops_per_step"] else None)
    return pps, tfs


def bench_backbone_fused(on_accelerator: bool):
    """ISSUE 16: the fused-backbone record — MobileNetV2 with the Pallas
    depthwise+BN+relu6 chain (`depthwise_impl="fused"`) and DenseNet201
    with concat-free packed blocks (`block_impl="packed"`) vs each
    model's unfused baseline, SAME fine-tune train-step methodology as
    `bench_backbone_throughput` (the variants come from
    registry.FUSED_BUILD_KWARGS / UNFUSED_BUILD_KWARGS, the one
    definition the profile verb and experiments/fused_backbone.py share).

    Emits `{mobile,dense}_fused_patches_per_sec`, `*_fused_speedup`
    (fused/unfused throughput) and — only where a roofline is known, so
    TPU device kinds — `*_fused_hbm_utilization`, the achieved fraction
    of peak HBM bytes/s. The mobile byte count merges the analytic
    Pallas-kernel cost (ops/fused_conv.depthwise_call_cost via
    mobilenet.fused_call_shapes) into XLA's accounting, which cannot
    see inside pallas_call (docs/BENCHMARKS.md MFU-attribution note);
    DenseNet's packed blocks are ordinary XLA ops, fully accounted.

    Structural gates run on EVERY backend: both variants of each model
    must agree on a forward pass (fp-close; bit-close for the packed
    DenseNet) from identical init params — on CPU the Pallas kernel
    runs in interpret mode, so this is the same-code-path parity the
    tier-1 suite banks on. The speedup >= 1 PERF gate is asserted only
    on TPU device kinds: interpret-mode Pallas on CPU is a correctness
    vehicle, not a performance claim."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.configs import BENCH_TRAIN_CONFIGS
    from idc_models_tpu.models import registry
    from idc_models_tpu.observe.profile import roofline_for
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    dev = jax.devices()[0]
    n_dev = len(jax.devices())
    spec_roof = roofline_for(dev) if on_accelerator else None
    out = {}
    for model_name, tag in (("mobilenet_v2", "mobile"),
                            ("densenet201", "dense")):
        cfg = BENCH_TRAIN_CONFIGS[model_name]
        per_chip = cfg["batch_per_chip"] if on_accelerator else 1
        batch = per_chip * n_dev
        size = cfg["image_size"]
        spec = registry.get_model(model_name)
        loss_fn = (binary_cross_entropy if cfg["num_outputs"] == 1
                   else sparse_categorical_cross_entropy)
        rng = np.random.default_rng(0)
        imgs = rng.random((batch, size, size, 3)).astype(np.float32)
        labels = rng.integers(0, max(cfg["num_outputs"], 2),
                              batch).astype(np.int32)

        # forward parity gate: identical init (deterministic from the
        # module structure + key) through both data paths, eval mode so
        # the mobile fused chain engages on every depthwise layer
        fused_kw = registry.FUSED_BUILD_KWARGS[model_name]
        base_kw = registry.UNFUSED_BUILD_KWARGS[model_name]
        m_fused = spec.build(cfg["num_outputs"], 3,
                             bn_frozen_below=cfg["fine_tune_at"],
                             **fused_kw)
        m_base = spec.build(cfg["num_outputs"], 3,
                            bn_frozen_below=cfg["fine_tune_at"],
                            **base_kw)
        v = m_fused.init(jax.random.key(0))
        xp = jnp.asarray(imgs[: min(batch, 2)])
        y_f, _ = jax.jit(lambda p, s, a: m_fused.apply(p, s, a,
                                                       train=False))(
            v.params, v.state, xp)
        y_b, _ = jax.jit(lambda p, s, a: m_base.apply(p, s, a,
                                                      train=False))(
            v.params, v.state, xp)
        np.testing.assert_allclose(
            np.asarray(y_f), np.asarray(y_b), rtol=1e-4, atol=1e-4,
            err_msg=f"{model_name}: fused forward disagrees with the "
                    f"unfused baseline — the fused record would be "
                    f"measuring a different model")

        pps = {}
        bytes_per_step = None
        for variant, model in (("fused", m_fused), ("base", m_base)):
            opt = rmsprop(cfg["lr"], trainable_mask=spec.fine_tune_mask(
                model.init(jax.random.key(0)).params,
                cfg["fine_tune_at"]))
            r = _timed_train_step(model, opt, loss_fn, imgs, labels,
                                  on_accelerator)
            pps[variant] = r["steps"] * batch / r["dt"] / n_dev
            if variant == "fused":
                from idc_models_tpu.observe.profile import program_report

                cost = program_report(r["compiled"], name=f"{tag}.fused")
                bytes_per_step = cost.bytes_accessed
                if model_name == "mobilenet_v2":
                    from idc_models_tpu.models import mobilenet
                    from idc_models_tpu.ops import fused_conv

                    _, k_bytes = fused_conv.depthwise_chain_cost(
                        mobilenet.fused_call_shapes(batch, size))
                    bytes_per_step = (bytes_per_step or 0.0) + k_bytes
                step_s_fused = r["dt"] / r["steps"]
        speedup = pps["fused"] / pps["base"]
        out[f"{tag}_fused_patches_per_sec"] = round(pps["fused"], 2)
        out[f"{tag}_fused_speedup"] = round(speedup, 3)
        if spec_roof is not None and bytes_per_step:
            achieved_gbps = bytes_per_step / n_dev / step_s_fused / 1e9
            out[f"{tag}_fused_hbm_utilization"] = round(
                achieved_gbps / spec_roof.peak_hbm_gbps, 4)
        if on_accelerator and dev.platform == "tpu":
            assert speedup >= 1.0, (
                f"{model_name}: fused backbone is SLOWER than the "
                f"unfused baseline on {dev.device_kind} "
                f"({pps['fused']:.0f} vs {pps['base']:.0f} patches/s) — "
                f"the fused default must not ship a regression "
                f"(ISSUE 16 perf gate)")
    return out


def bench_zigzag_schedule(on_accelerator: bool):
    """Zigzag vs contiguous causal ring COMPUTE schedule (emulated
    ring-of-8 per-device schedule, pallas blocks, t_local=16384) — the
    driver-side record of experiments/zigzag_bench.py's headline row.
    Only meaningful on the chip (interpret-mode pallas at this size is
    not runnable); returns {} off-accelerator."""
    if not on_accelerator:
        return {}
    import sys as _sys

    import jax.numpy as jnp
    import numpy as np

    _sys.path.insert(0, str(Path(__file__).parent / "experiments"))
    from zigzag_bench import B, D, H, N, make_schedule

    t_local = 16384
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(0, 1, (B, t_local, H, D)), jnp.bfloat16)
    kv = jnp.asarray(rng.normal(0, 1, (N, 2, B, t_local, H, D)),
                     jnp.bfloat16)
    iters, times = 4, {}
    for layout in ("contiguous", "zigzag"):
        fn = make_schedule(layout, t_local)
        o = fn(q, kv)
        _ = float(jnp.sum(o.astype(jnp.float32)))
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            o = q
            for _ in range(iters):
                o = fn(o, kv).astype(jnp.bfloat16)
            _ = float(jnp.sum(o.astype(jnp.float32)))
            best = min(best, (time.perf_counter() - t0) / iters)
        times[layout] = best
    return {"zigzag_t_local": t_local, "zigzag_ring": N,
            "zigzag_contiguous_ms": round(times["contiguous"] * 1e3, 2),
            "zigzag_zigzag_ms": round(times["zigzag"] * 1e3, 2),
            "zigzag_schedule_speedup":
                round(times["contiguous"] / times["zigzag"], 3)}


def bench_flash_train(on_accelerator: bool):
    """Flash fwd+bwd at the existence-proof scale (VERDICT r4 #3): the
    pallas ring's full forward+backward at t_local=16384 — the config
    where the jnp autodiff path fails TPU compilation outright (8.6 GB
    f32 scores; experiments/flash_bwd_bench.jsonl) — recorded
    driver-side every round. Returns {} off-accelerator."""
    if not on_accelerator:
        return {}
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.ring_attention import make_ring_attention

    T = 16384
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, T, 8, 64)), jnp.bfloat16)
               for _ in range(3))
    ring = make_ring_attention(meshlib.seq_mesh(1), causal=True,
                               block_impl="pallas")
    gfn = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(ring(a, b, c).astype(jnp.float32) ** 2)))
    dq = gfn(q, k, v)
    _ = float(jnp.sum(dq.astype(jnp.float32)))
    iters, best = 4, 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        a = q
        for _ in range(iters):
            dq = gfn(a, k, v)
            scl = jax.lax.rsqrt(jnp.mean(dq.astype(jnp.float32) ** 2)
                                + 1e-9)
            a = (dq.astype(jnp.float32) * scl).astype(jnp.bfloat16)
        _ = float(jnp.sum(a.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / iters)
    return {"flash_fwd_bwd_t": T,
            "flash_fwd_bwd_ms": round(best * 1e3, 2)}


def bench_attention_model_step(on_accelerator: bool):
    """End-to-end MODEL train step at 16,384 tokens: attention_classifier
    (2 blocks, d_model=512, 8 heads, mlp 2048, pallas blocks, ring of 1)
    through the standard train step — the model-level long-context
    record (BASELINE.md round-4 table), driver-side. Returns {}
    off-accelerator (the dense path cannot even compile there and the
    pallas path needs the real chip)."""
    if not on_accelerator:
        return {}
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.attention import attention_classifier
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    T = 16384
    mesh = meshlib.seq_mesh(1)
    model = attention_classifier(T, 8, embed_dim=512, num_heads=8,
                                 mlp_dim=2048, num_blocks=2,
                                 num_outputs=1, mesh=mesh, causal=True,
                                 block_impl="pallas")
    rng = np.random.default_rng(0)
    # batch of 1 on the ring-of-1 mesh: feed device-resident directly
    x = jnp.asarray(rng.normal(0, 1, (1, T, 8)).astype(np.float32))
    y = jnp.asarray(np.asarray([1], np.int32))
    r = _timed_train_step(model, rmsprop(1e-4), binary_cross_entropy,
                          None, None, True, axis=meshlib.SEQ_AXIS,
                          start_steps=4, pre_sharded=(mesh, x, y))
    return {"model_step_t": T,
            "model_step_ms": round(r["dt"] / r["steps"] * 1e3, 2)}


def bench_fed_round(on_accelerator: bool, n_clients: int = 10):
    """FedAvg round wall-clock at the reference's scale: 10 VGG16
    clients (fed_model.py:47) laid out k-per-device over however many
    chips exist (fed_model.py:214 Timer / NUM_ROUNDS). With
    n_clients=32 this is the north-star configuration (BASELINE.json:
    one client per v4-32 core) anchored on however many chips exist —
    k = 32/devices clients vmapped per device.

    Clients train the pretrained fine-tune configuration, exactly like
    the reference (fed_model.py:140-147 refreezes layers[:15] before the
    model reaches TFF; client optimizer RMSprop(lr/10), fed_model.py:208)
    and like `cli.py::_run_fed` — the frozen backbone's backward is
    DCE'd, same as the dist fine-tune step."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.federated import initialize_server, make_fedavg_round
    from idc_models_tpu.models.vgg import fine_tune_mask, vgg16
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    n_mesh = meshlib.largest_dividing_mesh(n_clients, n_dev)
    per_client = 256 if on_accelerator else 32
    size = 50 if on_accelerator else 10
    model = (vgg16(num_outputs=1) if on_accelerator else
             _small_model())
    mesh = meshlib.client_mesh(n_mesh)
    server = initialize_server(model, jax.random.key(0))
    # the fine-tune mask is the reference-parity workload on EVERY
    # backend (ADVICE r2): VGG gets the Keras-index mask; the CPU smoke
    # model gets the analogous frozen prefix (conv1) so both backends
    # time the same program shape (frozen backward DCE'd)
    mask = (fine_tune_mask(server.params, 15) if on_accelerator else
            {k: jax.tree_util.tree_map(lambda _: k != "conv1", v)
             for k, v in server.params.items()})
    round_fn = make_fedavg_round(model, rmsprop(1e-4, trainable_mask=mask),
                                 binary_cross_entropy, mesh,
                                 local_epochs=1, batch_size=32,
                                 compute_dtype=jnp.bfloat16)
    imgs, labels = synthetic.make_idc_like(n_clients * per_client,
                                           size=size, seed=0)
    imgs = imgs.reshape(n_clients, per_client, size, size, 3)
    labels = labels.reshape(n_clients, per_client)
    # upload client shards ONCE (round-loop inputs live in HBM, not host)
    imgs = jax.device_put(imgs, meshlib.sharding(mesh, meshlib.CLIENT_AXIS))
    labels = jax.device_put(labels,
                            meshlib.sharding(mesh, meshlib.CLIENT_AXIS))
    weights = np.full((n_clients,), per_client, np.float32)

    # >=3 warmup rounds: on the tunneled runtime the first TWO calls of a
    # fresh executable are slow (compile + terminal-side warmup)
    rounds, dt, _, _ = _run_timed(
        lambda sv, sub: round_fn(sv, imgs, labels, weights, sub)[0],
        server, jax.random.key(1), warmup=3,
        min_seconds=1.0 if on_accelerator else 0.2, start_steps=2)
    return dt / rounds


def _small_model():
    from idc_models_tpu.models import small_cnn

    return small_cnn(10, 3, 1)


def bench_federated_robustness(on_accelerator: bool, *, n_clients: int = 10,
                               n_byzantine: int = 3):
    """Byzantine-resilience scenario: final federated eval loss with
    `n_byzantine` of `n_clients` clients running the sign-flip x1000
    attack (faults.py), robust aggregator vs the weighted mean — the
    same identical fault plan for both, so the comparison isolates the
    aggregator. The mean has breakdown point 0 (one attacker steers the
    server arbitrarily); trimmed mean with trim = n_byzantine bounds
    every coordinate inside the honest range. The reported
    `fed_byz_robust_advantage` (mean loss / trimmed loss) is the
    scenario's headline: >> 1 means the robust path is doing its job."""
    import jax

    from idc_models_tpu import faults as faults_lib
    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.data.idc import ArrayDataset
    from idc_models_tpu.data.partition import (
        pad_clients, partition_clients,
    )
    from idc_models_tpu.federated import (
        get_aggregator, initialize_server, make_fedavg_round,
        make_federated_eval,
    )
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    n_mesh = meshlib.largest_dividing_mesh(n_clients, n_dev)
    per_client = 128 if on_accelerator else 16
    size = 50 if on_accelerator else 10
    rounds = 8 if on_accelerator else 3
    model = _small_model()
    mesh = meshlib.client_mesh(n_mesh)
    imgs, labels = synthetic.make_idc_like(n_clients * per_client,
                                           size=size, seed=0)
    ci, cl = partition_clients(ArrayDataset(imgs, labels), n_clients,
                               iid=True, seed=0)
    w = np.full((n_clients,), per_client, np.float32)
    ci, cl, w = pad_clients(ci, cl, w, multiple=n_mesh)
    ci = jax.device_put(ci, meshlib.sharding(mesh, meshlib.CLIENT_AXIS))
    cl = jax.device_put(cl, meshlib.sharding(mesh, meshlib.CLIENT_AXIS))
    plan = faults_lib.FaultPlan.byzantine(
        n_clients, n_byzantine, kind="sign_flip", scale=1000.0, seed=7)
    eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)

    def final_loss(agg):
        server = initialize_server(model, jax.random.key(0))
        rnd = make_fedavg_round(model, rmsprop(1e-3),
                                binary_cross_entropy, mesh,
                                local_epochs=1, batch_size=16,
                                aggregator=agg, faults=plan)
        for r in range(rounds):
            server, _ = rnd(server, ci, cl, w,
                            jax.random.fold_in(jax.random.key(1), r))
        return float(eval_fn(server, ci, cl, w)["loss"])

    mean_loss = final_loss(None)
    trimmed_loss = final_loss(get_aggregator("trimmed_mean",
                                             trim=n_byzantine))
    out = {
        "fed_byz_clients": n_byzantine,
        "fed_byz_total_clients": n_clients,
        "fed_byz_rounds": rounds,
        "fed_byz_mean_eval_loss": round(mean_loss, 4),
        "fed_byz_trimmed_eval_loss": round(trimmed_loss, 4),
        "fed_byz_robust_advantage": round(mean_loss / trimmed_loss, 2),
    }
    out.update(_bench_async_vs_sync_stragglers())
    return out


def _bench_async_vs_sync_stragglers():
    """ISSUE-13 acceptance pair: under one injected straggler plan,
    buffered-async FedAvg strictly beats the synchronous streamed
    round on wall-clock-to-target-loss, the PR 7 round-latency SLO
    alert FIRES in sync mode and stays SILENT in async (both
    asserted). The wall-clock gap is injected-sleep-driven — the sync
    barrier sleeps out each round's max straggler delay while the
    async buffer fills from the fast arrivals — so the comparison is
    valid on the CPU container (no device-overlap claim)."""
    import time

    import jax

    from idc_models_tpu import faults as faults_lib
    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.federated import (
        ClientPopulation, CohortSampler, DriverConfig, initialize_server,
        make_async_round, make_federated_eval, make_population_round,
        run_rounds,
    )
    from idc_models_tpu.observe import SLO, SLOEngine
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    model = _small_model()
    population = ClientPopulation(64, examples_per_client=16,
                                  image_size=10, seed=0)
    cohort, wave, buffer_k, rounds = 8, 8, 4, 6
    mesh = meshlib.client_mesh(1)
    # a quarter of the population straggles at lag 2, 0.5 s per lag
    # unit: every sync round that samples one waits ~1 s at the
    # barrier; the async server just keeps filling buffers — the
    # sleeps, not the (shared) compile cost, drive the wall-clock gap
    plan = faults_lib.PopulationFaultPlan(
        population.size,
        [faults_lib.PopulationFault("straggler", fraction=0.25,
                                    staleness=2)],
        seed=3, delay_unit_s=0.5)
    eval_sampler = CohortSampler(population, 8, seed=999)
    eval_imgs, eval_labels, eval_w = population.materialize(
        eval_sampler.cohort(0))
    eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)

    def slo_engine():
        # p80 of round wall <= 0.35 s with a 20% error budget: the
        # compile-heavy first round fits inside the budget, a straggler
        # WAVE (every round sleeping ~0.5 s) does not — the same shape
        # examples/11_slo_alerts.py drills
        return SLOEngine(
            [SLO.latency("round_seconds", threshold_s=0.35,
                         percentile=80.0)],
            short_window_s=60.0, long_window_s=300.0, min_samples=5)

    def eval_loss(server):
        return float(eval_fn(server, eval_imgs, eval_labels,
                             eval_w)["loss"])

    # --- sync: streamed round with the barrier sleep armed ------------
    sampler = CohortSampler(population, cohort, seed=11)
    sync_round = make_population_round(
        model, rmsprop(1e-3), binary_cross_entropy, mesh, population,
        sampler, wave_size=wave, local_epochs=1, batch_size=16,
        faults=plan, barrier_sleep=True)
    sync_slo = slo_engine()
    server = initialize_server(model, jax.random.key(0))
    server = jax.device_put(server, meshlib.replicated(mesh))
    t0 = time.monotonic()
    res = run_rounds(sync_round, server, None, None,
                     np.ones((cohort,), np.float32),
                     config=DriverConfig(rounds=rounds), seed=1,
                     slo=sync_slo)
    sync_wall = time.monotonic() - t0
    target_loss = eval_loss(res.server)
    sync_alerts = [a for a in sync_slo.alerts
                   if a["slo"] == "round_seconds"]
    assert sync_alerts, (
        "the straggler barrier must trip the round-latency SLO in "
        "sync mode (rounds: "
        f"{[e['seconds'] for e in res.events]})")

    # --- async: buffered server, same plan, run to the sync loss ------
    async_round = make_async_round(
        model, rmsprop(1e-3), binary_cross_entropy, population,
        CohortSampler(population, cohort, seed=11),
        buffer_size=buffer_k, staleness_decay=0.9, local_epochs=1,
        batch_size=16, faults=plan, base_latency_s=(0.005, 0.02),
        realtime=True, seed=1)
    async_slo = slo_engine()
    server = initialize_server(model, jax.random.key(0))
    t0 = time.monotonic()
    async_rounds = 0
    staleness = []
    while True:
        res = run_rounds(async_round, server, None, None,
                         np.ones((cohort,), np.float32),
                         config=DriverConfig(rounds=async_rounds + 1),
                         seed=1, slo=async_slo)
        server = res.server
        async_rounds += 1
        staleness.append(res.history[-1].get("staleness_mean", 0.0))
        if eval_loss(server) <= target_loss or async_rounds >= 4 * rounds:
            break
    async_wall = time.monotonic() - t0
    async_loss = eval_loss(server)
    assert not async_slo.alerts, (
        f"async mode must absorb the stragglers without burning the "
        f"round-latency budget, got alerts: {async_slo.alerts}")
    assert async_loss <= target_loss, (
        f"async never reached the sync target loss ({async_loss} > "
        f"{target_loss} after {async_rounds} rounds)")
    assert async_wall < sync_wall, (
        f"async must strictly beat sync wall-clock-to-target-loss, "
        f"got async {async_wall:.2f}s vs sync {sync_wall:.2f}s")
    return {
        "fed_sync_wall_to_loss_s": round(sync_wall, 3),
        "fed_async_wall_to_loss_s": round(async_wall, 3),
        "fed_async_speedup": round(sync_wall / async_wall, 2),
        "fed_async_rounds_to_loss": async_rounds,
        "fed_sync_slo_alerts": len(sync_alerts),
        "fed_async_slo_alerts": len(async_slo.alerts),
        "fed_async_staleness_mean": round(
            float(np.mean(staleness)), 3),
    }


def _rss_mb() -> float:
    """Current (not peak) resident set, MB, from /proc/self/status."""
    for line in open("/proc/self/status"):
        if line.startswith("VmRSS:"):
            return float(line.split()[1]) / 1024.0
    return float("nan")


def _peak_rss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def bench_federated_scale(on_accelerator: bool):
    """ISSUE-13 acceptance: a 10k-virtual-client population with a
    256-client sampled cohort trains in memory bounded by the WAVE,
    independent of the population size. Methodology: run the identical
    cohort/wave configuration at a 1k and then a 10k population; the
    10k run's PEAK-RSS growth over the already-established 1k peak is
    asserted under a small fixed bound (a population-sized allocation
    of even one float per client per shard example would blow it), and
    per-round RSS deltas are reported for both. A sampled round also
    replays bit-identically from (seed, round) across two fresh
    builds — the tree-wide drill contract."""
    import jax

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.federated import (
        ClientPopulation, CohortSampler, initialize_server,
        make_population_round,
    )
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    model = _small_model()
    cohort, wave = 256, 32
    n_dev = len(jax.devices())
    mesh = meshlib.client_mesh(meshlib.largest_dividing_mesh(wave,
                                                             n_dev))

    def build_round(n_population):
        population = ClientPopulation(
            n_population, examples_per_client=16, image_size=10,
            seed=0)
        sampler = CohortSampler(population, cohort, seed=0)
        return make_population_round(
            model, rmsprop(1e-3), binary_cross_entropy, mesh,
            population, sampler, wave_size=wave, local_epochs=1,
            batch_size=16)

    def run(rnd, seed_round=0):
        server = initialize_server(model, jax.random.key(0))
        server = jax.device_put(server, meshlib.replicated(mesh))
        rss0 = _rss_mb()
        t0 = time.perf_counter()
        server, metrics = rnd(server, None, None, None,
                              jax.random.key(1), round_idx=seed_round)
        jax.block_until_ready(server.params)
        return server, metrics, time.perf_counter() - t0, \
            _rss_mb() - rss0

    rnd_1k, rnd_10k = build_round(1_000), build_round(10_000)
    run(rnd_1k)                                  # cold: pays compiles
    _, metrics, dt_10k, _ = run(rnd_10k)
    assert int(metrics["participants"]) == cohort

    # bit-identical replay from (seed, round): a fresh build of the
    # same population/sampler/round replays the sampled round exactly
    s_a, _, _, _ = run(build_round(10_000), seed_round=3)
    s_b, _, _, _ = run(build_round(10_000), seed_round=3)
    for a, b in zip(jax.tree.leaves(jax.device_get(s_a.params)),
                    jax.tree.leaves(jax.device_get(s_b.params))):
        np.testing.assert_array_equal(a, b)

    # the O(wave) memory gate, in a form that holds BOTH standalone and
    # inside a full bench run (where the process peak is pre-saturated
    # by earlier benchmarks): with every compile paid above, WARM
    # rounds at 1k and 10k must (a) not move the process PEAK at all
    # beyond wave-transient noise and (b) show near-equal per-round
    # RSS deltas — a population-sized shard materialization alone
    # would be ~190 MB at 10k
    peak_before_warm = _peak_rss_mb()
    _, _, dt_1k_warm, rss_1k = run(rnd_1k, seed_round=5)
    _, _, dt_warm, rss_10k = run(rnd_10k, seed_round=5)
    peak_growth = _peak_rss_mb() - peak_before_warm
    assert peak_growth < 64.0, (
        f"warm 1k+10k rounds grew the process peak RSS by "
        f"{peak_growth:.1f} MB — population-sized state is leaking "
        f"into the round (the contract is O(wave) memory, independent "
        f"of population)")
    assert rss_10k < max(2.0 * abs(rss_1k), 32.0), (
        f"a warm 10k-population round grew RSS by {rss_10k:.1f} MB vs "
        f"{rss_1k:.1f} MB at 1k — the per-round footprint must be "
        f"O(wave), independent of the population")

    return {
        "fed_scale_population": 10_000,
        "fed_scale_cohort": cohort,
        "fed_scale_wave": wave,
        "fed_scale_round_s": round(dt_warm, 3),
        "fed_scale_round_s_cold": round(dt_10k, 3),
        "fed_scale_round_s_1k": round(dt_1k_warm, 3),
        "fed_scale_rss_delta_mb_1k": round(rss_1k, 1),
        "fed_scale_rss_delta_mb_10k": round(rss_10k, 1),
        "fed_scale_peak_growth_mb": round(peak_growth, 1),
        "fed_scale_replay_bitwise": 1.0,
    }


def bench_secure_round(on_accelerator: bool):
    """Secure-aggregation round wall-clock at the reference's scale: 8
    small-CNN clients (secure_fed_model.py:41), pairwise-masked
    aggregation (secure_fed_model.py:223-236 per round), k clients per
    device over however many chips exist."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.data import synthetic
    from idc_models_tpu.federated import initialize_server
    from idc_models_tpu.secure import make_secure_fedavg_round
    from idc_models_tpu.train import rmsprop
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    n_clients = 8  # secure_fed_model.py:41 NUM_CLIENTS
    n_mesh = meshlib.largest_dividing_mesh(n_clients, n_dev)
    per_client = 512 if on_accelerator else 32
    model = _small_model()
    mesh = meshlib.client_mesh(n_mesh)
    server = initialize_server(model, jax.random.key(0))
    round_fn = make_secure_fedavg_round(
        model, rmsprop(1e-3), binary_cross_entropy, mesh, percent=0.5,
        local_epochs=5, batch_size=32)
    imgs, labels = synthetic.make_idc_like(n_clients * per_client, size=10,
                                           seed=0)
    imgs = imgs.reshape(n_clients, per_client, 10, 10, 3)
    labels = labels.reshape(n_clients, per_client)
    imgs = jax.device_put(imgs, meshlib.sharding(mesh, meshlib.CLIENT_AXIS))
    labels = jax.device_put(labels,
                            meshlib.sharding(mesh, meshlib.CLIENT_AXIS))

    rounds, dt, _, _ = _run_timed(
        lambda sv, sub: round_fn(sv, imgs, labels, sub)[0],
        server, jax.random.key(1), warmup=3,
        min_seconds=1.0 if on_accelerator else 0.2, start_steps=2)
    return dt / rounds


def bench_ring_attention(on_accelerator: bool):
    """Sequence-parallel evidence in the official record: forward ring
    attention at a long local block (causal bf16 B=1 H=8 D=64, ring of
    1 so t_local == T), fused pallas blocks vs the jnp path — the
    BENCH-file version of experiments/ring_attention_bench.py's
    amortized measurement (6 chained calls, best of 2 windows)."""
    import time

    import jax.numpy as jnp
    import numpy as np

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.ring_attention import make_ring_attention

    import statistics

    t = 16384 if on_accelerator else 512
    iters = 6 if on_accelerator else 2
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (1, t, 8, 64)), jnp.bfloat16)
               for _ in range(3))
    mesh = meshlib.seq_mesh(1)
    times, medians = {}, {}
    for impl in ("pallas", "jnp"):
        fn = make_ring_attention(mesh, causal=True, block_impl=impl)
        o = fn(q, k, v)
        _ = float(jnp.sum(o.astype(jnp.float32)))
        windows = []
        for _ in range(3):
            t0 = time.perf_counter()
            o = q
            for _ in range(iters):
                o = fn(o, k, v).astype(jnp.bfloat16)
            _ = float(jnp.sum(o.astype(jnp.float32)))
            windows.append((time.perf_counter() - t0) / iters)
        times[impl] = min(windows)
        medians[impl] = statistics.median(windows)
    # best AND median speedup: the shared chip's ±10% drift is the
    # difference between the 1.44x and 1.62x historical quotes — the
    # bracket makes an excursion distinguishable from a regression
    return {"ring_fwd_t": t,
            "ring_fwd_pallas_ms": round(times["pallas"] * 1e3, 2),
            "ring_fwd_speedup_vs_jnp":
                round(times["jnp"] / times["pallas"], 3),
            "ring_fwd_speedup_median":
                round(medians["jnp"] / medians["pallas"], 3)}


def bench_lm_decode(on_accelerator: bool):
    """The compiled serving path (models/lm.py Generator): ring prefill
    over a 16k-token prompt + the fused scan decode loop — one device
    dispatch per decode WINDOW, not per token, so the ~4 ms tunneled
    dispatch cost is amortized over the window and per-token cost
    approaches the 0.15-0.35 ms device floor the decode-op bench
    measured (experiments/decode_bench.jsonl). Reports `prefill_ms`
    (prompt 16k, pallas ring blocks) and `decode_ms_per_token` /
    `decode_tokens_per_sec` (greedy, bf16 cache). Off-accelerator runs
    a smoke-scale config so the record always carries the fields."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import Generator, attention_lm

    if on_accelerator:
        t_max, p_len, n_dec = 32768, 16384, 256
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        impl = "pallas"      # 16k local block: jnp would materialize
        #                      [B, H, 16k, 16k] f32 scores and OOM
    else:
        t_max, p_len, n_dec = 64, 32, 16
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        impl = "jnp"
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    gen = Generator(params, embed_dim=e, num_heads=heads,
                    num_blocks=blocks, t_max=t_max, mesh=mesh,
                    block_impl=impl)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, vocab, (1, p_len)), jnp.int32)

    # compile + warm both programs (first TWO calls of a fresh
    # executable are slow on the tunneled runtime, see module docstring)
    logits, caches = gen.prefill(prompt)
    _ = float(jnp.sum(logits.astype(jnp.float32)))
    toks, logits, caches = gen.decode(caches, logits, p_len, n_dec)
    _ = int(np.asarray(toks)[0, -1])

    pf_windows = []
    for _i in range(3):
        t0 = time.perf_counter()
        logits, caches = gen.prefill(prompt)
        # a host fetch that data-depends on the result is the only
        # trustworthy fence on this runtime (module docstring)
        _ = float(jnp.sum(logits.astype(jnp.float32)))
        pf_windows.append(time.perf_counter() - t0)

    # decode windows CHAIN through the returned (logits, caches), so
    # every window measures appends into a progressively fuller cache —
    # the honest serving pattern, not a fresh-cache best case
    pos, dec_windows = p_len, []
    while pos + n_dec <= t_max and len(dec_windows) < 4:
        t0 = time.perf_counter()
        toks, logits, caches = gen.decode(caches, logits, pos, n_dec)
        _ = int(np.asarray(toks)[0, -1])
        dec_windows.append(time.perf_counter() - t0)
        pos += n_dec
    best = min(dec_windows)
    return {"prefill_t": p_len,
            "prefill_ms": round(min(pf_windows) * 1e3, 2),
            "decode_window_tokens": n_dec,
            "decode_ms_per_token": round(best / n_dec * 1e3, 4),
            "decode_tokens_per_sec": round(n_dec / best, 1)}


def bench_lm_sharded(on_accelerator: bool):
    """ISSUE 15: rule-based GSPMD sharding (partition.py) — CAPACITY
    keys, per the CPU-container measurement policy (multi-device
    wall-clock scaling is not measurable on 2-core virtual devices;
    per-device memory footprint is).

    One LM train-step config accounted three ways — replicated,
    FSDP (params + optimizer moments over "data"), and TP (Megatron
    orientation over "model", registry rule set 'lm') — reporting each
    layout's per-device `peak_hbm_bytes` from XLA program accounting
    (memory_analysis is per-device: a sharded program's argument
    buffers are the shards) plus the sharded step times for the
    regression trail. Headline: the hbm ratios sharded/replicated,
    strictly < 1 when the rules actually shard (the ROADMAP item 2
    capacity gate, also asserted in tests/test_partition.py). With
    fewer than 2 devices only the replicated account is recorded."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.observe import profile as prof
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train.step import place_state

    if on_accelerator:
        vocab, e, mlp, heads, blocks, seq_len, batch = (
            8192, 1024, 4096, 8, 4, 512, 8)
    else:
        vocab, e, mlp, heads, blocks, seq_len, batch = (
            512, 128, 512, 4, 2, 64, 4)
    rng = np.random.default_rng(0)
    seqs = (rng.integers(0, vocab, (batch, 1))
            + np.arange(seq_len)) % vocab

    def account(mesh, rules, tag):
        model = attention_lm(vocab, seq_len, embed_dim=e,
                             num_heads=heads, mlp_dim=mlp,
                             num_blocks=blocks, mesh=mesh)
        opt = rmsprop(3e-3)
        v = model.init(jax.random.key(0))
        state = TrainState(step=jnp.zeros((), jnp.int32),
                           params=v.params, model_state=v.state,
                           opt_state=opt.init(v.params))
        step = jit_data_parallel(
            make_train_step(model, opt, next_token_loss), mesh,
            axis=meshlib.DATA_AXIS,
            state_shardings=(rules.shardings(mesh, state)
                             if rules is not None else None))
        state = place_state(mesh, state, rules=rules)
        x = shard_batch(mesh, jnp.asarray(seqs, jnp.int32),
                        axis=meshlib.DATA_AXIS)
        key = jax.random.key(1)
        compiled = step.lower(state, x, x, key).compile()
        cost = prof.program_report(compiled, name=f"lm_sharded.{tag}")
        windows = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _i in range(2):
                key, sub = jax.random.split(key)
                state, m = compiled(state, x, x, sub)
            _ = float(m["loss"])             # the fence
            windows.append((time.perf_counter() - t0) / 2)
        return cost.peak_hbm_bytes, min(windows)

    rules = registry.get_partition_rules("lm")
    rep_hbm, rep_s = account(meshlib.fsdp_tp_mesh(1, 1, 1), None,
                             "replicated")
    out = {"lm_sharded_peak_hbm_replicated_mb":
           round(rep_hbm / 2**20, 3) if rep_hbm else None}
    if len(jax.devices()) < 2 or not rep_hbm:
        return out
    fsdp_hbm, fsdp_s = account(meshlib.fsdp_tp_mesh(2, 1, 1), rules,
                               "fsdp")
    tp_hbm, tp_s = account(meshlib.fsdp_tp_mesh(1, 2, 1), rules, "tp")
    out.update({
        "lm_sharded_peak_hbm_fsdp_mb": round(fsdp_hbm / 2**20, 3),
        "lm_sharded_peak_hbm_tp_mb": round(tp_hbm / 2**20, 3),
        "lm_sharded_hbm_ratio_fsdp": round(fsdp_hbm / rep_hbm, 4),
        "lm_sharded_hbm_ratio_tp": round(tp_hbm / rep_hbm, 4),
        "lm_sharded_step_ms_fsdp": round(fsdp_s * 1e3, 3),
        "lm_sharded_step_ms_tp": round(tp_s * 1e3, 3),
    })
    return out


def bench_serving(on_accelerator: bool):
    """The continuous-batching engine (serve/) vs the serial PR-1
    `Generator` on the SAME trace — the serving scenario record.

    The scenario is EOS-terminated GOODPUT, the thing a multi-user
    server is judged on: every request carries a stop token (probed as
    the deepest-first-appearing token of a greedy stream, so stops land
    mid-budget) and a budget near t_max. The engine's masked windows
    retire a slot the step its EOS lands and recycle it into the next
    queued request; the serial fused scan CANNOT early-exit — it decodes
    every request's full budget and throws the post-EOS tail away. Both
    paths produce bit-identical useful tokens (engine parity is gated
    by test), both replay the trace in arrival order as a burst, both
    are timed warm (compilation in a discarded first pass), and both
    end with host fetches that data-depend on the emitted tokens
    (module docstring: the only trustworthy fence). Three interleaved
    pairs, best window each — `serve_tokens_per_sec` must be >= the
    serial baseline."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import Generator, attention_lm
    from idc_models_tpu.serve import LMServer, poisson_trace

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 2048, 8, 64, 16
        prompt_lens, budgets = (64, 256), (1200, 1500)
    else:
        # CPU smoke note: a serial CPU has no idle batch lanes for
        # continuous batching to fill, so the structural win here is
        # EOS-recycling alone and the margin is thin — on the
        # accelerator the batch rows are near-free and the gap is the
        # real story
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_slots, window, n_req = 128, 8, 8, 48
        prompt_lens, budgets = (4, 12), (110, 116)
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16)

    # probe a greedy stream for the token whose FIRST appearance is
    # deepest: as the scenario's EOS it stops most requests mid-budget
    gen = Generator(params, **kw)
    probe = gen(jnp.asarray([[1, 2, 3]], jnp.int32),
                min(t_max // 3, 256)).tolist()[0][3:]
    first: dict[int, int] = {}
    for i, t in enumerate(probe):
        first.setdefault(t, i)
    eos = max(first, key=first.get)

    trace = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                          t_max=t_max, prompt_lens=prompt_lens,
                          budgets=budgets, seed=0, eos_id=eos)

    def engine_pass():
        server = LMServer(params, n_slots=n_slots, window=window,
                          max_prefills_per_cycle=n_slots, eos_id=eos,
                          **kw)
        t0 = time.perf_counter()
        results = server.run(trace)
        useful = sum(len(r.tokens) for r in results)        # fence
        assert useful
        return time.perf_counter() - t0, useful, server.summary()

    def serial_pass():
        g = Generator(params, **kw)
        t0 = time.perf_counter()
        useful = 0
        for _, req in trace:
            out = g(jnp.asarray([req.prompt], jnp.int32),
                    req.max_new_tokens)
            stream = out.tolist()[0][len(req.prompt):]      # fence
            useful += (stream.index(eos) + 1 if eos in stream
                       else len(stream))
        return time.perf_counter() - t0, useful

    engine_pass()                                    # compile both paths
    serial_pass()
    eng, ser, ratios, summary = [], [], [], None
    for _ in range(3):                               # interleaved pairs
        dt_e, tok_e, summary = engine_pass()
        dt_s, tok_s = serial_pass()
        assert tok_e == tok_s, (tok_e, tok_s)        # same useful output
        eng.append(tok_e / dt_e)
        ser.append(tok_s / dt_s)
        # the chip/host load drifts on the minutes scale (±10-40%
        # observed); a PAIRED ratio cancels most of it, best-of pairs
        # is the honest structural comparison (same discipline as
        # _run_timed's best-of-4)
        ratios.append((tok_e / dt_e) / (tok_s / dt_s))
    return {
        "serve_trace_requests": n_req,
        "serve_slots": n_slots,
        "serve_window": window,
        "serve_eos_id": eos,
        "serve_tokens": summary["serve_tokens"],
        "serve_tokens_per_sec": round(max(eng), 1),
        "serve_tokens_per_sec_windows": [round(x, 1) for x in eng],
        "serve_ttft_ms_p50": summary["serve_ttft_ms_p50"],
        "serve_ttft_ms_p95": summary["serve_ttft_ms_p95"],
        "serve_slot_occupancy": summary["serve_slot_occupancy"],
        "serial_tokens_per_sec": round(max(ser), 1),
        "serve_speedup_vs_serial": round(max(ratios), 3),
        "serve_speedup_windows": [round(r, 3) for r in ratios],
    }


def bench_serving_shared_prefix(on_accelerator: bool):
    """Chunked prefill + radix prefix cache vs monolithic admission on
    SHARED-PREFIX traffic — the scenario the prefix cache exists for.

    N requests arrive over K distinct system prompts (long shared
    prefix, short unique tail) mixed with long-prompt stragglers. The
    treated server admits prompts one CHUNK per decode window and reuses
    chunk-boundary KV snapshots across requests sharing a prefix; the
    baseline runs the historical one-dispatch-per-prompt admission. Both
    emit bit-identical greedy tokens (asserted — the comparison is pure
    scheduling). Reported: the prefix hit rate, both TTFT p95s, and the
    per-cycle decode stall (host time between windows spent on
    admission/prefill — the thing a monolithic 16k-token prefill
    inflates and chunking bounds). Interleaved pairs, best-of, same
    discipline as bench_serving. Plus the int8-KV capacity ratio:
    ring-cache bytes per slot bf16 vs int8 at identical config — slots
    per HBM byte is the reciprocal."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import LMServer, Request, SlotEngine

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 2048, 8, 32
        chunk, sys_len, n_req, k_prefix = 256, 1792, 24, 4
        tail_lens, budgets = (8, 32), (16, 48)
    else:
        # long prompts relative to the model so prefill COMPUTE (not
        # dispatch overhead) is what the prefix cache removes — the
        # regime the feature targets; tiny prompts make monolithic
        # admission win on dispatch count alone
        vocab, e, heads, blocks, mlp = 32, 64, 2, 2, 128
        t_max, n_slots, window = 256, 4, 8
        chunk, sys_len, n_req, k_prefix = 32, 224, 16, 4
        tail_lens, budgets = (3, 8), (6, 12)
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16)

    rng = np.random.default_rng(7)
    prefixes = [tuple(int(x) for x in rng.integers(0, vocab, sys_len))
                for _ in range(k_prefix)]

    def mk_trace(tag, n):
        tr = []
        for i in range(n):
            tail = tuple(int(x) for x in rng.integers(
                0, vocab, int(rng.integers(*tail_lens))))
            tr.append((0.0, Request(
                id=f"{tag}{i}", prompt=prefixes[i % k_prefix] + tail,
                max_new_tokens=int(rng.integers(budgets[0],
                                                budgets[1])))))
        return tr

    warm_trace = mk_trace("warm", k_prefix)
    trace = mk_trace("r", n_req)

    def run_pass(chunked: bool):
        from idc_models_tpu.serve import ServingMetrics

        server = LMServer(
            params, n_slots=n_slots, window=window,
            max_prefills_per_cycle=4,
            prefill_chunk=chunk if chunked else None,
            prefix_cache_mb=256.0 if chunked else 0.0, **kw)
        if chunked:
            # steady-state measurement: one request per prefix warms
            # the radix cache, then the metrics (serving AND prefix
            # counters) reset so the reported summary covers ONLY the
            # timed trace — without the reset, the cold warm-trace
            # requests dominate the p95s this scenario exists to
            # compare (cold misses are a once-per-prefix transient,
            # not the steady state)
            server.run(warm_trace)
            pc = server.engine.prefix_cache
            pc.hits = pc.misses = pc.evictions = 0
            pc.hit_tokens = pc.lookup_tokens = 0
            server.metrics = ServingMetrics(prefix_cache=pc)
            server.scheduler.metrics = server.metrics
        results = server.run(trace)
        toks = {r.id: tuple(r.tokens)
                for r in results if r.id.startswith("r")}  # fence
        return toks, server.summary()

    run_pass(True)                                   # compile both paths
    run_pass(False)
    best_c, best_m = None, None
    for _ in range(2):                               # interleaved pairs
        tok_c, sum_c = run_pass(True)
        tok_m, sum_m = run_pass(False)
        assert tok_c == tok_m                        # pure scheduling
        if (best_c is None
                or sum_c["serve_ttft_ms_p95"] < best_c["serve_ttft_ms_p95"]):
            best_c = sum_c
        if (best_m is None
                or sum_m["serve_ttft_ms_p95"] < best_m["serve_ttft_ms_p95"]):
            best_m = sum_m

    # int8 capacity at identical config: bytes of ring-cache state per
    # slot (+ scales) — the denominator of slots-per-HBM-budget
    eng16 = SlotEngine(params, n_slots=2, **kw)
    eng8 = SlotEngine(params, n_slots=2, kv_dtype="int8", **kw)
    ratio = eng16.kv_bytes_per_slot() / eng8.kv_bytes_per_slot()

    return {
        "serve_prefix_requests": n_req,
        "serve_prefix_distinct_prefixes": k_prefix,
        "serve_prefix_hit_rate": best_c["serve_prefix_hit_rate"],
        "serve_prefix_token_hit_rate": best_c["serve_prefix_token_hit_rate"],
        "serve_ttft_ms_p95_shared_prefix": best_c["serve_ttft_ms_p95"],
        "serve_ttft_ms_p95_shared_prefix_monolithic":
            best_m["serve_ttft_ms_p95"],
        "serve_chunked_prefill_decode_stall_ms":
            best_c["serve_prefill_stall_ms_mean"],
        "serve_monolithic_prefill_decode_stall_ms":
            best_m["serve_prefill_stall_ms_mean"],
        "serve_chunked_prefill_decode_stall_ms_max":
            best_c["serve_prefill_stall_ms_max"],
        "serve_monolithic_prefill_decode_stall_ms_max":
            best_m["serve_prefill_stall_ms_max"],
        "serve_int8_kv_slot_capacity_ratio": round(ratio, 3),
    }


def bench_serving_speculative(on_accelerator: bool):
    """Speculative decoding (draft-and-verify, ISSUE 10) vs plain fused
    windows on REPETITIVE/TEMPLATED traffic — the regime prompt-lookup
    drafting exists for.

    The model is briefly trained on the counting task (next = (tok+1)
    % vocab — the same template `cli serve --train-steps` demos) and
    every prompt is a counting run LONGER than the vocab, so the
    stream's trailing n-gram always recurs earlier: the n-gram drafter
    proposes the counting continuation and the trained model's greedy
    decode confirms it. Both servers emit the SAME tokens (asserted —
    the comparison is pure scheduling): spec-off decodes one token per
    fused-scan step, spec-on verifies k drafts + its own correction in
    ONE chunk-query dispatch, reading the KV cache once instead of k
    times. Interleaved pairs, best-of, the bench_serving discipline.

    The CPU smoke ASSERTS the two machine-noise-proof proxies — accept
    rate >= 0.5 and per-slot tokens-per-dispatch > 1.5 (each verify
    advances a slot past what a one-token step could) — and records
    the wall-clock speedup; on the accelerator the >= 1.5x decode
    tokens/sec gate is the headline.

    `_bench_spec_nonrepetitive` appends the other half of the story:
    the NON-repetitive trace where prompt lookup is inert and only
    the distilled draft LM wins (serve_spec_nonrep_* keys)."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.serve import LMServer, Request
    from idc_models_tpu.train import TrainState, make_train_step, rmsprop

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 64, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 2048, 8, 32, 16
        draft_k, order, train_steps = 16, 2, 300
        budgets = (900, 1200)
    else:
        # the cache is deliberately DEEP relative to the model: each
        # fused-window step re-reads the whole [S, t_max] KV cache for
        # one token, the verify reads it once for k — the deeper the
        # cache, the more of decode's cost that k-fold read saving
        # covers (t_max 128 measures ~1.2x here, 256 ~1.8x)
        vocab, e, heads, blocks, mlp = 16, 32, 2, 2, 64
        t_max, n_slots, window, n_req = 256, 4, 8, 8
        draft_k, order, train_steps = 16, 2, 300
        budgets = (150, 180)
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    opt = rmsprop(3e-3)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       model_state={}, opt_state=opt.init(params))
    step = jax.jit(make_train_step(model, opt, next_token_loss))
    rng = np.random.default_rng(3)
    key = jax.random.key(4)
    batch = 8 if not on_accelerator else 16
    for _ in range(train_steps):
        starts = rng.integers(0, vocab, (batch, 1))
        seqs = jnp.asarray((starts + np.arange(t_max)) % vocab,
                           jnp.int32)
        key, sub = jax.random.split(key)
        state, _ = step(state, seqs, seqs, sub)
    params = jax.device_get(state.params)

    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16)
    # counting prompts longer than the vocab: every trailing n-gram
    # has an earlier occurrence, so the drafter ALWAYS proposes (the
    # templated-traffic best case the accept-rate gate scores)
    trace = []
    for i in range(n_req):
        p_len = int(rng.integers(vocab + 4, min(vocab * 2, t_max // 2)))
        start = int(rng.integers(0, vocab))
        prompt = tuple((start + j) % vocab for j in range(p_len))
        budget = int(rng.integers(budgets[0], budgets[1]))
        budget = min(budget, t_max - p_len - 1)
        trace.append((0.0, Request(id=f"s{i}", prompt=prompt,
                                   max_new_tokens=budget)))
    assert all(len(r.prompt) > vocab for _, r in trace)

    def run_pass(spec: bool):
        server = LMServer(params, n_slots=n_slots, window=window,
                          max_prefills_per_cycle=n_slots,
                          spec_decode=spec, draft_k=draft_k,
                          draft_order=order, **kw)
        t0 = time.perf_counter()
        results = server.run(trace)
        toks = {r.id: tuple(r.tokens) for r in results}       # fence
        dt = time.perf_counter() - t0
        n_tok = sum(len(t) for t in toks.values())
        return dt, n_tok, toks, server.summary()

    run_pass(True)                                   # compile both paths
    run_pass(False)
    spec_tps, base_tps, ratios = [], [], []
    summary = base_summary = None
    for _ in range(3):                               # interleaved pairs
        dt_s, tok_s, out_s, summary = run_pass(True)
        dt_b, tok_b, out_b, base_summary = run_pass(False)
        assert out_s == out_b                        # pure scheduling
        spec_tps.append(tok_s / dt_s)
        base_tps.append(tok_b / dt_b)
        ratios.append((tok_s / dt_s) / (tok_b / dt_b))
    accept = summary["serve_spec_accept_rate"]
    tpd = summary["serve_spec_tokens_per_dispatch"]
    if not on_accelerator:
        # the machine-noise-proof proxies (wall-clock ratios drift
        # +/- 40% with the shared box's load; these are structural)
        assert accept is not None and accept >= 0.5, accept
        assert tpd is not None and tpd > 1.5, tpd
    rep = {
        "serve_spec_requests": n_req,
        "serve_spec_draft_k": draft_k,
        "serve_spec_tokens": summary["serve_tokens"],
        "serve_spec_tokens_per_sec": round(max(spec_tps), 1),
        "serve_spec_baseline_tokens_per_sec": round(max(base_tps), 1),
        "serve_spec_speedup": round(max(ratios), 3),
        "serve_spec_speedup_windows": [round(r, 3) for r in ratios],
        "serve_spec_accept_rate": accept,
        "serve_spec_tokens_per_dispatch": tpd,
        "serve_spec_verify_dispatches":
            summary["serve_spec_verify_dispatches"],
        # the SHARED tokens-per-dispatch definition on both sides
        # (serve/metrics.py): emitted tokens over decode dispatches —
        # the apples-to-apples batch-level figure next to the
        # per-slot serve_spec_tokens_per_dispatch above
        "serve_tokens_per_dispatch_spec":
            summary["serve_tokens_per_dispatch"],
        "serve_tokens_per_dispatch_nospec":
            base_summary["serve_tokens_per_dispatch"],
    }
    rep.update(_bench_spec_nonrepetitive(on_accelerator, mesh))
    return rep


def _bench_spec_nonrepetitive(on_accelerator: bool, mesh):
    """The NON-REPETITIVE half of the speculative bench: traffic where
    prompt-lookup drafting is structurally inert and only a learned
    drafter (models/draft_lm, distilled from the target) can win.

    The task is a full-period LCG: next = (5*tok + 3) % vocab. Full
    period means a stream shorter than the vocab NEVER repeats a
    token, so no trailing n-gram — down to order 1 — recurs and the
    NGramDrafter proposes ~nothing (measured and ASSERTED). The
    learned drafter is distilled against the target's own greedy
    streams (KL on the teacher's logits, through train/loop.fit),
    round-tripped through save_draft_lm/load_draft_lm, and proposes
    for every running slot in ONE batched device dispatch per cycle.

    Three interleaved passes — spec-off / n-gram / learned — emit
    bit-IDENTICAL tokens (asserted: a drafter changes scheduling,
    never content). The CPU smoke asserts the structural claims
    (learned accept rate > 0 where the n-gram drafted ~0); the
    tokens/sec speedup is the accelerator-stated headline. The draft
    overhead key states what speculation PAYS: seconds spent in
    propose (host + the batched dispatch) as a percent of the learned
    pass's end-to-end serve wall time."""
    import tempfile
    import types

    import jax
    import jax.numpy as jnp

    from idc_models_tpu.models.draft_lm import (
        DraftLM, distill_draft_lm, draft_config, greedy_streams,
        load_draft_lm, save_draft_lm,
    )
    from idc_models_tpu.models.lm import attention_lm, next_token_loss
    from idc_models_tpu.serve import LMServer, Request
    from idc_models_tpu.train import TrainState, make_train_step, rmsprop

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 4096, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 1024, 8, 32, 16
        draft_k, train_steps, batch = 8, 400, 16
        n_streams, epochs = 24, 12
        budgets = (600, 900)
    else:
        vocab, e, heads, blocks, mlp = 64, 32, 2, 2, 64
        t_max, n_slots, window, n_req = 64, 4, 8, 6
        draft_k, train_steps, batch = 4, 300, 8
        n_streams, epochs = 32, 20
        budgets = (30, 44)

    def lcg_orbit(starts, length):
        seq = np.empty((len(starts), length), np.int64)
        seq[:, 0] = starts
        for t in range(1, length):
            seq[:, t] = (5 * seq[:, t - 1] + 3) % vocab
        return seq

    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(7)).params
    opt = rmsprop(3e-3)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       model_state={}, opt_state=opt.init(params))
    step = jax.jit(make_train_step(model, opt, next_token_loss))
    rng = np.random.default_rng(11)
    key = jax.random.key(12)
    for _ in range(train_steps):
        seqs = jnp.asarray(lcg_orbit(rng.integers(0, vocab, batch),
                                     t_max), jnp.int32)
        key, sub = jax.random.split(key)
        state, _ = step(state, seqs, seqs, sub)
    params = jax.device_get(state.params)
    variables = types.SimpleNamespace(params=params, state={})

    # distill the student on the TARGET'S OWN greedy streams (the
    # serve-time stream distribution), then round-trip it through the
    # sharded-checkpoint path — the same artifact `cli serve
    # --drafter learned --draft-ckpt DIR` restores
    dcfg = draft_config(vocab, t_max)
    # the teacher forward is fixed-length (the position table), so
    # the distillation streams span exactly t_max tokens
    prompts = lcg_orbit(rng.integers(0, vocab, n_streams), 4)
    streams = greedy_streams(model, variables, prompts, t_max)
    # distillation runs through train/loop.fit, whose input pipeline
    # shards batches over a DATA mesh; serving stays on `mesh`
    from idc_models_tpu import mesh as meshlib

    _, dstate, _ = distill_draft_lm(
        model, variables, streams, config=dcfg,
        mesh=meshlib.data_seq_mesh(1, 1), epochs=epochs, batch_size=8,
        lr=1e-2, seed=13)
    with tempfile.TemporaryDirectory() as tmp:
        save_draft_lm(tmp, jax.device_get(dstate.params),
                      config=dcfg).wait()
        dparams, dcfg = load_draft_lm(tmp, mesh=mesh)
    learned = DraftLM(draft_k, dparams, dcfg)

    # fresh-text prompts: every request is one LCG run shorter than
    # the vocab's full period, so its stream never repeats a token
    # and NO trailing n-gram recurs — the prompt-lookup worst case
    trace = []
    for i in range(n_req):
        p_len = int(rng.integers(6, 12))
        budget = min(int(rng.integers(budgets[0], budgets[1])),
                     t_max - p_len - 1, vocab - p_len - 1)
        prompt = tuple(int(t) for t in
                       lcg_orbit([int(rng.integers(0, vocab))],
                                 p_len)[0])
        trace.append((0.0, Request(id=f"n{i}", prompt=prompt,
                                   max_new_tokens=budget)))

    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16,
              max_prefills_per_cycle=n_slots, n_slots=n_slots,
              window=window)

    def run_pass(mode: str):
        server = LMServer(params, spec_decode=(mode != "off"),
                          draft_k=draft_k,
                          drafter=(learned if mode == "learned"
                                   else None), **kw)
        t0 = time.perf_counter()
        results = server.run(trace)
        toks = {r.id: tuple(r.tokens) for r in results}       # fence
        dt = time.perf_counter() - t0
        n_tok = sum(len(t) for t in toks.values())
        return dt, n_tok, toks, server.summary()

    for mode in ("learned", "ngram", "off"):                  # compile
        run_pass(mode)
    learned_tps, off_tps, ratios = [], [], []
    overheads = []
    summary = ngram_summary = None
    for _ in range(3):                               # interleaved
        dt_l, tok_l, out_l, summary = run_pass("learned")
        dt_o, tok_o, out_o, _ = run_pass("off")
        dt_n, tok_n, out_n, ngram_summary = run_pass("ngram")
        assert out_l == out_o == out_n               # pure scheduling
        learned_tps.append(tok_l / dt_l)
        off_tps.append(tok_o / dt_o)
        ratios.append((tok_l / dt_l) / (tok_o / dt_o))
        overheads.append(100.0 * summary["serve_spec_propose_s"]
                         / dt_l)
    accept = summary["serve_spec_accept_rate"]
    drafted = summary["serve_spec_drafted"]
    ngram_drafted = ngram_summary["serve_spec_drafted"]
    # the structural claims, machine-noise-proof: the lookup drafter
    # is inert on this traffic while the learned drafter both
    # proposes AND gets drafts accepted
    assert ngram_drafted <= summary["serve_tokens"] * 0.02, (
        ngram_drafted, summary["serve_tokens"])
    assert drafted > 0 and accept is not None and accept > 0, (
        drafted, accept)
    return {
        "serve_spec_nonrep_requests": n_req,
        "serve_spec_nonrep_tokens": summary["serve_tokens"],
        "serve_spec_nonrep_tokens_per_sec":
            round(max(learned_tps), 1),
        "serve_spec_nonrep_baseline_tokens_per_sec":
            round(max(off_tps), 1),
        "serve_spec_nonrep_speedup": round(max(ratios), 3),
        "serve_spec_nonrep_speedup_windows":
            [round(r, 3) for r in ratios],
        "serve_spec_nonrep_accept_rate": accept,
        "serve_spec_nonrep_drafted": drafted,
        "serve_spec_nonrep_ngram_drafted": ngram_drafted,
        "serve_spec_nonrep_draft_overhead_pct":
            round(min(overheads), 2),
        "serve_spec_propose_s":
            round(summary["serve_spec_propose_s"], 4),
    }


def bench_serving_paged_kv(on_accelerator: bool):
    """Paged KV (ISSUE 11) vs the contiguous per-slot ring rows at an
    EQUAL HBM BUDGET — the tokens-resident-per-HBM-byte capacity claim.

    Scenario 1 (capacity, MIXED-length burst): the contiguous engine
    pre-reserves a full [t_max] row per slot, so a budget of B bytes
    caps concurrency at S_c = B / bytes_per_slot REGARDLESS of request
    lengths. The paged engine spends the SAME bytes as a page pool
    (n_pages * page_bytes == S_c * bytes_per_slot, asserted) shared by
    4*S_c slots; short requests hold only the pages their tokens
    occupy, so under a mixed-length burst the peak number of requests
    RESIDENT at once must reach >= 1.5x the contiguous cap (the
    ROADMAP item-3 gate — asserted; measured ~3-4x here). Outputs are
    asserted BIT-IDENTICAL per request between the two engines and
    against the serial Generator (greedy; the paged fold presents the
    same values in the same reduction order on a 1-device mesh).

    Scenario 2 (the price, UNIFORM-length trace): same slot count both
    sides, every request the same shape, so the only difference is the
    page-table gather indirection inside the fused window — the
    reported `serve_paged_overhead_pct` (interleaved pairs, best-of,
    the bench_serving discipline). This is what you pay when paging
    buys you nothing; docs/BENCHMARKS.md carries the figure."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import LMServer, Request

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, s_contig, window, chunk, ps = 2048, 8, 32, 256, 128
        n_req, p_lens, budgets = 64, (32, 256), (32, 512)
        uni_req, uni_p, uni_b = 16, 64, 192
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, s_contig, window, chunk, ps = 128, 4, 4, 16, 16
        n_req, p_lens, budgets = 24, (3, 16), (4, 24)
        uni_req, uni_p, uni_b = 8, 8, 24
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16,
              prefill_chunk=chunk, max_queue_depth=2 * n_req,
              max_prefills_per_cycle=4, window=window)
    s_paged = 4 * s_contig
    n_pages = s_contig * (t_max // ps)      # the EQUAL-budget pool

    rng = np.random.default_rng(11)
    trace = []
    for i in range(n_req):
        p_len = int(rng.integers(*p_lens))
        trace.append((0.0, Request(
            id=f"r{i}",
            prompt=tuple(int(x) for x in rng.integers(0, vocab, p_len)),
            max_new_tokens=int(rng.integers(*budgets)))))

    def run_mixed(paged: bool):
        server = LMServer(
            params, n_slots=s_paged if paged else s_contig,
            kv_page_size=ps if paged else None,
            kv_pages=n_pages if paged else None, **kw)
        t0 = time.perf_counter()
        results = server.run(trace)
        dt = time.perf_counter() - t0
        toks = {r.id: tuple(r.tokens) for r in results}      # fence
        m = server.metrics
        peak = max(m.occupancies) * server.engine.n_slots
        if paged:
            # the equal-HBM claim must be true by construction, not
            # by narrative: pool bytes == the contiguous reservation
            assert (server.engine.kv_pages
                    * server.engine.kv_page_bytes()
                    == s_contig * contig_slot_bytes), (
                server.engine.kv_page_bytes(), contig_slot_bytes)
        else:
            assert peak <= s_contig + 1e-9
        return toks, round(peak), server.summary(), dt

    # contiguous per-slot bytes, for the equal-budget assertion
    probe = LMServer(params, n_slots=1, **kw)
    contig_slot_bytes = probe.engine.kv_bytes_per_slot()
    probe.close()

    run_mixed(True)                          # compile both paths
    run_mixed(False)
    tok_p, peak_p, sum_p, _ = run_mixed(True)
    tok_c, peak_c, sum_c, _ = run_mixed(False)
    assert tok_p == tok_c, "paged vs contiguous token streams differ"
    residency_ratio = peak_p / peak_c
    assert residency_ratio >= 1.5, (
        f"paged engine held {peak_p} concurrent requests vs "
        f"{peak_c} contiguous at equal HBM — below the 1.5x gate")

    # scenario 2: uniform-length trace, same slots both sides — the
    # indirection overhead in isolation
    uni = [(0.0, Request(
        id=f"u{i}",
        prompt=tuple(int(x) for x in rng.integers(0, vocab, uni_p)),
        max_new_tokens=uni_b)) for i in range(uni_req)]

    def run_uniform(paged: bool):
        server = LMServer(
            params, n_slots=s_contig,
            kv_page_size=ps if paged else None,
            kv_pages=(s_contig * (t_max // ps)) if paged else None,
            **kw)
        t0 = time.perf_counter()
        results = server.run(uni)
        dt = time.perf_counter() - t0
        n_tok = sum(len(r.tokens) for r in results)          # fence
        assert n_tok
        return n_tok / dt

    run_uniform(True)                        # compile
    run_uniform(False)
    ratios = []
    for _ in range(3):                       # interleaved pairs
        tps_p = run_uniform(True)
        tps_c = run_uniform(False)
        ratios.append(tps_c / tps_p - 1.0)
    overhead_pct = min(ratios) * 100.0

    return {
        "serve_paged_requests": n_req,
        "serve_paged_pages": n_pages,
        "serve_paged_page_size": ps,
        "serve_paged_slots": s_paged,
        "serve_contig_slots": s_contig,
        "serve_paged_peak_resident": peak_p,
        "serve_contig_peak_resident": peak_c,
        "serve_paged_concurrent_residency_ratio": round(residency_ratio,
                                                        3),
        "serve_kv_pages_used_peak": sum_p["serve_kv_pages_used_peak"],
        "serve_kv_tokens_per_hbm_byte":
            sum_p["serve_kv_tokens_per_hbm_byte"],
        "serve_paged_tokens_per_sec": round(
            sum_p["serve_tokens_per_sec"] or 0.0, 1),
        "serve_paged_overhead_pct": round(overhead_pct, 2),
        "serve_paged_overhead_windows": [round(r * 100, 2)
                                         for r in ratios],
    }


def bench_serving_cluster(on_accelerator: bool):
    """The ISSUE-12 router tier: aggregate tokens/sec from 1 vs 2
    replicas on the SAME Poisson burst trace — the scale-out record.

    Each replica is its own engine on its OWN device slice (the
    per-replica seq-mesh carve-up), so with two replicas the router's
    host loop dispatches replica A's window while replica B's
    executes. On an ACCELERATOR fleet (each replica its own chip)
    `cluster_scaling_1to2` is the >= 1.8x scale-out gate with
    `cluster_ttft_ms_p95_2r` no worse than single-replica
    (docs/BENCHMARKS.md). On the CPU SIMULATOR the virtual devices
    share the host's physical cores, so one replica already saturates
    the machine when busy and wall-clock compute scaling is
    machine-bound at ~1.0x — the CPU figure therefore measures the
    ROUTER TAX (scaling must stay near 1.0: the tier must not COST
    throughput at 2 replicas) plus the structural TTFT win from the
    doubled slot pool; the >= 1.8x claim is stated as an accelerator
    expectation, the same discipline docs/LONG_CONTEXT.md "What is
    measured vs expected" applies to ring comm/compute overlap.

    Methodology matches bench_serving: both fleets replay the
    identical trace as a burst (arrival order kept, deterministic),
    per-request outputs are bit-identical between fleet sizes (greedy
    serial parity — asserted via total useful tokens), compilation is
    paid at fleet construction (outside the timed window), and three
    interleaved pairs are taken with the best PAIRED ratio reported
    (the chip/host load drifts on the minutes scale; pairing cancels
    most of it). Request ids are re-labelled per pass so the same
    routers replay the trace repeatedly without rebuilding."""
    import dataclasses

    import jax
    import numpy as np

    from idc_models_tpu.serve import Router, build_replica, poisson_trace
    from idc_models_tpu.models.lm import attention_lm

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 2048, 8, 64, 24
        prompt_lens, budgets = (64, 256), (400, 500)
    else:
        # CPU smoke scale: big enough that window compute (not python
        # bookkeeping) dominates the passes being compared — the
        # router-tax figure is then about the tier, not the noise
        vocab, e, heads, blocks, mlp = 128, 64, 2, 2, 256
        t_max, n_slots, window, n_req = 128, 4, 16, 24
        prompt_lens, budgets = (8, 16), (48, 56)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks)
    params = model.init(jax.random.key(0)).params
    devices = jax.devices()
    base_trace = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                               t_max=t_max, prompt_lens=prompt_lens,
                               budgets=budgets, seed=0)

    def mk_router(n: int) -> Router:
        reps = [build_replica(
            params, replica_id=f"f{n}r{i}",
            device=devices[i % len(devices)], embed_dim=e,
            num_heads=heads, num_blocks=blocks, t_max=t_max,
            n_slots=n_slots, window=window, max_queue_depth=256)
            for i in range(n)]
        return Router(reps)

    def cluster_pass(router: Router, tag: str):
        trace = [(t, dataclasses.replace(r, id=f"{tag}-{r.id}"))
                 for t, r in base_trace]
        t0 = time.perf_counter()
        results = router.run(trace)
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in results)        # fence
        assert toks and all(r.status == "ok" for r in results)
        ttft = float(np.percentile([r.ttft_ms for r in results], 95))
        return toks / dt, ttft, toks

    r1, r2 = mk_router(1), mk_router(2)
    cluster_pass(r1, "w1")                       # compile + warm both
    cluster_pass(r2, "w2")
    tp1s, tp2s, ratios = [], [], []
    ttft1 = ttft2 = None
    for i in range(3):                           # interleaved pairs
        tp1, ttft1, tok1 = cluster_pass(r1, f"p{i}a")
        tp2, ttft2, tok2 = cluster_pass(r2, f"p{i}b")
        assert tok1 == tok2, (tok1, tok2)        # same useful output
        tp1s.append(tp1)
        tp2s.append(tp2)
        ratios.append(tp2 / tp1)
    return {
        "cluster_trace_requests": n_req,
        "cluster_slots_per_replica": n_slots,
        "cluster_tokens_per_sec_1r": round(max(tp1s), 1),
        "cluster_tokens_per_sec_2r": round(max(tp2s), 1),
        "cluster_scaling_1to2": round(max(ratios), 3),
        "cluster_scaling_windows": [round(x, 3) for x in ratios],
        "cluster_ttft_ms_p95_1r": round(ttft1, 2),
        "cluster_ttft_ms_p95_2r": round(ttft2, 2),
    }


def bench_serving_elastic(on_accelerator: bool):
    """The ISSUE-18 elastic cluster: autoscaled 1 -> 2 -> 1 serving of
    a Poisson burst, with the new replica spun up WARM through the
    persistent compile cache — the two record claims asserted, not
    narrated.

    Part 1, warm spin-up: `build_replica` is timed twice against the
    same on-disk cache — cold (empty cache: every decode/sample
    program AOT-compiles and stores) and warm (a fresh CompileCache
    instance over the populated directory: every program deserializes
    instead). Both figures are honest wall-clock on THIS machine, the
    hit/store counters are asserted so the ratio provably compares
    deserialize-vs-compile and not two compiles, and the >= 10x gate
    is a hard assert (measured ~20x on the CPU simulator; the gap only
    widens on an accelerator, where XLA compiles are slower while
    deserialization stays I/O-bound).

    Part 2, the elastic loop: ONE replica + an armed autoscaler
    (max 2) replays the burst. The queue trips the up signal
    mid-trace, the factory builds the second replica against the warm
    cache, the drained queue then trips the down signal and the
    victim live-migrates its in-flight slots onto the survivor. Gates,
    asserted: at least one up AND one down decision (the fleet lands
    back at one live replica), ZERO dropped or duplicated request ids,
    and every request's tokens bit-identical to a STATIC single-
    replica run of the same trace — elasticity must be invisible to
    outputs, exactly the serial-parity discipline every other serving
    bench holds."""
    import shutil
    import tempfile

    import jax

    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import (
        AutoscaleConfig, Autoscaler, CompileCache, Router,
        build_replica, poisson_trace,
    )

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 2048, 8, 64, 24
        prompt_lens, budgets = (64, 256), (400, 500)
    else:
        vocab, e, heads, blocks, mlp = 128, 64, 2, 2, 256
        t_max, n_slots, window, n_req = 128, 4, 16, 24
        prompt_lens, budgets = (8, 16), (48, 56)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks)
    params = model.init(jax.random.key(0)).params
    devices = jax.devices()
    cache_dir = tempfile.mkdtemp(prefix="idc_compile_cache_")

    def mk_replica(rid, cache, device):
        return build_replica(
            params, replica_id=rid, device=device, embed_dim=e,
            num_heads=heads, num_blocks=blocks, t_max=t_max,
            n_slots=n_slots, window=window, max_queue_depth=256,
            compile_cache=cache)

    try:
        # ---- part 1: cold vs warm spin-up over the same cache ------
        cold_cache = CompileCache(cache_dir)
        t0 = time.perf_counter()
        rep_cold = mk_replica("cold0", cold_cache, devices[0])
        cold_s = time.perf_counter() - t0
        assert cold_cache.stores > 0 and cold_cache.hits == 0, (
            "cold spin-up must compile+store", cold_cache.summary())
        warm_cache = CompileCache(cache_dir)   # fresh counters, same dir
        t0 = time.perf_counter()
        rep_warm = mk_replica("warm0", warm_cache, devices[0])
        warm_s = time.perf_counter() - t0
        assert warm_cache.hits > 0 and warm_cache.stores == 0, (
            "warm spin-up must deserialize, never compile",
            warm_cache.summary())
        spinup_speedup = cold_s / warm_s
        assert spinup_speedup >= 10.0, (
            f"warm spin-up {warm_s:.3f}s is only "
            f"{spinup_speedup:.1f}x faster than cold {cold_s:.3f}s — "
            f"the >= 10x warm-spin-up claim failed on this machine")
        rep_cold.kill()
        rep_warm.kill()

        # ---- part 2: autoscaled 1 -> 2 -> 1 vs the static run ------
        trace = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                              t_max=t_max, prompt_lens=prompt_lens,
                              budgets=budgets, seed=0)
        static = Router([mk_replica("s0", CompileCache(cache_dir),
                                    devices[0])])
        static_results = {r.id: r.tokens for r in static.run(trace)}
        static.close()

        auto = Autoscaler(AutoscaleConfig(
            min_replicas=1, max_replicas=2, queue_high=2.0,
            queue_low=1.0, dwell_s=0.05, cooldown_s=0.2))
        fleet_cache = CompileCache(cache_dir)

        def factory(rid):
            return mk_replica(rid, fleet_cache,
                              devices[1 % len(devices)])

        router = Router([mk_replica("e0", fleet_cache, devices[0])],
                        autoscaler=auto, replica_factory=factory)
        t0 = time.perf_counter()
        results = router.run(trace)
        dt = time.perf_counter() - t0
        toks = sum(len(r.tokens) for r in results)         # fence
        # keep the control loop ticking on the idle fleet until the
        # down signal earns its dwell + cooldown (bounded wait)
        deadline = time.perf_counter() + 10.0
        while (not any(d["action"] == "down" for d in auto.decisions)
               and time.perf_counter() < deadline):
            router.step()
        ups = sum(1 for d in auto.decisions if d["action"] == "up")
        downs = sum(1 for d in auto.decisions
                    if d["action"] == "down")
        assert ups >= 1 and downs >= 1, (
            "the burst must scale the fleet up and the drained queue "
            "must scale it back down", auto.decisions)
        assert fleet_cache.hits > 0 and fleet_cache.stores == 0, (
            "the mid-trace spin-up must open WARM",
            fleet_cache.summary())
        live = router.summary()["cluster_replicas_live"]
        assert live == 1, f"fleet must land back at 1 live, got {live}"
        # zero dropped, zero duplicated, bit-identical to static
        ids = [r.id for r in results]
        assert sorted(ids) == sorted(static_results), (
            "dropped/duplicated request ids across the elastic run")
        for r in results:
            assert r.status == "ok", (r.id, r.status, r.error)
            assert r.tokens == static_results[r.id], (
                f"{r.id}: elastic output diverged from the static run")
        n_slot_migrations = len(router.slot_migrations)
        router.close()
        return {
            "elastic_trace_requests": n_req,
            "elastic_tokens_per_sec": round(toks / dt, 1),
            "elastic_scale_ups": ups,
            "elastic_scale_downs": downs,
            "elastic_slot_migrations": n_slot_migrations,
            "elastic_spinup_cold_s": round(cold_s, 3),
            "elastic_spinup_warm_s": round(warm_s, 3),
            "elastic_spinup_speedup": round(spinup_speedup, 1),
        }
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def bench_cluster_watchdog(on_accelerator: bool):
    """The ISSUE-20 anomaly watchdogs (serve/cluster/telemetry.py):
    silent-on-clean, fire-on-injected-fault — per detector — plus the
    enabled-path overhead, all ASSERTED.

    A 2-replica journaled fleet runs a burst with the watchdog armed
    on the router (one detector pass per step): ZERO anomalies on the
    clean run is the first gate — hysteresis thresholds exist so a
    healthy fleet never pages. Then each detector's fault is injected
    under a fake watchdog clock (windows advance deterministically)
    and the matching kind must fire exactly once:

    - ``accept_collapse`` / ``compile_churn``: the cumulative counters
      the detectors read (`ServingMetrics.spec_drafted` / `.accepted`,
      `.compiles_observed`) are driven past the window thresholds —
      the same inputs the serve hooks maintain, at drill speed;
    - ``canary_divergence``: a REAL rollout opens on a canary whose
      own `SLOEngine` is burn-breached (bad TTFT samples through the
      real engine) while the baseline replica stays clean;
    - ``migration_spike``: a REAL kill of a loaded replica — its
      journaled in-flight requests migrate onto the survivor, and the
      per-window migration count crosses the limit. The drained run
      must still finish every request OK (failover correctness rides
      along).

    Overhead: `watchdog.check()` is micro-timed and compared against
    the clean run's mean router-step wall — the enabled path must
    stay under the same <2% bar the tracer and profiler hold."""
    import dataclasses
    import shutil
    import tempfile

    import jax

    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.observe.slo import SLO, SLOEngine
    from idc_models_tpu.observe.metrics_registry import MetricsRegistry
    from idc_models_tpu.serve import (
        ClusterWatchdog, Router, WatchdogConfig, build_replica,
        poisson_trace,
    )

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window, n_req = 2048, 8, 64, 16
        prompt_lens, budgets = (64, 256), (200, 300)
    else:
        vocab, e, heads, blocks, mlp = 128, 64, 2, 2, 256
        t_max, n_slots, window, n_req = 128, 4, 16, 12
        prompt_lens, budgets = (8, 16), (40, 56)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks)
    params = model.init(jax.random.key(0)).params
    devices = jax.devices()
    journal_dir = tempfile.mkdtemp(prefix="idc_wd_journal_")
    wt = [0.0]                     # the watchdog's fake clock

    def mk(rid, i):
        return build_replica(
            params, replica_id=rid,
            device=devices[i % len(devices)], embed_dim=e,
            num_heads=heads, num_blocks=blocks, t_max=t_max,
            n_slots=n_slots, window=window, max_queue_depth=256,
            journal_path=str(Path(journal_dir) / f"{rid}.jsonl"))

    # the canary fault attaches this tight SLO engine (min_samples=1:
    # a handful of bad samples breach it) to the canary replica ONLY
    # for that phase — armed at build it would skew placement (a
    # breached replica is avoided) and poison the other phases
    canary_slo = SLOEngine(
        [SLO.latency("ttft", threshold_s=1e-4)],
        short_window_s=60.0, long_window_s=300.0, min_samples=1,
        registry=MetricsRegistry())
    try:
        router = Router([mk("w0", 0), mk("w1", 1)])
        cfg = WatchdogConfig(window_s=5.0, accept_min_drafted=64,
                             accept_rate_floor=0.2,
                             compile_churn_limit=8,
                             migration_spike_limit=2)
        wd = ClusterWatchdog(router, cfg, clock=lambda: wt[0])
        router.watchdog = wd

        # ---- clean gate: an armed healthy fleet stays silent -------
        trace = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                              t_max=t_max, prompt_lens=prompt_lens,
                              budgets=budgets, seed=0)
        router.run(trace)                          # warmup compiles
        trace2 = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                               t_max=t_max, prompt_lens=prompt_lens,
                               budgets=budgets, seed=1)
        trace2 = [(t, dataclasses.replace(r, id=f"c{r.id}"))
                  for t, r in trace2]
        for _, req in trace2:
            while not router.submit(req):
                router.step()
        t0 = time.perf_counter()
        steps = 0
        while not router.idle():
            router.step()
            steps += 1
        clean_dt = time.perf_counter() - t0
        assert wd.anomalies == [], (
            "the clean armed run must stay silent", wd.anomalies)

        # ---- overhead: check() micro-timed vs the step wall --------
        n_checks = 400
        t0 = time.perf_counter()
        for _ in range(n_checks):
            wd.check()
        check_us = (time.perf_counter() - t0) / n_checks * 1e6
        step_us = clean_dt / max(steps, 1) * 1e6
        overhead_pct = 100.0 * check_us / step_us
        assert overhead_pct < 2.0, (
            f"watchdog check {check_us:.1f}us is "
            f"{overhead_pct:.2f}% of a {step_us:.0f}us router step — "
            f"over the <2% observability bar")
        assert wd.anomalies == [], (
            "micro-timing checks on a quiet fleet fired", wd.anomalies)

        # ---- fault 1: speculative accept-rate collapse -------------
        wt[0] += 10.0
        wd.check()                         # rebase every window
        m0 = router.replicas[0].server.metrics
        m0.spec_drafted += 200
        m0.spec_accepted += 10             # 5% << the 20% floor
        wt[0] += 1.0
        fired = wd.check()
        assert [a["kind"] for a in fired] == ["accept_collapse"], fired
        assert wd.check() == [], "hysteresis: no re-fire while anomalous"

        # ---- fault 2: compile churn on one replica -----------------
        m1 = router.replicas[1].server.metrics
        m1.compiles_observed += 20
        wt[0] += 1.0
        fired = wd.check()
        assert ([(a["kind"], a["replica"]) for a in fired]
                == [("compile_churn", "w1")]), fired

        # ---- fault 3: canary SLO divergence ------------------------
        canary_id = router.start_rollout(params, replica_id="w1")
        assert canary_id == "w1"
        router.replicas[1].server.metrics.slo = canary_slo
        for _ in range(8):
            canary_slo.observe("ttft", 1.0)    # 1s vs the 0.1ms SLO
        canary_slo.evaluate()
        assert canary_slo.breached()
        wt[0] += 1.0
        fired = wd.check()
        assert [(a["kind"], a["replica"]) for a in fired] == [
            ("canary_divergence", "w1")], fired
        router.finish_rollout()
        # detach the drill engine: a breached replica is avoided by
        # placement, which would starve the migration fault of work
        router.replicas[1].server.metrics.slo = None

        # ---- fault 4: migration spike (real kill + failover) -------
        wt[0] += 10.0
        wd.check()
        trace3 = poisson_trace(n_req, rate_per_s=1e9, vocab=vocab,
                               t_max=t_max, prompt_lens=prompt_lens,
                               budgets=budgets, seed=2)
        trace3 = [(t, dataclasses.replace(r, id=f"m{r.id}"))
                  for t, r in trace3]
        for _, req in trace3:
            while not router.submit(req):
                router.step()
        router.step()
        n_before = len(wd.anomalies)
        migrated = router.kill_replica("w1")
        assert len(migrated) > cfg.migration_spike_limit, (
            "the kill must strand enough journaled work to spike",
            migrated)
        wt[0] += 1.0
        router.drain()                 # step() drives wd.check()
        spikes = [a for a in wd.anomalies[n_before:]
                  if a["kind"] == "migration_spike"]
        assert len(spikes) == 1, (wd.anomalies[n_before:])
        ids3 = {r.id for _, r in trace3}
        done = {r.id: r for r in router.results() if r.id in ids3}
        assert set(done) == ids3 and all(
            r.status == "ok" for r in done.values()), (
            "failover under the spike must still finish every request")

        kinds = {a["kind"] for a in wd.anomalies}
        assert kinds == {"accept_collapse", "compile_churn",
                         "canary_divergence", "migration_spike"}
        router.close()
        return {
            "cluster_watchdog_check_us": round(check_us, 2),
            "cluster_watchdog_overhead_pct": round(overhead_pct, 3),
            "cluster_watchdog_kinds_fired": len(kinds),
        }
    finally:
        shutil.rmtree(journal_dir, ignore_errors=True)


def bench_serving_multitenant(on_accelerator: bool):
    """Noisy-neighbor isolation (serve/tenancy.py, ISSUE 14): two
    tenants with independent TTFT SLOs on ONE engine, tenant A
    flooded mid-run.

    Tenant B (globex, the victim) runs the same open-loop Poisson
    trace twice: once ALONE (its clean baseline) and once mixed with
    tenant A's (acme's) background traffic PLUS an injected A flood —
    a burst far past A's quota. The acceptance gate, ASSERTED here:

    - A's ``ttft:acme`` burn-rate alert FIRES and A is degraded (its
      own brownout sheds / its queue quota rejects) — the flood is
      seen and punished;
    - B's ``ttft:globex`` alert stays SILENT, and B's TTFT p95 under
      the flood holds within a machine-noise bar of its clean
      baseline (the shared box drifts +/-40-50% on the minutes scale
      — BASELINE.md — so the bar is multiplicative-with-floor, while
      the alert silence is the structural, noise-proof half);
    - zero jit-cache growth across the whole mixed-tenant run after
      its first wave (tenant mixes are values, not shapes).

    Isolation is quota-shaped: A may hold at most 2 of the 6 decode
    slots and 8 queue entries, so the flood serializes behind A's own
    allocation while B keeps 4 slots' worth of service. The client
    replays with on_full="reject" (a flood drill's honest client:
    refusals are answers, not things to re-offer forever)."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import (
        LMServer, Request, TenantQuota, TenantRegistry,
    )

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 512, 6, 16
        n_b, rate_b, n_a, rate_a, n_flood = 48, 120.0, 24, 40.0, 80
        a_slo_ms, b_slo_ms = 30.0, 500.0
    else:
        vocab, e, heads, blocks, mlp = 16, 32, 2, 2, 64
        t_max, n_slots, window = 64, 6, 8
        n_b, rate_b, n_a, rate_a, n_flood = 24, 60.0, 24, 25.0, 40
        a_slo_ms, b_slo_ms = 12.0, 800.0
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16)
    rng = np.random.default_rng(5)

    def requests(prefix, tenant, n, rate, t0=0.0, budgets=None):
        lo_b, hi_b = budgets or (6, max(t_max // 4, 8))
        t, out = t0, []
        for i in range(n):
            t += float(rng.exponential(1.0 / rate))
            p_len = int(rng.integers(3, max(t_max // 8, 4)))
            budget = int(rng.integers(lo_b, hi_b))
            out.append((t, Request(
                id=f"{prefix}{i}",
                prompt=tuple(int(x)
                             for x in rng.integers(0, vocab, p_len)),
                max_new_tokens=min(budget, t_max - p_len),
                tenant=tenant)))
        return out

    def build_server():
        reg = TenantRegistry()
        reg.register("acme",
                     quota=TenantQuota(max_resident_slots=2,
                                       max_queued=8),
                     slo_ttft_p95_ms=a_slo_ms)
        reg.register("globex", slo_ttft_p95_ms=b_slo_ms)
        tenancy = reg.build(vocab=vocab, slo_short_window_s=10.0,
                            slo_min_samples=5, brownout_dwell_s=0.0)
        server = LMServer(params, n_slots=n_slots, window=window,
                          max_prefills_per_cycle=n_slots,
                          tenancy=tenancy, **kw)
        return server, tenancy

    trace_b = requests("b", "globex", n_b, rate_b)
    span_b = trace_b[-1][0]

    warm = [(0.0, Request(id=f"w{i}", prompt=(1, 2, 3),
                          max_new_tokens=4,
                          tenant=("acme" if i % 2 else "globex")))
            for i in range(4)]

    # -- clean baseline: tenant B alone on an identical server --------
    server, tenancy = build_server()
    server.run(warm)                     # warm the admission shapes
    server.run(trace_b, realtime=True)
    clean = server.summary()["serve_tenants"]["globex"]
    assert tenancy.slo is not None and not tenancy.slo.alerts

    # -- mixed: same B trace + A background + an injected A flood -----
    server, tenancy = build_server()
    flood_t = max(span_b * 0.3, 0.05)
    # the flood asks for LONG generations (over half the cache each):
    # serialized through A's 2-slot quota they pin A's queue at its
    # watermark and stretch A's own TTFT far past its objective —
    # while B, holding the other 4 slots, barely notices
    trace = (trace_b
             + requests("a", "acme", n_a, rate_a)
             + [(flood_t, r) for _, r in
                requests("f", "acme", n_flood, 1e9,
                         budgets=(t_max * 3 // 8, t_max * 5 // 8))])
    server.run(warm)
    sizes = server.engine.cache_sizes()
    results = server.run(trace, realtime=True, on_full="reject")
    assert server.engine.cache_sizes() == sizes, (
        server.engine.cache_sizes(), sizes)
    s = server.summary()
    mixed_b = s["serve_tenants"]["globex"]
    mixed_a = s["serve_tenants"]["acme"]
    a_alerts = [a for a in tenancy.slo.alerts
                if a["slo"] == "ttft:acme"]
    b_alerts = [a for a in tenancy.slo.alerts
                if a["slo"] == "ttft:globex"]
    degraded = (mixed_a["shed"] + mixed_a["quota_rejections"]
                + sum(1 for r in results
                      if r.id.startswith(("a", "f"))
                      and r.status == "rejected"))
    # the acceptance gates — structural, machine-noise-proof
    assert a_alerts, "tenant A flooded but its TTFT alert never fired"
    assert not b_alerts, (
        f"tenant B's TTFT alert fired under A's flood: {b_alerts}")
    assert degraded > 0, "the flood was never shed/quota-refused"
    assert all(server.poll(r.id) is not None
               and server.poll(r.id).status == "ok"
               for r in (req for _, req in trace_b)), (
        "a tenant-B request was lost under the flood")
    ratio = (mixed_b["ttft_ms_p95"] / clean["ttft_ms_p95"]
             if clean["ttft_ms_p95"] else None)
    # B "unharmed": multiplicative bar with an absolute floor (clean
    # p95 is single-digit ms on the smoke config, where scheduler
    # jitter alone is a large multiple)
    limit = max(3.0 * clean["ttft_ms_p95"],
                clean["ttft_ms_p95"] + 80.0)
    assert mixed_b["ttft_ms_p95"] <= limit, (
        f"tenant B TTFT p95 {mixed_b['ttft_ms_p95']}ms vs clean "
        f"{clean['ttft_ms_p95']}ms exceeds the isolation bar {limit}")
    return {
        "serve_mt_tenants": 2,
        "serve_mt_flood_requests": n_flood,
        "serve_mt_b_requests": mixed_b["requests"],
        "serve_mt_b_ttft_ms_p95_clean": clean["ttft_ms_p95"],
        "serve_mt_b_ttft_ms_p95_mixed": mixed_b["ttft_ms_p95"],
        "serve_mt_b_ttft_ratio_mixed_vs_clean": (
            round(ratio, 3) if ratio is not None else None),
        "serve_mt_a_slo_alerts": len(a_alerts),
        "serve_mt_b_slo_alerts": len(b_alerts),
        "serve_mt_a_shed": mixed_a["shed"],
        "serve_mt_a_quota_rejected": mixed_a["quota_rejections"],
        "serve_mt_a_requests_ok": mixed_a["requests"],
    }


def bench_serving_resilience(on_accelerator: bool):
    """The ISSUE-8 resilience layer under load, two scenarios:

    1. OVERLOAD BURST — the same synthetic burst wave (declarative
       `burst` faults, deterministic arrivals) against a brownout-
       protected server vs an unprotected one. The protected server
       escalates pause-writes -> clamp -> shed as the queue passes its
       watermark and TTFT p95 of the requests it DOES serve stays
       bounded (documented bound, asserted here: strictly below the
       unprotected run's p95 — which grows with the unshed queue);
       the unprotected server serves everything late.
    2. CLEAN-PATH TAX — what arming EVERY resilience feature (per-cycle
       slot health checks, request journal, brownout controller, TTFT
       SLO evaluation) adds to one steady-state decode cycle, with no
       faults firing. Measured the same way as bench_tracer_overhead
       (whose <2% bar this shares): each component's per-cycle cost is
       timed in isolation over many iterations against the measured
       decode-window wall — an A/B of full serve runs cannot resolve a
       <2% effect under this machine's ±50% run-to-run noise, while
       the component arithmetic is noise-immune. The gated figure
       charges the work that sits on the DEVICE-IDLE critical path
       (the slot-health reduce + fetch, between collect and the next
       dispatch); the journal write and the brownout/SLO evaluation
       run in the tick's deferred-bookkeeping section WHILE the next
       window executes on device, so they are reported separately
       (`serve_resilience_deferred_us_per_cycle`) and measured
       pessimistically (every slot emitting every cycle).
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import (
        BrownoutController, LMServer, RetryPolicy, Request, ServeFault,
        ServeFaultPlan,
    )
    from idc_models_tpu.observe import SLO, SLOEngine
    from idc_models_tpu.observe.metrics_registry import MetricsRegistry

    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 2048, 8, 32
        n_base, budgets = 8, (200, 260)
        burst_ticks, burst_n, burst_budget = range(4, 10), 8, 200
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_slots, window = 128, 4, 8
        n_base, budgets = 8, (24, 32)
        burst_ticks, burst_n, burst_budget = range(3, 9), 6, 24
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, mesh=mesh, cache_dtype=jnp.bfloat16,
              n_slots=n_slots, window=window, max_queue_depth=256)

    rng = np.random.default_rng(11)

    def mk_trace(tag, n, lo, hi):
        return [(0.0, Request(
            id=f"{tag}{i}",
            prompt=tuple(int(x) for x in rng.integers(0, vocab, 6)),
            max_new_tokens=int(rng.integers(lo, hi))))
            for i in range(n)]

    # ---- scenario 1: burst vs brownout --------------------------------
    burst_plan = ServeFaultPlan(
        [ServeFault("burst", t, n=burst_n, prompt_len=6,
                    budget=burst_budget) for t in burst_ticks])

    def burst_pass(protected: bool):
        ctrl = None
        if protected:
            ctrl = BrownoutController(
                queue_high=2 * n_slots, queue_low=1, clamp_tokens=8,
                escalate_dwell_s=0.0, clear_after_s=0.05)
        server = LMServer(params, fault_plan=burst_plan, brownout=ctrl,
                          **kw)
        server.run(mk_trace("p" if protected else "u", n_base,
                            *budgets))
        s = server.summary()
        return s, (ctrl.max_stage_seen if ctrl else 0)

    burst_pass(True)                                 # compile both paths
    burst_pass(False)
    best_p = best_u = None
    max_stage = 0
    for _ in range(2):                               # interleaved pairs
        s_p, stage = burst_pass(True)
        s_u, _ = burst_pass(False)
        max_stage = max(max_stage, stage)
        if (best_p is None
                or s_p["serve_ttft_ms_p95"] < best_p["serve_ttft_ms_p95"]):
            best_p = s_p
        if (best_u is None
                or s_u["serve_ttft_ms_p95"] < best_u["serve_ttft_ms_p95"]):
            best_u = s_u
    assert best_p["serve_shed"] > 0, "brownout never shed under burst"
    # the documented bound: while shedding, served-request TTFT p95
    # stays strictly below the unprotected run's (which absorbs the
    # whole unshed queue as tail latency)
    assert (best_p["serve_ttft_ms_p95"]
            < best_u["serve_ttft_ms_p95"]), (best_p, best_u)

    # ---- scenario 2: clean-path tax -----------------------------------
    # One full armed run first — parity/status sanity, not timing: every
    # feature on, no fault fires, everything finishes ok with zero
    # quarantines. (Token parity vs the serial Generator is gated in
    # tests/test_serve_resilience.py.)
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    slo = SLOEngine([SLO.latency("ttft", threshold_s=60.0)],
                    registry=MetricsRegistry())
    armed = LMServer(
        params, retry=RetryPolicy(max_retries=2),
        fault_plan=ServeFaultPlan([]),          # health checks on
        journal=tmp.name,
        brownout=BrownoutController(queue_high=10_000), slo=slo, **kw)
    results = armed.run(mk_trace("c", 3 * n_slots, *budgets))
    assert results and all(r.status == "ok" for r in results)
    assert armed.summary()["serve_slot_faults"] == 0

    # The tax itself is measured per COMPONENT, bench_tracer_overhead
    # style: the armed loop adds exactly (a) one slot_health reduce +
    # fetch + the host invariant checks on the device-idle critical
    # path, and — in the deferred-bookkeeping section overlapping the
    # dispatched window — (b) journal progress writes, (c) one empty
    # fault-plan probe, (d) one brownout evaluate, and (e) the SLO
    # evaluate (PR 7 machinery). Each is timed in isolation over many
    # iterations; the denominator is the measured steady-state decode
    # window wall on the SAME armed server.
    for i in range(n_slots):
        armed.submit(Request(id=f"w{i}", prompt=(1, 2, 3, 4),
                             max_new_tokens=t_max - 8))
    armed.step()                                # admissions + window
    armed.step()                                # warm steady state

    def timed_windows(k):
        t0 = time.perf_counter()
        for _ in range(k):
            armed.step()    # collect (host token fetch = fence) + next
        return (time.perf_counter() - t0) / k
    k = max(4, (t_max - 8) // window - 4)
    window_s = min(timed_windows(k // 2), timed_windows(k - k // 2))

    eng, sched = armed.engine, armed.scheduler
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        codes = eng.slot_health()
        for s in range(n_slots):
            if codes[s] or not eng.slot_invariants_ok(s):
                raise AssertionError("clean engine reported a fault")
    health_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        # pessimistic: every slot emits every cycle; the journal
        # batches the cycle into one record and strides the writes
        armed.journal.record_progress(
            {f"w{s}": window for s in range(n_slots)})
    journal_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        sched.brownout.evaluate(queue_depth=0)
        sched.fault_plan.at(sched._cycle)
        sched.fault_plan.bursts_at(sched._cycle)
    control_s = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        slo.evaluate()
    slo_s = (time.perf_counter() - t0) / reps
    armed.close()
    os.unlink(tmp.name)

    deferred_s = journal_s + control_s + slo_s
    overhead_pct = health_s / window_s * 100.0
    return {
        "serve_resilience_requests": n_base,
        "serve_resilience_burst_requests": burst_n * len(burst_ticks),
        "serve_resilience_shed": best_p["serve_shed"],
        "serve_brownout_max_stage": max_stage,
        "serve_resilience_ttft_ms_p95_brownout":
            best_p["serve_ttft_ms_p95"],
        "serve_resilience_ttft_ms_p95_unprotected":
            best_u["serve_ttft_ms_p95"],
        "serve_resilience_window_ms": round(window_s * 1e3, 3),
        "serve_resilience_health_us_per_cycle": round(health_s * 1e6, 2),
        "serve_resilience_deferred_us_per_cycle":
            round(deferred_s * 1e6, 2),
        "serve_resilience_overhead_pct": round(overhead_pct, 4),
    }


def bench_tracer_overhead(on_accelerator: bool):
    """The observability tax on the serve decode hot loop — gated by
    the ISSUE-5 acceptance bar (< 2% with tracing disabled).

    PR 5 threaded `observe.trace.span(...)` calls through the
    scheduler's tick cycle (tick/admit/collect/window) and the engine's
    prefill paths. With no tracer installed each call is one module-
    global read returning a shared no-op handle; the overhead added vs
    the PR-4 (uninstrumented) loop is EXACTLY those disabled calls. So
    the honest decomposition is measured directly:

    - `trace_disabled_ns_per_span` — the cost of one disabled span
      (micro-timed over a large N);
    - `serve_trace_spans_per_window` — how many span sites one decode
      cycle executes (counted by running the same loop under an
      enabled tracer);
    - `serve_decode_window_ms` — the wall cost of one steady-state
      decode cycle through the scheduler (host fetch fence: collect's
      token transfer data-depends on the window);
    - `serve_trace_disabled_overhead_pct` = spans/window x ns/span /
      window wall — the recorded bar;

    plus `trace_enabled_us_per_span` so the tracing-ON cost is on
    record too (operators opt into that per run with --trace-out)."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.observe import trace as trace_lib
    from idc_models_tpu.serve import Request, LMServer

    # 1) disabled / enabled span micro-cost
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace_lib.span("bench", a=1):
            pass
    disabled_ns = (time.perf_counter() - t0) / n * 1e9
    tr = trace_lib.Tracer()
    prev = trace_lib.set_tracer(tr)
    try:
        ne = 20_000
        t0 = time.perf_counter()
        for _ in range(ne):
            with trace_lib.span("bench", a=1):
                pass
        enabled_us = (time.perf_counter() - t0) / ne * 1e6
    finally:
        # a raise mid-measurement must not leave the global tracer
        # armed for every later benchmark (the library's tracing()
        # context restores in finally; match it here)
        trace_lib.set_tracer(prev)

    # 2) the decode hot loop: long-budget requests saturating all slots,
    #    timed over steady-state windows (scale mirrors bench_serving)
    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 2048, 8, 64
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_slots, window = 128, 4, 8
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params

    def build():
        return LMServer(params, embed_dim=e, num_heads=heads,
                        num_blocks=blocks, t_max=t_max, mesh=mesh,
                        n_slots=n_slots, window=window,
                        cache_dtype=jnp.bfloat16)

    def fill(server):
        budget = t_max - 8
        for i in range(n_slots):
            server.submit(Request(id=f"b{i}", prompt=(1, 2, 3, 4),
                                  max_new_tokens=budget))
        server.step()                       # admissions + first window

    def timed_windows(server, k):
        t0 = time.perf_counter()
        for _ in range(k):
            server.step()   # collect (host token fetch = fence) + next
        return (time.perf_counter() - t0) / k

    server = build()
    fill(server)
    timed_windows(server, 2)                # warm
    k = max(2, (t_max - 32) // window - 4)
    window_s = min(timed_windows(server, k // 2),
                   timed_windows(server, k - k // 2))

    # 3) span sites per cycle, counted with the tracer ON — armed only
    #    AFTER admission so the numerator holds exactly the steady-state
    #    decode ticks the denominator (window_s) measures, not the fill
    #    tick's prefill spans
    server2 = build()
    fill(server2)
    tr = trace_lib.Tracer()
    prev = trace_lib.set_tracer(tr)
    try:
        n_ticks = 4
        for _ in range(n_ticks):
            server2.step()
    finally:
        trace_lib.set_tracer(prev)
    spans_per_window = len([r for r in tr.records()
                            if r["name"].startswith("serve.")]) / n_ticks

    overhead_pct = (spans_per_window * disabled_ns * 1e-9
                    / window_s * 100.0)
    return {
        "trace_disabled_ns_per_span": round(disabled_ns, 1),
        "trace_enabled_us_per_span": round(enabled_us, 3),
        "serve_trace_spans_per_window": round(spans_per_window, 2),
        "serve_decode_window_ms": round(window_s * 1e3, 3),
        "serve_trace_disabled_overhead_pct": round(overhead_pct, 4),
    }


def bench_profile_overhead(on_accelerator: bool):
    """The ISSUE-9 armed-profiler tax on the serve decode hot loop —
    gated against the house <2%-of-a-decode-window bar.

    A `profile` run arms three things on the serve cycle: (a) the
    `device.sync` span bracketing collect's token fetch (an ENABLED
    tracer span — disabled it is the no-op handle bench_tracer_overhead
    already prices), (b) the scheduler's `naming_compiles("serve.admit")`
    thread-local compile-name context (a shared no-op read when no
    watchdog is armed), and (c) the jax.monitoring listener, which
    fires only on an actual compile — zero on the steady-state cycle
    the no-recompile contract guarantees. Same component-wise
    methodology as bench_tracer_overhead / bench_serving_resilience:
    an A/B of full runs cannot resolve a <2% effect under this
    machine's run-to-run noise, while micro-timing each component
    against the measured window wall is noise-immune."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.observe import profile as prof
    from idc_models_tpu.observe import trace as trace_lib
    from idc_models_tpu.serve import LMServer, Request

    # 1) per-component micro-costs
    n = 50_000
    tr = trace_lib.Tracer()
    prev = trace_lib.set_tracer(tr)
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            with trace_lib.span("device.sync"):
                pass
        sync_span_s = (time.perf_counter() - t0) / n
    finally:
        trace_lib.set_tracer(prev)
    wd = prof.arm_watchdog(limit=1_000_000)
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            with prof.naming_compiles("serve.admit"):
                pass
        naming_s = (time.perf_counter() - t0) / n
    finally:
        prof.disarm_watchdog()
    assert not wd.report()["flagged"]

    # 2) the decode window wall (same loop/scale as
    #    bench_tracer_overhead's denominator)
    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 512, 8, 2, 2048
        t_max, n_slots, window = 2048, 8, 64
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_slots, window = 128, 4, 8
    mesh = meshlib.seq_mesh(1)
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks, mesh=mesh)
    params = model.init(jax.random.key(0)).params
    server = LMServer(params, embed_dim=e, num_heads=heads,
                      num_blocks=blocks, t_max=t_max, mesh=mesh,
                      n_slots=n_slots, window=window,
                      cache_dtype=jnp.bfloat16)
    for i in range(n_slots):
        server.submit(Request(id=f"b{i}", prompt=(1, 2, 3, 4),
                              max_new_tokens=t_max - 8))
    server.step()
    server.step()

    def timed_windows(k):
        t0 = time.perf_counter()
        for _ in range(k):
            server.step()
        return (time.perf_counter() - t0) / k

    k = max(2, (t_max - 32) // window - 4)
    window_s = min(timed_windows(k // 2), timed_windows(k - k // 2))
    server.close()

    per_cycle_s = sync_span_s + naming_s
    overhead_pct = per_cycle_s / window_s * 100.0
    assert overhead_pct < 2.0, (
        f"armed profiler costs {overhead_pct:.3f}% of a decode window "
        f"(bar: 2%)")
    return {
        "profile_sync_span_us": round(sync_span_s * 1e6, 4),
        "profile_naming_us": round(naming_s * 1e6, 4),
        "profile_armed_us_per_cycle": round(per_cycle_s * 1e6, 4),
        "profile_decode_window_ms": round(window_s * 1e3, 3),
        "profile_armed_overhead_pct": round(overhead_pct, 4),
    }


def bench_checkpoint_rollout(on_accelerator: bool):
    """The ISSUE-17 acceptance drills, measured:

    1. CROSS-MESH SAVE/RESTORE — a sharded tree saved under one mesh
       layout restores bit-identically under a DIFFERENT layout (the
       partition rules are re-resolved against the target mesh), with
       restore peak host bytes bounded by one target block plus one
       saved shard — never O(model) on any single host. Throughput is
       the headline: `ckpt_save_mb_per_s` / `ckpt_restore_mb_per_s`,
       plus `ckpt_restore_peak_host_ratio` (peak host bytes over the
       full tree — the smaller, the more out-of-core the restore).
    2. LIVE ROLLOUT — `run_with_rollout` replays a Poisson trace while
       staging -> canarying -> promoting a candidate that arrives as a
       sharded checkpoint DIRECTORY: zero dropped, zero duplicated,
       zero errored requests, asserted. Then the forced-bad drill: a
       NaN candidate is refused at staging (spot-check on the compiled
       programs), the serve stage lands rolled_back, and every client
       request still finishes ok.

    Degrades gracefully below 8 devices: the mesh shapes are derived
    from the live device count (on one device both layouts collapse to
    1x1 — the bit-identity, integrity, and peak-bound assertions still
    run; only the cross-layout re-shard goes trivial).
    """
    import tempfile

    import jax
    from jax.sharding import PartitionSpec as P

    from idc_models_tpu import mesh as meshlib, partition
    from idc_models_tpu.checkpoint import (
        checkpoint_info, restore_sharded, run_with_rollout,
        save_sharded,
    )
    from idc_models_tpu.models.lm import attention_lm
    from idc_models_tpu.serve import LMServer, poisson_trace

    # ---- scenario 1: cross-mesh save/restore throughput ---------------
    if on_accelerator:
        dim, blocks_n = 4096, 4          # ~ 128 MiB tree
    else:
        dim, blocks_n = 1024, 4          # ~ 8 MiB tree
    rules = partition.PartitionRules((
        (r"w1$", P(meshlib.DATA_AXIS, meshlib.MODEL_AXIS)),
        (r"blocks/.*/kernel$", P(None, meshlib.MODEL_AXIS)),
        (r".*", P()),
    ))
    rng = np.random.default_rng(17)
    tree = {
        "w1": rng.normal(size=(dim, dim)).astype(np.float32),
        "blocks": {str(i): {"kernel": rng.normal(size=(dim // 2,
                                                       dim // 2))
                            .astype(np.float32)}
                   for i in range(blocks_n)},
        "step": np.int32(0),
    }
    total = sum(a.nbytes for _, a in partition.tree_paths(tree))
    n_dev = jax.device_count()
    tp = 2 if n_dev % 2 == 0 else 1
    save_mesh = meshlib.fsdp_tp_mesh(n_dev // tp, tp)
    restore_mesh = meshlib.fsdp_tp_mesh(n_dev, 1)
    placed = partition.shard_tree(save_mesh, rules, tree)

    save_s = restore_s = float("inf")
    restored = stats = None
    for _ in range(2):                   # keep the best of two passes
        with tempfile.TemporaryDirectory() as td:
            ck = Path(td) / "ck"
            t0 = time.perf_counter()
            save_sharded(ck, placed, step=1).wait()
            save_s = min(save_s, time.perf_counter() - t0)
            stats = {}
            t0 = time.perf_counter()
            restored = restore_sharded(ck, mesh=restore_mesh,
                                       rules=rules, stats=stats)
            jax.block_until_ready(restored)
            restore_s = min(restore_s, time.perf_counter() - t0)
            biggest_shard = max(
                s["bytes"]
                for rec in checkpoint_info(ck)["leaves"].values()
                for s in rec["shards"])
    # bit-identical across the layout change, every leaf
    for (n1, a), (n2, b) in zip(partition.tree_paths(restored),
                                partition.tree_paths(tree)):
        assert n1 == n2
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), b, err_msg=n1)
    # and the no-O(model)-host-memory bound from the stats hook
    biggest_block = max(sh.data.nbytes
                        for _, leaf in partition.tree_paths(restored)
                        for sh in leaf.addressable_shards)
    assert stats["peak_host_bytes"] <= biggest_block + biggest_shard, (
        stats["peak_host_bytes"], biggest_block, biggest_shard)
    assert stats["bytes_read"] >= total

    # ---- scenario 2: live rollout under a Poisson trace ---------------
    if on_accelerator:
        vocab, e, heads, blocks, mlp = 1024, 256, 4, 2, 512
        t_max, n_req = 256, 48
    else:
        vocab, e, heads, blocks, mlp = 32, 32, 2, 2, 64
        t_max, n_req = 64, 24
    model = attention_lm(vocab, t_max, embed_dim=e, num_heads=heads,
                         mlp_dim=mlp, num_blocks=blocks)
    params = model.init(jax.random.key(0)).params
    candidate = model.init(jax.random.key(1)).params
    kw = dict(embed_dim=e, num_heads=heads, num_blocks=blocks,
              t_max=t_max, n_slots=4, window=8)
    trace = poisson_trace(n_req, rate_per_s=500.0, vocab=vocab,
                          t_max=t_max, prompt_lens=(3, 8),
                          budgets=(3, 6), seed=17)

    with tempfile.TemporaryDirectory() as td:
        save_sharded(Path(td) / "cand", candidate).wait()
        server = LMServer(params, **kw)
        t0 = time.perf_counter()
        res, ctl = run_with_rollout(server, trace,
                                    str(Path(td) / "cand"),
                                    canary_fraction=0.5,
                                    canary_requests=3)
        promote_s = time.perf_counter() - t0
        server.close()
    ids = [r.id for r in res]
    assert sorted(ids) == sorted(t[1].id for t in trace)   # zero drop
    assert len(set(ids)) == len(ids)                       # zero dup
    assert all(r.status == "ok" for r in res), (
        [r.status for r in res])
    assert ctl.stage == "promoted", (ctl.stage, ctl.reason)

    # forced-bad: NaN candidate refused at staging, clients untouched
    import jax.numpy as jnp

    bad = jax.tree.map(lambda a: jnp.full_like(a, jnp.nan), params)
    server = LMServer(params, **kw)
    res, ctl = run_with_rollout(server, trace, bad,
                                canary_fraction=0.5,
                                canary_requests=3)
    server.close()
    assert ctl.stage == "rolled_back", (ctl.stage, ctl.reason)
    assert all(r.status == "ok" for r in res)
    assert len(res) == len(trace)

    mib = total / 2**20
    return {
        "ckpt_tree_mb": round(mib, 2),
        "ckpt_save_mb_per_s": round(mib / save_s, 2),
        "ckpt_restore_mb_per_s": round(mib / restore_s, 2),
        "ckpt_restore_peak_host_ratio": round(
            stats["peak_host_bytes"] / total, 4),
        "ckpt_rollout_promote_s": round(promote_s, 3),
    }


# ---------------------------------------------------------------------------
# bench_compare: regression triage over the recorded BENCH_rNN.json trail
# ---------------------------------------------------------------------------

# headline keys and their good direction — every key here is documented
# in docs/BENCHMARKS.md; keys absent from either run are skipped (the
# bench set grows over time)
HIGHER_IS_BETTER = (
    "value", "median_value", "mfu",
    "cached_fine_tune_patches_per_sec_per_chip",
    "mobile_patches_per_sec_per_chip", "mobile_mfu",
    "dense_patches_per_sec_per_chip", "dense_mfu",
    "mobile_fused_patches_per_sec", "mobile_fused_speedup",
    "mobile_fused_hbm_utilization",
    "dense_fused_patches_per_sec", "dense_fused_speedup",
    "dense_fused_hbm_utilization",
    "decode_tokens_per_sec", "serve_tokens_per_sec",
    "serve_speedup_vs_serial", "serve_slot_occupancy",
    "serve_prefix_hit_rate", "serve_int8_kv_slot_capacity_ratio",
    "serve_spec_tokens_per_sec", "serve_spec_speedup",
    "serve_spec_accept_rate", "serve_spec_tokens_per_dispatch",
    "serve_spec_nonrep_tokens_per_sec", "serve_spec_nonrep_speedup",
    "serve_spec_nonrep_accept_rate",
    "serve_paged_concurrent_residency_ratio",
    "serve_kv_tokens_per_hbm_byte", "serve_paged_tokens_per_sec",
    "cluster_tokens_per_sec_1r", "cluster_tokens_per_sec_2r",
    "cluster_scaling_1to2",
    "elastic_tokens_per_sec", "elastic_spinup_speedup",
    "ring_fwd_speedup_vs_jnp", "ring_fwd_speedup_median",
    "zigzag_schedule_speedup", "fed_byz_robust_advantage",
    "fed_async_speedup", "fed_scale_replay_bitwise",
    "ckpt_save_mb_per_s", "ckpt_restore_mb_per_s",
)
LOWER_IS_BETTER = (
    "fed_round_s", "fed_round_32_s", "secure_round_s",
    "prefill_ms", "decode_ms_per_token",
    "lm_sharded_hbm_ratio_fsdp", "lm_sharded_hbm_ratio_tp",
    "lm_sharded_step_ms_fsdp", "lm_sharded_step_ms_tp",
    "serve_ttft_ms_p50", "serve_ttft_ms_p95",
    "serve_ttft_ms_p95_shared_prefix", "cluster_ttft_ms_p95_1r",
    "cluster_ttft_ms_p95_2r",
    "elastic_spinup_cold_s", "elastic_spinup_warm_s",
    "serve_chunked_prefill_decode_stall_ms",
    "serve_resilience_ttft_ms_p95_brownout",
    "serve_mt_b_ttft_ms_p95_mixed",
    "serve_mt_b_ttft_ratio_mixed_vs_clean",
    "serve_resilience_overhead_pct",
    "serve_spec_nonrep_draft_overhead_pct",
    "serve_spec_propose_s",
    "serve_paged_overhead_pct",
    "serve_trace_disabled_overhead_pct",
    "trace_disabled_ns_per_span", "trace_enabled_us_per_span",
    "profile_armed_overhead_pct",
    "profile_sync_span_us", "profile_naming_us",
    "profile_armed_us_per_cycle",
    "cluster_watchdog_check_us", "cluster_watchdog_overhead_pct",
    "flash_fwd_bwd_ms", "model_step_ms",
    "zigzag_zigzag_ms", "ring_fwd_pallas_ms",
    "fed_scale_round_s", "fed_scale_peak_growth_mb",
    "fed_async_wall_to_loss_s",
    "ckpt_restore_peak_host_ratio",
    "ckpt_rollout_promote_s",
)

# Keys benches emit that carry no "good direction": configuration echoes
# (slot counts, window sizes, page geometry), raw event counts whose value
# depends on the scenario rather than on code quality (sheds, migrations,
# quota rejections), and context baselines that the directional ratios are
# already derived from.  bench_compare skips these; the completeness gate in
# tests/test_observability.py asserts every constant key a bench returns is
# either directional or listed here, and that nothing here has gone stale.
NEUTRAL_KEYS = (
    # model / kernel context
    "batch_per_chip", "flops_per_patch", "step_tflops", "steps",
    "patches_per_sec_per_chip", "median_patches_per_sec_per_chip",
    "flash_fwd_bwd_t", "model_step_t", "ring_fwd_t", "prefill_t",
    "zigzag_t_local", "zigzag_ring", "zigzag_contiguous_ms",
    "lm_sharded_peak_hbm_replicated_mb",
    # serving configuration echoes
    "serve_slots", "serve_window", "serve_eos_id", "serve_tokens",
    "serve_decode_window_ms", "decode_window_tokens", "window_s",
    "serve_contig_slots", "serve_paged_slots", "serve_paged_page_size",
    "serve_paged_pages", "serve_paged_requests", "serve_paged_peak_resident",
    "serve_paged_overhead_windows", "serve_contig_peak_resident",
    "serve_kv_pages_used_peak", "serve_tokens_per_sec_windows",
    "serve_speedup_windows",
    "serve_monolithic_prefill_decode_stall_ms",
    "serve_monolithic_prefill_decode_stall_ms_max",
    "serve_chunked_prefill_decode_stall_ms_max",
    "serve_ttft_ms_p95_shared_prefix_monolithic",
    "serial_tokens_per_sec",
    # speculative-decoding context (ratios above are the directional view)
    "serve_spec_requests", "serve_spec_tokens", "serve_spec_draft_k",
    "serve_spec_verify_dispatches", "serve_spec_speedup_windows",
    "serve_spec_baseline_tokens_per_sec",
    "serve_tokens_per_dispatch_spec", "serve_tokens_per_dispatch_nospec",
    # prefix cache scenario shape
    "serve_prefix_requests", "serve_prefix_distinct_prefixes",
    "serve_prefix_token_hit_rate",
    # resilience / multi-tenant scenario counts
    "serve_resilience_requests", "serve_resilience_burst_requests",
    "serve_resilience_shed", "serve_resilience_window_ms",
    "serve_resilience_ttft_ms_p95_unprotected",
    "serve_resilience_deferred_us_per_cycle",
    "serve_resilience_health_us_per_cycle",
    "serve_brownout_max_stage",
    "serve_mt_tenants", "serve_mt_a_requests_ok", "serve_mt_a_shed",
    "serve_mt_a_quota_rejected", "serve_mt_a_slo_alerts",
    "serve_mt_b_requests", "serve_mt_b_slo_alerts",
    "serve_mt_b_ttft_ms_p95_clean", "serve_mt_flood_requests",
    # tracing / cluster scenario counts
    "serve_trace_requests", "serve_trace_spans_per_window",
    "cluster_trace_requests", "cluster_slots_per_replica",
    "cluster_scaling_windows", "cluster_watchdog_kinds_fired",
    "elastic_trace_requests", "elastic_scale_ups", "elastic_scale_downs",
    "elastic_slot_migrations",
    # federated scenario shape
    "fed_byz_clients", "fed_byz_total_clients", "fed_byz_rounds",
    "fed_byz_mean_eval_loss", "fed_byz_trimmed_eval_loss",
    "fed_scale_population", "fed_scale_cohort", "fed_scale_wave",
    "fed_scale_round_s_1k", "fed_scale_round_s_cold",
    "fed_scale_rss_delta_mb_1k", "fed_scale_rss_delta_mb_10k",
    # checkpoint / profile context
    "ckpt_tree_mb", "profile_decode_window_ms",
)


def _load_bench_record(path: Path) -> dict | None:
    """The bench JSON line out of a BENCH_rNN.json driver record (its
    `tail` holds the run's stdout) or a raw one-line bench output."""
    try:
        doc = json.loads(Path(path).read_text())
    except ValueError:
        return None
    if isinstance(doc, dict) and "metric" in doc:
        return doc
    tail = doc.get("tail", "") if isinstance(doc, dict) else ""
    for line in reversed(tail.splitlines()):
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    return None


def bench_compare(bench_dir=".", *, tolerance: float = 0.10,
                  allow_cross_device: bool = False) -> dict:
    """Diff the NEWEST BENCH_rNN.json against the previous one and flag
    headline-key regressions beyond `tolerance` (default 10%).

    Returns {"old": path, "new": path, "keys": {key: {old, new, ratio,
    regressed}}, "regressions": [key, ...]} — `ratio` is new/old, and
    `regressed` respects each key's direction (a 15% TTFT p95 INCREASE
    regresses; a 15% throughput increase does not). Keys missing from
    either record (the bench set grows over time) are skipped. Prints a
    human table; the caller decides what a regression is worth (the
    recorded windows drift ±10% on the shared chip — see BASELINE.md —
    so treat a single flagged key as a re-measure prompt, not a
    verdict).

    Records from DIFFERENT `device_kind`s are refused outright unless
    `allow_cross_device=True` (CLI: --allow-cross-device): a CPU
    record diffed against a TPU trail measures the hardware swap, not
    a code regression — every key would flag and the table would be
    noise dressed as signal. With the override the comparison runs but
    is stamped loudly (a `cross_device` field plus a WARNING line),
    so it can never silently pass for a same-hardware diff."""
    # order by the integer run index — lexicographic order misplaces
    # r100 between r10 and r11 once the trail passes two digits
    files = sorted(
        (p for p in Path(bench_dir).glob("BENCH_r[0-9]*.json")
         if p.stem[len("BENCH_r"):].isdigit()),
        key=lambda p: int(p.stem[len("BENCH_r"):]))
    pairs = [(f, _load_bench_record(f)) for f in files]
    pairs = [(f, rec) for f, rec in pairs if rec is not None]
    if len(pairs) < 2:
        raise ValueError(
            f"need at least two parseable BENCH_rNN.json files under "
            f"{bench_dir!r}, found {len(pairs)}")
    (old_path, old), (new_path, new) = pairs[-2], pairs[-1]
    out: dict = {"old": str(old_path), "new": str(new_path), "keys": {},
                 "regressions": []}
    dk_old, dk_new = old.get("device_kind"), new.get("device_kind")
    if dk_old and dk_new and dk_old != dk_new:
        if not allow_cross_device:
            raise ValueError(
                f"refusing to compare across device kinds: "
                f"{old_path.name} was measured on {dk_old!r} but "
                f"{new_path.name} on {dk_new!r} — the diff would "
                f"measure the hardware swap, not a regression "
                f"(docs/BENCHMARKS.md caveats the r06 cpu record for "
                f"exactly this). Re-measure on one kind, or pass "
                f"--allow-cross-device / allow_cross_device=True to "
                f"proceed with the comparison loudly flagged")
        out["cross_device"] = [dk_old, dk_new]
        print(f"WARNING: cross-device comparison ({dk_old!r} -> "
              f"{dk_new!r}) — ratios measure the hardware swap, not "
              f"code; regressions below are NOT actionable")
    rows = []
    for key in HIGHER_IS_BETTER + LOWER_IS_BETTER:
        a, b = old.get(key), new.get(key)
        if (not isinstance(a, (int, float)) or isinstance(a, bool)
                or not isinstance(b, (int, float)) or a == 0):
            continue
        ratio = b / a
        higher_better = key in HIGHER_IS_BETTER
        regressed = (ratio < 1.0 - tolerance if higher_better
                     else ratio > 1.0 + tolerance)
        out["keys"][key] = {"old": a, "new": b,
                            "ratio": round(ratio, 4),
                            "regressed": regressed}
        if regressed:
            out["regressions"].append(key)
        rows.append((key, a, b, ratio, regressed, higher_better))
    print(f"bench compare: {old_path.name} -> {new_path.name} "
          f"(flagging >{tolerance:.0%} moves against each key's "
          f"direction)")
    for key, a, b, ratio, regressed, hb in rows:
        mark = " REGRESSED" if regressed else ""
        print(f"  {key:44s} {a:>12.4g} -> {b:>12.4g}  "
              f"x{ratio:.3f} ({'^' if hb else 'v'} better){mark}")
    if out["regressions"]:
        print(f"{len(out['regressions'])} regression(s): "
              f"{', '.join(out['regressions'])}")
    else:
        print("no headline regressions")
    return out


def main() -> None:
    if "--compare" in sys.argv:
        i = sys.argv.index("--compare")
        args = [a for a in sys.argv[i + 1:]
                if a != "--allow-cross-device"]
        bench_dir = args[0] if args else str(Path(__file__).parent)
        try:
            result = bench_compare(
                bench_dir,
                allow_cross_device="--allow-cross-device" in sys.argv)
        except ValueError as e:
            # exit 2, NOT 1: 1 means "regressions found" — a refusal
            # (cross-device records, unparseable trail) is a usage/
            # data problem and must not read as a perf regression
            print(f"bench --compare: {e}", file=sys.stderr)
            sys.exit(2)
        sys.exit(1 if result["regressions"] else 0)
    import jax

    dev = jax.devices()[0]
    on_accelerator = dev.platform != "cpu"

    vgg = bench_vgg_throughput(on_accelerator)
    remeasure = vgg.pop("remeasure")
    cached_pps = bench_vgg_cached_throughput(on_accelerator)
    mobile_pps, mobile_tfs = bench_backbone_throughput(
        "mobilenet_v2", on_accelerator)
    dense_pps, dense_tfs = bench_backbone_throughput(
        "densenet201", on_accelerator)
    fused = bench_backbone_fused(on_accelerator)
    fed_round_s = bench_fed_round(on_accelerator)
    fed_round_32_s = bench_fed_round(on_accelerator, n_clients=32)
    secure_round_s = bench_secure_round(on_accelerator)
    ring = bench_ring_attention(on_accelerator)
    ring.update(bench_zigzag_schedule(on_accelerator))
    ring.update(bench_flash_train(on_accelerator))
    ring.update(bench_attention_model_step(on_accelerator))
    ring.update(bench_lm_decode(on_accelerator))
    ring.update(bench_lm_sharded(on_accelerator))
    ring.update(bench_serving(on_accelerator))
    ring.update(bench_serving_shared_prefix(on_accelerator))
    ring.update(bench_serving_speculative(on_accelerator))
    ring.update(bench_serving_paged_kv(on_accelerator))
    ring.update(bench_serving_cluster(on_accelerator))
    ring.update(bench_serving_elastic(on_accelerator))
    ring.update(bench_cluster_watchdog(on_accelerator))
    ring.update(bench_serving_multitenant(on_accelerator))
    ring.update(bench_serving_resilience(on_accelerator))
    ring.update(bench_tracer_overhead(on_accelerator))
    ring.update(bench_profile_overhead(on_accelerator))
    ring.update(bench_federated_robustness(on_accelerator))
    ring.update(bench_federated_scale(on_accelerator))
    ring.update(bench_checkpoint_rollout(on_accelerator))
    if on_accelerator:
        # second headline sample, minutes after the first (the shared
        # chip's load drifts on that timescale; back-to-back windows
        # can all land in one slow stretch) — keep the best
        again = remeasure()
        if (again["patches_per_sec_per_chip"]
                > vgg["patches_per_sec_per_chip"]):
            vgg = again

    # ---- MFU self-check (only meaningful on a known accelerator) -------
    mfu = None
    peak = _peak_tflops(dev) if on_accelerator else None
    if vgg["step_tflops"] is None:
        # missing cost data is a degraded mode, not an MFU violation
        print("WARNING: compiled.cost_analysis() returned no FLOPs; "
              "skipping the MFU self-check", file=sys.stderr)
        peak = None
    if peak is not None:
        mfu = vgg["step_tflops"] / peak
        analytic = analytic_vgg16_step_flops()
        ratio = vgg["flops_per_patch"] / analytic
        if not (0.4 < ratio < 2.5):
            print(f"FATAL: XLA cost-analysis FLOPs/patch "
                  f"{vgg['flops_per_patch']:.3e} disagrees with analytic "
                  f"{analytic:.3e} (ratio {ratio:.2f}) — measurement or "
                  f"model changed", file=sys.stderr)
            sys.exit(1)
        if not (0.0 < mfu <= 1.0):
            print(f"FATAL: MFU {mfu:.2%} outside (0, 100%] — wall-clock "
                  f"is not measuring device execution (round-1 bug class) "
                  f"or peak table wrong for {dev.device_kind!r}",
                  file=sys.stderr)
            sys.exit(1)

    value = vgg["patches_per_sec_per_chip"]
    baseline_path = Path(__file__).parent / "BENCH_BASELINE.json"
    vs = 1.0
    if baseline_path.exists():
        base = json.loads(baseline_path.read_text()).get("value")
        if base:
            vs = value / base
    out = {
        "metric": "IDC patches/sec/chip (VGG16 fine-tune, bf16)",
        "value": round(value, 2),
        "unit": "patches/sec/chip",
        "vs_baseline": round(vs, 4),
        # median + raw windows of the KEPT sample, so drift-band
        # excursions are distinguishable from real regressions
        "median_value": round(vgg["median_patches_per_sec_per_chip"], 2),
        "window_s": vgg["window_s"],
        "batch_per_chip": vgg["batch_per_chip"],
        "step_tflops": (round(vgg["step_tflops"], 2)
                        if vgg["step_tflops"] is not None else None),
        "peak_tflops": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "cached_fine_tune_patches_per_sec_per_chip": round(cached_pps, 2),
        # the reference's other two DP backbones (VERDICT r4 #1): both
        # HBM-bound; see BASELINE.md for the roofline ceiling accounts
        "mobile_patches_per_sec_per_chip": round(mobile_pps, 2),
        "mobile_mfu": (round(mobile_tfs / peak, 4)
                       if peak and mobile_tfs else None),
        "dense_patches_per_sec_per_chip": round(dense_pps, 2),
        "dense_mfu": (round(dense_tfs / peak, 4)
                      if peak and dense_tfs else None),
        # ISSUE 16: fused Pallas backbone variants vs their baselines
        **fused,
        "fed_round_s": round(fed_round_s, 4),
        "fed_round_32_s": round(fed_round_32_s, 4),
        "secure_round_s": round(secure_round_s, 4),
        **ring,
        "device_kind": dev.device_kind,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
