"""Serve many users from one LM with the continuous-batching engine —
the multi-user half of the serving story example 07 started.

`python examples/08_serve_continuous_batching.py` runs on a virtual
8-device CPU pod. A trained counting-task LM serves a burst of
concurrent requests through `serve.LMServer`: fixed decode slots, one
fused masked window per scheduler tick (every busy slot decodes one
batch row; finished slots emit pad and append nothing), FIFO admission
with backpressure, and slot recycling the moment a request hits its
stop token or budget. Every request's output is bit-identical to a
serial `Generator` call — batching changes the throughput, not the
tokens.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.models.lm import Generator, attention_lm, next_token_loss
from idc_models_tpu.serve import LMServer, Request, poisson_trace
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    shard_batch,
)

VOCAB, SEQ = 11, 32
mesh = meshlib.data_seq_mesh(4, 2)
model = attention_lm(VOCAB, SEQ, embed_dim=32, num_heads=2, mlp_dim=64,
                     num_blocks=2, mesh=mesh)

# train succ() (next = tok + 1 mod VOCAB) exactly as in example 07
opt = rmsprop(3e-3)
variables = model.init(jax.random.key(0))
state = TrainState(step=jnp.zeros((), jnp.int32), params=variables.params,
                   model_state=variables.state,
                   opt_state=opt.init(variables.params))
step = jit_data_parallel(make_train_step(model, opt, next_token_loss),
                         mesh, axis="data")
state = replicate(mesh, state)
rng, key = np.random.default_rng(1), jax.random.key(2)
for i in range(150):
    starts = rng.integers(0, VOCAB, (32, 1))
    seqs = jnp.asarray((starts + np.arange(SEQ)) % VOCAB, jnp.int32)
    bx = shard_batch(mesh, seqs, axis="data")
    key, sub = jax.random.split(key)
    state, m = step(state, bx, bx, sub)
print(f"trained 150 steps: loss={float(m['loss']):.4f}")
params = jax.device_get(state.params)

# a server with 3 decode slots serving 8 concurrent requests: requests
# queue FIFO, prefill into free slots, and decode TOGETHER in fused
# masked windows; each slot recycles the moment its request finishes
server = LMServer(params, embed_dim=32, num_heads=2, num_blocks=2,
                  t_max=SEQ, n_slots=3, window=4,
                  cache_dtype=jnp.float32)
requests = [Request(id=f"user{i}", prompt=tuple((i + j) % VOCAB
                                                for j in range(3)),
                    max_new_tokens=6 + i % 4)
            for i in range(8)]
results = server.run([(0.0, r) for r in requests])
assert all(r.status == "ok" for r in results)

# every stream continues its counting prompt — and is bit-identical to
# a serial Generator call with the same prompt
gen = Generator(params, embed_dim=32, num_heads=2, num_blocks=2,
                t_max=SEQ, cache_dtype=jnp.float32)
for r in requests:
    got = server.poll(r.id)
    want = [(r.prompt[-1] + 1 + j) % VOCAB
            for j in range(r.max_new_tokens)]
    assert got.tokens == want, (r.id, got.tokens, want)
    serial = gen(jnp.asarray([r.prompt], jnp.int32),
                 r.max_new_tokens).tolist()[0][len(r.prompt):]
    assert got.tokens == serial
print(f"served {len(results)} concurrent users on 3 slots, every stream "
      f"= its serial generation, bit for bit")

s = server.summary()
print(f"throughput {s['serve_tokens_per_sec']} tok/s, "
      f"TTFT p50 {s['serve_ttft_ms_p50']} ms, "
      f"slot occupancy {s['serve_slot_occupancy']}")

# a Poisson arrival trace (the standard serving-benchmark workload)
# through a fresh server — zero recompilation: the programs were
# compiled once above and live in a process-wide cache
server2 = LMServer(params, embed_dim=32, num_heads=2, num_blocks=2,
                   t_max=SEQ, n_slots=3, window=4,
                   cache_dtype=jnp.float32)
sizes = server2.engine.cache_sizes()
trace = poisson_trace(6, rate_per_s=200.0, vocab=VOCAB, t_max=SEQ, seed=7)
server2.run(trace, realtime=True)
assert server2.engine.cache_sizes() == sizes
print("Poisson trace served with zero new compilations:", sizes)
