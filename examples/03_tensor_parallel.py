"""Tensor parallelism: the same training code on a 2-D ("data","model")
mesh — weights channel-sharded over "model", batch over "data", XLA
(GSPMD) inserts the collectives. Beyond-reference capability (tp.py).

`python examples/03_tensor_parallel.py` runs on a virtual 8-device CPU
pod as a 2x4 DP x TP mesh.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax

from idc_models_tpu import tp
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import fit, create_train_state, predict, rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

mesh = tp.dp_tp_mesh(model=4)     # 2-way data x 4-way tensor parallel
model = small_cnn(10, 3, 1)
opt = rmsprop(1e-3)
state = create_train_state(model, opt, jax.random.key(0))

images, labels = synthetic.make_idc_like(128, size=10, seed=0)
train = ArrayDataset(images[:96], labels[:96])
val = ArrayDataset(images[96:], labels[96:])

state, history = fit(model, opt, binary_cross_entropy, state, train, val,
                     mesh, epochs=3, batch_size=16, verbose=True)

probs = jax.nn.sigmoid(predict(model, state, val.images, mesh))
print("conv kernel sharding:",
      state.params["conv1"]["kernel"].sharding.spec)
print("first 5 malignancy probabilities:", probs[:5].reshape(-1))
