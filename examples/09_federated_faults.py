"""Fault-tolerant federated training (docs/ROBUSTNESS.md): inject a
deterministic Byzantine fault plan, watch the weighted mean degrade, and
survive it with the trimmed-mean aggregator + the self-healing driver.

`python examples/09_federated_faults.py` runs on a virtual 8-device CPU
pod; the same code drives a TPU pod with k clients per core.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import numpy as np

from idc_models_tpu import faults
from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import pad_clients, partition_clients
from idc_models_tpu.federated import (
    DriverConfig, get_aggregator, initialize_server, make_fedavg_round,
    make_federated_eval, run_rounds,
)
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N_CLIENTS, N_BYZANTINE, ROUNDS = 10, 3, 2
images, labels = synthetic.make_idc_like(N_CLIENTS * 16, size=10, seed=0)
client_imgs, client_labels = partition_clients(
    ArrayDataset(images, labels), N_CLIENTS, iid=True, seed=0)
weights = np.full((N_CLIENTS,), client_imgs.shape[1], np.float32)
# 10 clients on an 8-device mesh: pad with inert weight-0 dummies
client_imgs, client_labels, weights = pad_clients(
    client_imgs, client_labels, weights, multiple=8)

mesh = meshlib.client_mesh(8)
model = small_cnn(10, 3, 1)
eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)

# 3 of 10 clients run the sign-flip x1000 attack — finite updates, so
# non-finite detection cannot see them. Seeded: replays bit-identically.
plan = faults.FaultPlan.byzantine(N_CLIENTS, N_BYZANTINE,
                                  kind="sign_flip", scale=1000.0, seed=7)
print(f"fault plan: {plan}")

def build_round(agg):
    return make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                             mesh, local_epochs=1, batch_size=16,
                             aggregator=agg, faults=plan)


def drive(round_fn, config):
    # the self-healing driver: divergence rollback, timeout retry with
    # a reseeded client subset, bounded attempts, health events
    server = initialize_server(model, jax.random.key(0))
    result = run_rounds(round_fn, server, client_imgs, client_labels,
                        weights, config=config, seed=1)
    em = eval_fn(result.server, client_imgs, client_labels, weights)
    return result, float(em["loss"])


# 1. The weighted mean under attack: the driver's divergence detection
#    (loss-spike rollback) refuses the poisoned trajectory outright.
from idc_models_tpu.federated import RoundFailure

try:
    drive(build_round(None), DriverConfig(rounds=ROUNDS))
except RoundFailure as e:
    print(f"weighted mean: driver REFUSED the poisoned trajectory "
          f"({e})")

# 2. Detection off (loss_spike_ratio=None): the mean 'completes' — onto
#    a server the attackers steered far from descent.
_, mean_loss = drive(build_round(None),
                     DriverConfig(rounds=ROUNDS, loss_spike_ratio=None))
print(f"weighted mean, detection off: eval_loss={mean_loss:.4f}")

# 3. Trimmed mean with trim >= attacker count: completes healthily
#    under the default driver config, attackers trimmed every round.
result, trim_loss = drive(
    build_round(get_aggregator("trimmed_mean", trim=N_BYZANTINE)),
    DriverConfig(rounds=ROUNDS))
trimmed = result.history[-1].get("clients_trimmed", 0)
print(f"trimmed mean:  eval_loss={trim_loss:.4f} "
      f"(suspected attackers trimmed: {int(trimmed)})")
assert trim_loss < mean_loss
print("the robust aggregate stays near a sane binary cross entropy; "
      "the mean is steered away by the attackers")
