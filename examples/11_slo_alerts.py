"""SLO burn-rate alerting on the federated driver: a straggler-injected
run trips an alert, the clean baseline does not.

`python examples/11_slo_alerts.py` runs on a virtual 8-device CPU pod.
Two 12-round FedAvg runs share one model and jit cache:

1. **clean** — every round completes at its natural pace. The declared
   SLOs (p80 of round wall-clock <= 0.35 s, round-failure rate <= 20%)
   hold; the engine stays silent.
2. **straggler-injected** — from round 5 on, the round function sleeps
   0.5 s before dispatching (a straggling cohort holding up the
   synchronous round, injected at the wall-clock level). The
   round-latency SLO's error budget burns at ~3x the allowed rate in
   BOTH the short and long windows, so the engine fires a `slo_alert`
   (and would stream it to the run's jsonl next to round_health).

The same `SLOEngine` gauges (`slo_burn_rate{slo,window}`,
`slo_breached{slo}`) are live on `GET /metrics` whenever a
`MetricsExporter` is armed — see docs/OBSERVABILITY.md.
"""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import numpy as np

from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import (
    DriverConfig, initialize_server, make_fedavg_round, run_rounds,
)
from idc_models_tpu.models import small_cnn
from idc_models_tpu.observe import SLO, SLOEngine
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

ROUNDS = 12
STRAGGLE_FROM, STRAGGLE_S = 5, 0.5

mesh = meshlib.client_mesh(8)
model = small_cnn(10, 3, 1)
imgs, labels = synthetic.make_idc_like(8 * 64, size=10, seed=0)
ci, cl = partition_clients(ArrayDataset(imgs, labels), 8, iid=True,
                           seed=0)
w = np.full((8,), 64, np.float32)
round_fn = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                             mesh, local_epochs=1, batch_size=16)


def make_slo_engine():
    # p80 (not p95): the chronologically first round pays every XLA
    # compile in its wall time, and a 20% error budget absorbs that
    # plus machine-phase noise without masking a real straggler wave
    return SLOEngine(
        [SLO.latency("round_seconds", threshold_s=0.35, percentile=80.0),
         SLO.rate("round_failure_rate", budget=0.2)],
        short_window_s=60.0, long_window_s=300.0, min_samples=6)


def run(name, straggle):
    server = initialize_server(model, jax.random.key(0))
    calls = {"n": 0}

    def wrapped(server_, images, labels_, weights, rng):
        calls["n"] += 1
        if straggle and calls["n"] > STRAGGLE_FROM:
            time.sleep(STRAGGLE_S)      # the injected straggler wave
        return round_fn(server_, images, labels_, weights, rng)

    slo = make_slo_engine()
    result = run_rounds(wrapped, server, ci, cl, w,
                        config=DriverConfig(rounds=ROUNDS), seed=1,
                        slo=slo)
    secs = [e["seconds"] for e in result.events]
    print(f"{name}: {len(result.history)} rounds, wall/round "
          f"p50={sorted(secs)[len(secs) // 2]:.3f}s "
          f"max={max(secs):.3f}s -> {len(slo.alerts)} alert(s)")
    for a in slo.alerts:
        print(f"  slo_alert {a['slo']}: burn short={a['burn_short']}x "
              f"long={a['burn_long']}x of the {a['budget']:.0%} error "
              f"budget (threshold {a['burn_threshold']}x)")
    return slo


clean = run("clean baseline", straggle=False)
straggled = run("straggler-injected", straggle=True)

assert clean.alerts == [], "the clean run must stay silent"
assert any(a["slo"] == "round_seconds" for a in straggled.alerts), \
    "the straggler wave must trip the round-latency SLO"
assert straggled.breached("round_seconds")
print("OK: alert under injected stragglers, silence on the clean run")
