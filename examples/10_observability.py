"""See where a serve run spends its time: the ISSUE-5 observability
layer end to end — span tracer, metrics registry, Chrome trace export,
and the offline stats rollup.

`python examples/10_observability.py` runs on a virtual 8-device CPU
pod. A small LM serves a burst of requests through the
continuous-batching engine with a tracer armed; the run produces:

- `/tmp/idc_obs_example/trace.json` — Chrome trace-event JSON. Open it
  in Perfetto (https://ui.perfetto.dev) or chrome://tracing and you see
  the scheduler's cycles: `serve.tick` spans with `serve.admit` (and
  the chunked `serve.prefill_chunk` dispatches under it),
  `serve.collect` (blocking on the in-flight window's tokens) and
  `serve.window` (the next fused dispatch) nested inside.
- the same spans as a jsonl file, summarized by `observe.stats` — the
  library form of the `python -m idc_models_tpu stats <file>` verb.
- the process-wide metrics registry in Prometheus text exposition.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import jax.numpy as jnp

from idc_models_tpu.models.lm import attention_lm
from idc_models_tpu.observe import REGISTRY, format_summary, \
    summarize_jsonl, trace
from idc_models_tpu.serve import LMServer, poisson_trace

VOCAB, T_MAX = 11, 32
out_dir = pathlib.Path("/tmp/idc_obs_example")

mesh = meshlib.seq_mesh(1)
model = attention_lm(VOCAB, T_MAX, embed_dim=32, num_heads=2,
                     mlp_dim=64, num_blocks=2, mesh=mesh)
params = model.init(jax.random.key(0)).params

# arm the tracer for the serve run; both exports land on exit
with trace.tracing(chrome_path=out_dir / "trace.json",
                   jsonl_path=out_dir / "spans.jsonl"):
    server = LMServer(params, embed_dim=32, num_heads=2, num_blocks=2,
                      t_max=T_MAX, n_slots=2, window=4, mesh=mesh,
                      cache_dtype=jnp.float32, prefill_chunk=8)
    results = server.run(poisson_trace(
        8, rate_per_s=1e9, vocab=VOCAB, t_max=T_MAX,
        prompt_lens=(4, 12), budgets=(4, 8), seed=0))

assert all(r.status == "ok" for r in results)
print(f"served {len(results)} requests; trace at {out_dir}/trace.json "
      f"(open in https://ui.perfetto.dev)")

# the offline rollup the `stats` CLI verb prints, over the span export
print()
print(format_summary(summarize_jsonl(out_dir / "spans.jsonl")))

# the process-wide registry, Prometheus-ready
print()
print("metrics registry (Prometheus text exposition):")
text = REGISTRY.prometheus_text()
print("\n".join(l for l in text.splitlines()
                if l.startswith(("#", "serve_"))
                and "_bucket" not in l))
