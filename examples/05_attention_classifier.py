"""Train a transformer classifier with sequence-parallel attention on a
2-D ("data", "seq") mesh — DP x SP composed, driven by the SAME train
step every CNN in this framework uses.

`python examples/05_attention_classifier.py` runs on a virtual 8-device
CPU pod (batch sharded 2 ways, every self-attention a 4-device ring);
on a TPU pod the identical code shards batch over DCN/ICI rows and
rotates K/V blocks over ICI within each ring.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.data import synthetic
from idc_models_tpu.models.attention import attention_classifier
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    shard_batch,
)
from idc_models_tpu.train.losses import binary_cross_entropy

SEQ, FEAT = 32, 8
mesh = meshlib.data_seq_mesh(4, 2)           # ("data": 2, "seq": 4)
model = attention_classifier(SEQ, FEAT, embed_dim=32, num_heads=2,
                             mlp_dim=64, num_blocks=2, num_outputs=1,
                             mesh=mesh, causal=True)

opt = rmsprop(1e-3)
variables = model.init(jax.random.key(0))
state = TrainState(step=jnp.zeros((), jnp.int32), params=variables.params,
                   model_state=variables.state,
                   opt_state=opt.init(variables.params))
step = jit_data_parallel(make_train_step(model, opt, binary_cross_entropy),
                         mesh, axis="data")
state = replicate(mesh, state)

# position-sensitive task: label = marker in the late half — unsolvable
# without attention moving positional information into the pooled features
x, y = synthetic.make_sequence_task(512, SEQ, FEAT, seed=5)
key = jax.random.key(1)
sel_rng = np.random.default_rng(7)
for i in range(150):
    sel = sel_rng.integers(0, len(x), 64)
    key, sub = jax.random.split(key)
    state, m = step(state, *shard_batch(mesh, x[sel], y[sel], axis="data"),
                    sub)
    if i % 30 == 0 or i == 149:
        print(f"step {i:3d}  loss {float(m['loss']):.3f}  "
              f"acc {float(m['accuracy']):.3f}")

assert float(m["accuracy"]) > 0.8, "should be well above chance by now"
print("OK: ring-attention transformer trained on a (data, seq) mesh")
