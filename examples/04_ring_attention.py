"""Long-context ring attention as a library: a sequence 8x longer than
any single device holds, attended EXACTLY over the "seq" mesh axis.

`python examples/04_ring_attention.py` runs on a virtual 8-device CPU
pod; the same code on a TPU pod keeps O(T/n) activations per chip and
rotates K/V blocks over ICI, one ppermute hop per ring step.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.ring_attention import full_attention, make_ring_attention

B, T, H, D = 2, 512, 4, 32        # T is sharded 8 ways: 64 per device
mesh = meshlib.seq_mesh()
rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.float32)
           for _ in range(3))

# place the sequence shards: no device ever holds the full T
seq_sharding = meshlib.sharding(mesh, None, meshlib.SEQ_AXIS)
q, k, v = (jax.device_put(x, seq_sharding) for x in (q, k, v))

attn = make_ring_attention(mesh, causal=True)
out = attn(q, k, v)
print("ring attention out:", out.shape, "sharded over", out.sharding.spec)

# exact, not approximate: gather and compare against full attention
ref = full_attention(jax.device_get(q), jax.device_get(k),
                     jax.device_get(v), causal=True)
err = float(jnp.max(jnp.abs(out - ref)))
print(f"max |ring - full| = {err:.2e}")
assert err < 1e-5
