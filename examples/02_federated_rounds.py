"""FedAvg as a library: partition a dataset into non-IID clients, run
rounds over the "client" mesh axis, evaluate on held-out clients
(fed_model.py parity — TFF replaced by one jitted shard_map program).

`python examples/02_federated_rounds.py` runs on a virtual 8-device CPU
pod; the same code drives a TPU pod with one client per core (or k per
core — client count is independent of chip count).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import numpy as np

from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.partition import partition_clients
from idc_models_tpu.federated import (
    initialize_server, make_fedavg_round, make_federated_eval,
)
from idc_models_tpu.models import small_cnn
from idc_models_tpu.train import rmsprop
from idc_models_tpu.train.losses import binary_cross_entropy

N_CLIENTS = 8
images, labels = synthetic.make_idc_like(N_CLIENTS * 64, size=10, seed=0)
client_imgs, client_labels = partition_clients(
    ArrayDataset(images, labels), N_CLIENTS, iid=False, seed=0)
weights = np.full((N_CLIENTS,), client_imgs.shape[1], np.float32)

mesh = meshlib.client_mesh(N_CLIENTS)
model = small_cnn(10, 3, 1)
server = initialize_server(model, jax.random.key(0))
round_fn = make_fedavg_round(model, rmsprop(1e-3), binary_cross_entropy,
                             mesh, local_epochs=2, batch_size=16)
eval_fn = make_federated_eval(model, binary_cross_entropy, mesh)

for r in range(3):
    server, m = round_fn(server, client_imgs, client_labels, weights,
                         jax.random.fold_in(jax.random.key(1), r))
    em = eval_fn(server, client_imgs, client_labels, weights)
    print(f"round {r}: train_loss={float(m['loss']):.4f} "
          f"eval_acc={float(em['accuracy']):.4f} "
          f"dropped={int(m['clients_dropped'])}")
