"""Train a causal LM through the sequence-parallel ring, then generate
from it through the ring-sharded KV-cache decoder — one parameter tree,
both directions.

`python examples/07_lm_train_and_generate.py` runs on a virtual
8-device CPU pod ("data" x "seq" mesh); the same code on a TPU pod
trains with ring attention over ICI and serves with two collectives per
decoded token.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.models.lm import (
    attention_lm, generate, next_token_loss,
)
from idc_models_tpu.train import (
    TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    shard_batch,
)

VOCAB, SEQ = 11, 32
mesh = meshlib.data_seq_mesh(4, 2)        # batch x ring
model = attention_lm(VOCAB, SEQ, embed_dim=32, num_heads=2, mlp_dim=64,
                     num_blocks=2, mesh=mesh)

opt = rmsprop(3e-3)
variables = model.init(jax.random.key(0))
state = TrainState(step=jnp.zeros((), jnp.int32), params=variables.params,
                   model_state=variables.state,
                   opt_state=opt.init(variables.params))
step = jit_data_parallel(make_train_step(model, opt, next_token_loss),
                         mesh, axis="data")
state = replicate(mesh, state)

# the task: sequences count upward mod VOCAB; the LM must learn succ()
rng, key = np.random.default_rng(1), jax.random.key(2)
for i in range(150):
    starts = rng.integers(0, VOCAB, (32, 1))
    seqs = jnp.asarray((starts + np.arange(SEQ)) % VOCAB, jnp.int32)
    bx = shard_batch(mesh, seqs, axis="data")
    key, sub = jax.random.split(key)
    state, m = step(state, bx, bx, sub)
print(f"trained 150 steps: loss={float(m['loss']):.4f} "
      f"next-token accuracy={float(m['accuracy']):.3f}")

prompt = jnp.asarray([[7, 8, 9]], jnp.int32)
out = generate(jax.device_get(state.params), prompt, 8, embed_dim=32,
               num_heads=2, num_blocks=2, t_max=SEQ,
               cache_dtype=jnp.float32)
print("prompt", prompt.tolist()[0], "->", out.tolist()[0])
assert out.tolist()[0] == [(7 + i) % VOCAB for i in range(11)]
print("generation matches the learned successor pattern")

# hot serving: a Generator holds the compiled programs — one ring
# prefill dispatch + ONE fused scan dispatch per request, and repeated
# requests (or a fresh same-shape checkpoint) recompile nothing
from idc_models_tpu.models.lm import Generator

gen = Generator(jax.device_get(state.params), embed_dim=32, num_heads=2,
                num_blocks=2, t_max=SEQ, cache_dtype=jnp.float32)
for start in (3, 5):
    p = jnp.asarray([[start, start + 1, start + 2]], jnp.int32)
    toks = gen(p, 8).tolist()[0]
    assert toks == [(start + i) % VOCAB for i in range(11)]
print("Generator served 2 requests, compiled once:", gen.cache_sizes())
