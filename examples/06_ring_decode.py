"""Serving the long context the ring trained: KV-cache decode with the
cache sharded over the SAME "seq" mesh axis as training — device i owns
cache slots [i*T/n, (i+1)*T/n) and never sees the rest.

`python examples/06_ring_decode.py` runs on a virtual 8-device CPU pod;
on a TPU pod each decode step is two ICI collectives (pmax + psum of
the per-shard softmax partials) and an owner-local cache write.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax.numpy as jnp
import numpy as np

from idc_models_tpu.ring_attention import full_attention
from idc_models_tpu.ring_decode import make_ring_decode, prefill

B, T_MAX, H, D = 2, 256, 4, 32    # cache sharded 8 ways: 32 slots/device
P_LEN = 192                        # prompt tokens, placed via prefill
mesh = meshlib.seq_mesh()
rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.normal(0, 1, (B, T_MAX, H, D)), jnp.float32)
           for _ in range(3))

# 1. prefill: the prompt's K/V drops straight into the ring layout
kc, vc = prefill(mesh, k[:, :P_LEN], v[:, :P_LEN], T_MAX,
                 dtype=jnp.float32)
print(f"cache: {kc.shape} sharded over {kc.sharding.spec}")

# 2. decode the remaining tokens one at a time (caches donated in place)
step = make_ring_decode(mesh)
outs = []
for pos in range(P_LEN, T_MAX):
    tok = slice(pos, pos + 1)
    out, kc, vc = step(kc, vc, q[:, tok], k[:, tok], v[:, tok], pos)
    outs.append(out)
decoded = jnp.concatenate(outs, axis=1)

# exact: each step == the matching row of full causal attention
ref = full_attention(q, k, v, causal=True)[:, P_LEN:]
err = float(jnp.max(jnp.abs(decoded - ref)))
print(f"decoded {T_MAX - P_LEN} tokens after a {P_LEN}-token prefill; "
      f"max |err| vs full causal attention = {err:.2e}")
assert err < 1e-4
