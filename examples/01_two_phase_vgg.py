"""The flagship workload as library calls: two-phase VGG16 transfer
learning (dist_model_tf_vgg.py parity) on a data-parallel mesh.

Runs anywhere: `python examples/01_two_phase_vgg.py` uses a virtual
8-device CPU pod and synthetic IDC-like data; point `load_directory` at
a real `<root>/<label>/*.png` tree and drop `force_cpu_pod` on a TPU.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from idc_models_tpu import mesh as meshlib

meshlib.force_cpu_pod(8)          # delete this line on real TPU hardware

import jax.numpy as jnp

from idc_models_tpu.data import synthetic
from idc_models_tpu.data.idc import ArrayDataset, train_val_test_split
from idc_models_tpu.train import TwoPhaseConfig, two_phase_fit

images, labels = synthetic.make_idc_like(256, size=50, seed=0)
ds = ArrayDataset(images, labels)          # or: load_directory(root)
train, val, test = train_val_test_split(ds, seed=0)

result = two_phase_fit(
    "vgg16", 1, train, val, meshlib.data_mesh(),
    TwoPhaseConfig(lr=1e-3, epochs=1, fine_tune_epochs=1, batch_size=32,
                   compute_dtype=jnp.float32),
    # pretrained_weights="vgg16_imagenet.npz",   # convert-weights output
)
print(f"pre-train {result.pretrain_seconds:.1f}s, "
      f"fine-tune {result.fine_tune_seconds:.1f}s, "
      f"final val acc {result.history_fine['val_accuracy'][-1]:.3f}")
