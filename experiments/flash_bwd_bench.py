"""Training-step (fwd+bwd) timing for the ring block impls on the chip.

Companion to ring_attention_bench.py (forward-only): this times
`grad(sum(ring(q,k,v)^2))` — the full forward + backward — ring of 1
(t_local == T) so the single chip runs the whole schedule. The pallas
path now uses the blockwise flash backward (no [T, T] HBM tensor in
either direction); the jnp path's autodiff rematerializes the f32
score tensor, which at T=16384 is 8.6 GB (B=1, H=8) and may not fit
alongside its backward — an OOM there is itself the datapoint.

Methodology as ring_attention_bench.py: chained calls (dq, renormed,
feeds back as q), best-of-3 windows, host fetch of a dependent scalar.
Run: python experiments/flash_bwd_bench.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_attention import make_ring_attention

B, H, D = 1, 8, 64
ITERS = 6


def main():
    mesh = meshlib.seq_mesh(1)
    rows = []
    for T in (4096, 8192, 16384):
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.normal(0, 1, (B, T, H, D)),
                               jnp.bfloat16) for _ in range(3))
        row = {"t_local": T}
        for impl in ("jnp", "pallas"):
            ring = make_ring_attention(mesh, causal=True, block_impl=impl)
            gfn = jax.jit(jax.grad(
                lambda a, b, c: jnp.sum(ring(a, b, c)
                                        .astype(jnp.float32) ** 2)))
            try:
                dq = gfn(q, k, v)
                _ = float(jnp.sum(dq.astype(jnp.float32)))
                best = 1e9
                for _ in range(3):
                    t0 = time.perf_counter()
                    a = q
                    for _ in range(ITERS):
                        dq = gfn(a, k, v)
                        scl = jax.lax.rsqrt(
                            jnp.mean(dq.astype(jnp.float32) ** 2) + 1e-9)
                        a = (dq.astype(jnp.float32) * scl
                             ).astype(jnp.bfloat16)
                    _ = float(jnp.sum(a.astype(jnp.float32)))
                    best = min(best, (time.perf_counter() - t0) / ITERS)
                row[impl] = best
            except Exception as e:  # noqa: BLE001 — OOM is a datapoint
                row[impl] = None
                row[f"{impl}_error"] = type(e).__name__
        rows.append(row)
        jn, pa = row.get("jnp"), row.get("pallas")
        msg = (f"t_local={T}: fwd+bwd jnp "
               f"{jn*1e3:.1f} ms" if jn else f"t_local={T}: fwd+bwd jnp "
               f"{row.get('jnp_error')}")
        msg += (f"  pallas {pa*1e3:.1f} ms" if pa
                else f"  pallas {row.get('pallas_error')}")
        if jn and pa:
            msg += f"  speedup {jn/pa:.2f}x"
        print(msg, flush=True)
    out = pathlib.Path(__file__).parent / "flash_bwd_bench.jsonl"
    with out.open("w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
