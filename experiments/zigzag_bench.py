"""Zigzag vs contiguous causal ring schedule, measured on the real chip.

One chip cannot host a real n-device ring, so this measures what the
layout actually changes: the per-device COMPUTE schedule. In SPMD
lockstep every device executes the same kernel calls per ring step and
the wall clock is the per-step max, so one device's schedule timed on
one chip is the ring's compute time (the ppermute hops, which both
layouts issue identically — n-1 neighbor hops of the same bytes — are
excluded for both).

  contiguous: n full-block causal flash updates (t_local x t_local);
              ~half land on fully masked blocks but are paid anyway.
  zigzag:     3 quarter attends (2 stripe diagonals + 1 full) plus
              2 unmasked quarter attends per remaining hop
              = (2n+1)/(4n) of the contiguous score work.

Methodology follows ring_attention_bench.py: chained calls per timing
window (output feeds back as q) to amortize the tunneled runtime's
~90 ms dispatch overhead, best-of-3 windows, and the timing ends with a
host fetch of a scalar that data-depends on the result (the axon
runtime's block_until_ready can return early; see BASELINE.md).
Run: python experiments/zigzag_bench.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.ops import flash_block_kernel as fbk

B, H, D = 1, 8, 64
N = 8          # emulated ring size
ITERS = 6
ME = N - 1     # any device works: the schedule length is identical


def make_schedule(layout, t_local, *, interpret=False):
    """One device's compute for a full causal ring pass, as fn(q, kv)
    with kv [N, 2, B, t_local, H, D] stacking the visiting blocks in
    visit order."""
    scale = D ** -0.5
    diag = fbk.make_flash_block_update(scale=scale, causal=True,
                                       interpret=interpret)
    full = fbk.make_flash_block_update(scale=scale, causal=False,
                                       interpret=interpret)
    th = t_local // 2

    def contiguous(q, kv):
        m = jnp.full((B, H, t_local), -1e30, jnp.float32)
        l = jnp.zeros((B, H, t_local), jnp.float32)
        acc = jnp.zeros((B, t_local, H, D), jnp.float32)
        for s in range(N):
            c = (ME - s) % N
            offs = jnp.asarray([ME * t_local, c * t_local], jnp.int32)
            m, l, acc = diag(q, kv[s, 0], kv[s, 1], m, l, acc, offs)
        return acc / jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-37)

    def zigzag(q, kv):
        m = jnp.full((B, H, t_local), -1e30, jnp.float32)
        l = jnp.zeros((B, H, t_local), jnp.float32)
        acc = jnp.zeros((B, t_local, H, D), jnp.float32)
        lo_off, hi_off = ME * th, (2 * N - 1 - ME) * th

        def quarter(m, l, acc, row0, qh, kh, vh, qo, ko, is_diag):
            ms, ls = m[:, :, row0:row0 + th], l[:, :, row0:row0 + th]
            accs = acc[:, row0:row0 + th]
            upd = diag if is_diag else full
            offs = jnp.asarray([qo, ko], jnp.int32)
            ms, ls, accs = upd(qh, kh, vh, ms, ls, accs, offs)
            return (m.at[:, :, row0:row0 + th].set(ms),
                    l.at[:, :, row0:row0 + th].set(ls),
                    acc.at[:, row0:row0 + th].set(accs))

        q_lo, q_hi = q[:, :th], q[:, th:]
        for s in range(N):
            k_lo, k_hi = kv[s, 0, :, :th], kv[s, 0, :, th:]
            v_lo, v_hi = kv[s, 1, :, :th], kv[s, 1, :, th:]
            c = (ME - s) % N
            c_lo, c_hi = c * th, (2 * N - 1 - c) * th
            if s == 0:
                m, l, acc = quarter(m, l, acc, 0, q_lo, k_lo, v_lo,
                                    lo_off, lo_off, True)
                m, l, acc = quarter(m, l, acc, th, q_hi, k_hi, v_hi,
                                    hi_off, hi_off, True)
                m, l, acc = quarter(m, l, acc, th, q_hi, k_lo, v_lo,
                                    hi_off, lo_off, False)
            else:
                m, l, acc = quarter(m, l, acc, th, q_hi, k_lo, v_lo,
                                    hi_off, c_lo, False)
                if c < ME:
                    m, l, acc = quarter(m, l, acc, 0, q_lo, k_lo, v_lo,
                                        lo_off, c_lo, False)
                else:
                    m, l, acc = quarter(m, l, acc, th, q_hi, k_hi, v_hi,
                                        hi_off, c_hi, False)
        return acc / jnp.maximum(l.transpose(0, 2, 1)[..., None], 1e-37)

    return jax.jit(contiguous if layout == "contiguous" else zigzag)


def main():
    out_path = pathlib.Path(__file__).parent / "zigzag_bench.jsonl"
    rows = []
    for t_local in (4096, 8192, 16384):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(0, 1, (B, t_local, H, D)), jnp.bfloat16)
        kv = jnp.asarray(rng.normal(0, 1, (N, 2, B, t_local, H, D)),
                         jnp.bfloat16)
        row = {"t_local": t_local, "ring": N}
        for layout in ("contiguous", "zigzag"):
            fn = make_schedule(layout, t_local)
            o = fn(q, kv)
            _ = float(jnp.sum(o.astype(jnp.float32)))  # warm + sync
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                o = q
                for _ in range(ITERS):
                    o = fn(o, kv).astype(jnp.bfloat16)
                _ = float(jnp.sum(o.astype(jnp.float32)))
                best = min(best, (time.perf_counter() - t0) / ITERS)
            row[layout] = best
        row["speedup"] = row["contiguous"] / row["zigzag"]
        rows.append(row)
        print(f"t_local={t_local} ring={N}: contiguous "
              f"{row['contiguous']*1e3:.1f} ms  zigzag "
              f"{row['zigzag']*1e3:.1f} ms  speedup "
              f"{row['speedup']:.2f}x  (ideal {4*N/(2*N+1):.2f}x)",
              flush=True)
    with out_path.open("w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
