"""Tiny-spatial conv -> one matmul: can it move DenseNet's bottleneck?

The round-5 attribution (backbone_mfu.jsonl) pins DenseNet201@32x32's
cost in the late stages: stage4 (48 concat layers at 2x2 spatial,
221k p/s fwd, MFU 0.079) and stage5 (32 layers at 1x1). A 3x3 SAME
conv at spatial S<=3 touches every input position from every output
position, so it IS a dense linear map over (position, channel) — a
single [S^2*Cin, S^2*Cout] matmul with the block weights gathered from
the 3x3 kernel by geometry (taps outside the window are zero). That
shape (e.g. 1152x128 for stage4's 3x3 convs instead of halo-padded
K=288 patches with N=32) is a much better MXU tile; at 1x1 the map
degenerates to x @ k[center] (the 8 border taps only ever see padding).

This measures: (a) exactness vs lax.conv (asserted before timing),
(b) stage4/stage5 forward with the transformed convs vs the native
lowering, on the chip. If the stage-level numbers move, the transform
graduates to a core.conv2d option; if not, this file is the closed
lever account.

Run: python experiments/dense_smallconv.py
Appends JSON lines to experiments/dense_smallconv.jsonl.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from mfu_matrix import _timed  # noqa: E402

from idc_models_tpu.observe.profile import program_report  # noqa: E402

OUT = Path(__file__).resolve().parent / "dense_smallconv.jsonl"


def block_weight(k, S):
    """[3, 3, Cin, Cout] SAME-conv kernel -> the [S^2*Cin, S^2*Cout]
    dense position-mixing matrix it realizes on S x S inputs (S <= 3:
    every (in, out) position pair lies inside the 3x3 window or sees
    only zero padding)."""
    import jax.numpy as jnp

    c_in, c_out = k.shape[2], k.shape[3]
    blocks = []
    for pi in range(S * S):
        iy, ix = divmod(pi, S)
        row = []
        for po in range(S * S):
            oy, ox = divmod(po, S)
            dy, dx = iy - oy + 1, ix - ox + 1
            if 0 <= dy < 3 and 0 <= dx < 3:
                row.append(k[dy, dx])
            else:
                row.append(jnp.zeros((c_in, c_out), k.dtype))
        blocks.append(jnp.concatenate(row, axis=1))
    return jnp.concatenate(blocks, axis=0)


def smallconv(k, x):
    """y = conv2d(x, k, SAME, stride 1) for [B, S, S, Cin], S <= 3."""
    import jax.numpy as jnp

    b, S, _, c_in = x.shape
    c_out = k.shape[3]
    if S == 1:
        return (x.reshape(b, c_in) @ k[1, 1]).reshape(b, 1, 1, c_out)
    W = block_weight(k, S)
    y = x.reshape(b, S * S * c_in) @ W
    return y.reshape(b, S, S, c_out)


def check_exact():
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(0)
    for S, c_in, c_out in ((1, 896, 128), (2, 288, 128), (2, 128, 32),
                           (3, 64, 16)):
        k = jnp.asarray(rng.normal(0, 1, (3, 3, c_in, c_out)), jnp.float32)
        x = jnp.asarray(rng.normal(0, 1, (4, S, S, c_in)), jnp.float32)
        ref = lax.conv_general_dilated(
            x, k, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        got = smallconv(k, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
    print("exactness: smallconv == lax.conv for S in {1,2,3}",
          file=sys.stderr)


def measure_stage(group: str, *, transform: bool, batch=1024):
    """dense stage forward (as backbone_mfu.measure_group) with 3x3
    convs at tiny spatial optionally replaced by the matmul form."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.models import core, densenet
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    import backbone_mfu as bm

    lo, hi, size, c_in = bm._DENSE_GROUPS[group]
    units, modules = densenet._units(3, densenet.FREEZE_ALL)
    if transform:
        # swap every 3x3 conv module for the matmul form (1x1 convs and
        # BNs untouched); geometry guarantees spatial <= 3 in-stage
        for name, mod in list(modules.items()):
            if name.endswith("_2_conv"):
                modules[name] = _matmul_conv_like(mod)
    init, apply = bm._range_model(units, modules, lo, hi)
    variables = init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(0)
                    .random((batch, size, size, c_in), np.float32),
                    dtype=jnp.bfloat16)

    @jax.jit
    def fwd(params, state, x):
        return jnp.sum(apply(params, state, x).astype(jnp.float32))

    compiled = fwd.lower(variables.params, variables.state, x).compile()
    flops = program_report(compiled,
                           name="dense_smallconv.fwd").flops or 0.0
    box = {}

    def dispatch(n):
        for _ in range(n):
            box["y"] = compiled(variables.params, variables.state, x)

    def fence():
        return float(box["y"])

    steps, dt, dts = _timed(dispatch, fence)
    return {"patches_per_sec_per_chip": steps * batch / dt,
            "steps": steps, "best_dt": dt, "window_dts": dts,
            "flops_per_patch": flops / batch if flops else None}


def _matmul_conv_like(mod):
    """Same init/params as the wrapped core.conv2d; apply via smallconv
    when the input spatial is <= 3 (else fall back to the original)."""
    from idc_models_tpu.models import core

    def apply(params, state, x, *, train=False, rng=None):
        if x.shape[1] <= 3 and x.shape[1] == x.shape[2]:
            return smallconv(params["kernel"].astype(x.dtype), x), state
        return mod.apply(params, state, x, train=train, rng=rng)

    return core.Module(mod.init, apply, mod.name)


def main():
    import jax

    check_exact()
    dev = jax.devices()[0]
    rows = []
    with OUT.open("a") as f:
        for group in ("stage4_2", "stage5_1"):
            for transform in (False, True):
                t0 = time.time()
                r = measure_stage(group, transform=transform)
                r.update(name=f"{group}_{'matmul' if transform else 'native'}",
                         wall_s=round(time.time() - t0, 1),
                         device_kind=dev.device_kind)
                line = json.dumps(r)
                print(line, flush=True)
                f.write(line + "\n")
                f.flush()
                rows.append(r)
    for i in (0, 2):
        nat, mat = rows[i], rows[i + 1]
        print(f"{nat['name'][:-7]}: matmul/native = "
              f"{mat['patches_per_sec_per_chip'] / nat['patches_per_sec_per_chip']:.3f}x",
              file=sys.stderr)


if __name__ == "__main__":
    main()
