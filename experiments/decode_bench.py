"""KV-cache decode latency on the chip, per context length.

The serving-side record for ring_decode.py: single-token decode steps
against a resident cache at several context lengths (ring of 1, so one
chip holds the whole cache — the per-device work of an n-device ring at
n× the context). Methodology as everywhere in this repo: chained jitted
steps per timing window (pos advances, caches donated in place), best
of 3 windows, host fetch of a dependent scalar as the fence.

Run: python experiments/decode_bench.py
Appends one JSON line per context length to experiments/decode_bench.jsonl.
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_decode import init_cache, make_ring_decode, prefill

B, H, D = 1, 8, 64
ITERS = 32          # per-call decode steps per timing window
SCAN_ITERS = 512    # in-jit chained steps (amortizes the ~100 ms tunnel RTT)
OUT = pathlib.Path(__file__).parent / "decode_bench.jsonl"


def main():
    mesh = meshlib.seq_mesh(1)
    dev = jax.devices()[0]
    step = make_ring_decode(mesh)
    rng = np.random.default_rng(0)
    with OUT.open("a") as f:
        for t_max in (4096, 16384, 65536):
            p_len = t_max - SCAN_ITERS - 1
            kp, vp = (jnp.asarray(rng.normal(0, 1, (B, p_len, H, D)),
                                  jnp.bfloat16) for _ in range(2))
            kc, vc = prefill(mesh, kp, vp, t_max)
            toks = [jnp.asarray(rng.normal(0, 1, (B, 1, H, D)),
                                jnp.bfloat16) for _ in range(3)]
            q_t, k_t, v_t = toks
            # warm (compile)
            out, kc, vc = step(kc, vc, q_t, k_t, v_t, p_len)
            _ = float(jnp.sum(out.astype(jnp.float32)))
            best = 1e9
            for w in range(3):
                # fresh cache region each window: restart pos at p_len
                # is fine (slots just overwrite; timing is unaffected)
                t0 = time.perf_counter()
                o = q_t
                for s in range(ITERS):
                    o, kc, vc = step(kc, vc, o, k_t, v_t, p_len + s)
                    o = o.astype(jnp.bfloat16)
                _ = float(jnp.sum(o.astype(jnp.float32)))
                best = min(best, (time.perf_counter() - t0) / ITERS)
            # per-call latency above is TUNNEL-dispatch bound (~3.5 ms
            # flat vs context); the in-jit scan below chains ITERS
            # steps inside ONE executable — the device-side cost of the
            # decode op itself (real serving interleaves the model
            # forward between steps, so this is the op's floor, not an
            # end-to-end tokens/s claim)
            @jax.jit
            def scan_steps(kc, vc, q, k, v, pos0):
                def body(carry, s):
                    kc, vc, o = carry
                    o, kc, vc = _inner(kc, vc, o, k, v, pos0 + s)
                    return (kc, vc, o.astype(jnp.bfloat16)), ()

                (kc, vc, o), _ = jax.lax.scan(
                    body, (kc, vc, q), jnp.arange(SCAN_ITERS))
                return o, kc, vc

            _inner = make_ring_decode(mesh)
            o, kc2, vc2 = scan_steps(kc, vc, q_t, k_t, v_t, p_len)
            _ = float(jnp.sum(o.astype(jnp.float32)))
            best_scan = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                o, kc2, vc2 = scan_steps(kc2, vc2, q_t, k_t, v_t, p_len)
                _ = float(jnp.sum(o.astype(jnp.float32)))
                best_scan = min(best_scan,
                                (time.perf_counter() - t0) / SCAN_ITERS)

            row = {"t_max": t_max, "prefill": p_len,
                   "decode_step_ms": round(best * 1e3, 3),
                   "tokens_per_s": round(1.0 / best, 1),
                   "decode_step_injit_ms": round(best_scan * 1e3, 3),
                   "injit_tokens_per_s": round(1.0 / best_scan, 1),
                   "device_kind": dev.device_kind}
            line = json.dumps(row)
            print(line, flush=True)
            f.write(line + "\n")
            f.flush()


if __name__ == "__main__":
    main()
