"""Remat necessity proof (VERDICT r4 #4).

Round 4 measured `remat=True` (jax.checkpoint per transformer block)
only at 32,768 tokens / 6 blocks, where BOTH variants fit the 16 GB
chip — the +33% step cost bought nothing demonstrated. This script
finds the (seq_len, num_blocks) point on the chip where the
stored-activation model FAILS to compile/allocate and the remat model
TRAINS, recording both sides like the flash backward's 16k existence
proof (experiments/flash_bwd_bench.jsonl pattern).

Config family: the long-context model at its bench shape (d_model=512,
8 heads, mlp 2048, pallas blocks, ring of 1, bf16 train step, batch 1).
Candidates walk upward until the split point appears; each side's
outcome (step ms, or the failure type) is one JSONL row in
experiments/remat_necessity.jsonl.

Run: python experiments/remat_necessity.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import jax  # noqa: E402
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.observe.profile import program_report  # noqa: E402

OUT = pathlib.Path(__file__).parent / "remat_necessity.jsonl"

CANDIDATES = [
    # (seq_len, num_blocks) — walk memory upward; 32k/6 is the round-4
    # both-fit anchor re-measured for continuity
    (32768, 6),
    (32768, 12),
    (65536, 8),
]


def try_step(seq_len: int, num_blocks: int, remat: bool):
    """Compile + run 2 train steps; returns dict(ok, step_ms | error)."""
    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.attention import attention_classifier
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
    )
    from idc_models_tpu.train.losses import binary_cross_entropy

    mesh = meshlib.seq_mesh(1)
    model = attention_classifier(seq_len, 8, embed_dim=512, num_heads=8,
                                 mlp_dim=2048, num_blocks=num_blocks,
                                 num_outputs=1, mesh=mesh, causal=True,
                                 block_impl="pallas", remat=remat)
    try:
        opt = rmsprop(1e-4)
        variables = model.init(jax.random.key(0))
        state = TrainState(step=jnp.zeros((), jnp.int32),
                           params=variables.params,
                           model_state=variables.state,
                           opt_state=opt.init(variables.params))
        step = jit_data_parallel(
            make_train_step(model, opt, binary_cross_entropy,
                            compute_dtype=jnp.bfloat16), mesh,
            axis=meshlib.SEQ_AXIS)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (1, seq_len, 8)), jnp.float32)
        y = jnp.asarray([1], jnp.int32)
        state = replicate(mesh, state)
        key = jax.random.key(1)
        compiled = step.lower(state, x, y, key).compile()
        # one extraction point for XLA memory accounting (ISSUE 9):
        # program_report degrades to None fields on backends that do
        # not expose memory_analysis
        rep = program_report(compiled, name="remat.step")
        mem = ({"temp_gb": round(rep.temp_bytes / 2**30, 2),
                "args_gb": round(rep.argument_bytes / 2**30, 2)}
               if rep.temp_bytes is not None
               and rep.argument_bytes is not None else {})
        digest = jax.jit(lambda s: jnp.sum(
            s.params["head"]["kernel"].astype(jnp.float32)))
        state, _ = compiled(state, x, y, key)      # warm
        _ = float(digest(state))
        t0 = time.perf_counter()
        state, _ = compiled(state, x, y, jax.random.key(2))
        _ = float(digest(state))
        return {"ok": True,
                "step_ms": round((time.perf_counter() - t0) * 1e3, 1),
                **mem}
    except Exception as e:  # noqa: BLE001 — the failure IS the datapoint
        return {"ok": False,
                "error": f"{type(e).__name__}: {e}"[:300]}


def main():
    dev = jax.devices()[0]
    with OUT.open("a") as f:
        for seq_len, num_blocks in CANDIDATES:
            row = {"seq_len": seq_len, "num_blocks": num_blocks,
                   "d_model": 512, "mlp": 2048,
                   "device_kind": dev.device_kind}
            for remat in (False, True):
                r = try_step(seq_len, num_blocks, remat)
                row["remat" if remat else "stored"] = r
                print(f"T={seq_len} blocks={num_blocks} "
                      f"remat={remat}: {r}", flush=True)
            line = json.dumps(row)
            f.write(line + "\n")
            f.flush()
            stored, rem = row["stored"], row["remat"]
            if not stored["ok"] and rem["ok"]:
                print(f"NECESSITY POINT: T={seq_len} blocks={num_blocks} "
                      f"— stored fails ({stored['error'][:80]}), remat "
                      f"trains at {rem['step_ms']} ms", flush=True)
                break


if __name__ == "__main__":
    main()
