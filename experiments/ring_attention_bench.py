"""Ring attention block-impl comparison on the real chip (the numbers
quoted in ops/flash_block_kernel.py's docstring).

Methodology: 20 CHAINED calls per timing window (the output feeds back
as q), so the tunneled runtime's ~90 ms per-dispatch overhead is
amortized; single-call timings at these sizes are pure dispatch noise.
Run: python experiments/ring_attention_bench.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import time
import jax, jax.numpy as jnp, numpy as np
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.ring_attention import make_ring_attention

B, H, D = 1, 8, 64
ITERS = 20
mesh = meshlib.seq_mesh(1)
for T in (4096, 8192, 16384):
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(0, 1, (B, T, H, D)), jnp.bfloat16)
               for _ in range(3))
    row = {}
    for impl in ("jnp", "pallas"):
        fn = make_ring_attention(mesh, causal=True, block_impl=impl)
        out = fn(q, k, v)
        _ = float(jnp.sum(out.astype(jnp.float32)))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            o = q
            for _ in range(ITERS):          # chained: o feeds back as q
                o = fn(o, k, v).astype(jnp.bfloat16)
            f = float(jnp.sum(o.astype(jnp.float32)))
            best = min(best, (time.perf_counter() - t0) / ITERS)
        row[impl] = best
    print(f"T={T}: jnp {row['jnp']*1e3:.2f} ms/call  pallas "
          f"{row['pallas']*1e3:.2f} ms/call  speedup "
          f"{row['jnp']/row['pallas']:.2f}x", flush=True)
