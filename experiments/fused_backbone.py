"""ISSUE-16 tuning harness: fused Pallas backbone layout/batch sweep.

Three sweeps, one JSONL record each, all on the real fine-tune train
step (the backbone_mfu.py `measure_train` methodology — phase-2 model,
bf16, honest host-fetch fence):

1. MobileNetV2 depthwise lowering x batch: `depthwise_impl` in
   {grouped, taps, fused} at batch 1024/2048/4096 — the fused rows
   carry the ANALYTIC Pallas kernel FLOPs/bytes merged into XLA's
   accounting (cost_analysis cannot see inside a pallas_call), so
   their intensity/hbm columns are comparable with the unfused rows.
2. DenseNet201 block data movement x batch: `block_impl` in
   {packed, concat} at batch 512/1024/2048 — packed preallocates the
   block buffer and dynamic_update_slices each layer's 32 channels;
   concat is the re-materializing baseline the MFU attribution blamed.
3. Fused-kernel channel-tile microsweep: the stem/block depthwise
   shapes at `channel_tile` in {None, 32, 16, 8} — `None` (whole-C per
   grid cell; every 50x50-scale activation fits VMEM) is the recorded
   default, frozen as ops/fused_conv.DEFAULT_CHANNEL_TILE. Re-run this
   sweep before changing it.

Usage (results are only perf-meaningful on the chip; on CPU the Pallas
rows run the interpreter and measure correctness, not speed):

    python experiments/fused_backbone.py            # run everything
    python experiments/fused_backbone.py mobile_fused_2048 tile_25x96_none
    python experiments/fused_backbone.py --list

Appends one JSON line per experiment to experiments/fused_backbone.jsonl.
`*_base`-style unfused rows bracket the fused rows (shared-chip drift
is +/-10% over minutes — BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from backbone_mfu import _peak_gbps, measure_train  # noqa: E402
from mfu_matrix import _timed  # noqa: E402  (shared honest-timing loop)

OUT = Path(__file__).resolve().parent / "fused_backbone.jsonl"


def measure_mobile(batch: int, impl: str):
    """MobileNetV2 fine-tune step with one depthwise lowering; fused
    rows get the analytic Pallas cost merged in (same accounting as
    `profile --model mobile --depthwise-impl fused`, cli.py)."""
    r = measure_train("mobile", batch=batch,
                      build_kwargs={"depthwise_impl": impl})
    if impl == "fused":
        import jax

        from idc_models_tpu.models import mobilenet
        from idc_models_tpu.ops import fused_conv

        n_dev = len(jax.devices())
        total = batch * n_dev
        k_flops, k_bytes = fused_conv.depthwise_chain_cost(
            mobilenet.fused_call_shapes(total, 50))
        steps, dt = r["steps"], r["best_dt"]
        r["flops_per_patch"] = (r["flops_per_patch"] or 0.0) \
            + k_flops / total
        r["bytes_per_patch"] = (r["bytes_per_patch"] or 0.0) \
            + k_bytes / total
        r["tflops_per_s"] = (r["flops_per_patch"] * total * steps
                             / dt / 1e12 / n_dev)
        r["hbm_gbytes_per_s"] = (r["bytes_per_patch"] * total * steps
                                 / dt / 1e9 / n_dev)
        r["pallas_cost_merged"] = True
    r["depthwise_impl"] = impl
    return r


def measure_dense(batch: int, impl: str):
    """DenseNet201 fine-tune step with one block data-movement impl —
    both are ordinary XLA ops, fully cost-accounted."""
    r = measure_train("dense", batch=batch,
                      build_kwargs={"block_impl": impl})
    r["block_impl"] = impl
    return r


def measure_tile(*, batch=256, size=25, c=96, stride=1,
                 channel_tile=None):
    """One fused depthwise+BN+relu6 call at a MobileNetV2 activation
    shape, timed standalone — the channel-tile layout sweep that chose
    ops/fused_conv.DEFAULT_CHANNEL_TILE."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.ops import fused_conv

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((batch, size, size, c), np.float32))
    w = jnp.asarray(rng.normal(0, 0.1, (3, 3, 1, c)), jnp.float32)
    scale = jnp.ones((c,), jnp.float32)
    bias = jnp.zeros((c,), jnp.float32)
    mean = jnp.asarray(rng.normal(0, 0.1, (c,)), jnp.float32)
    var = jnp.abs(jnp.asarray(rng.random((c,)), jnp.float32)) + 0.5

    fn = jax.jit(lambda a: fused_conv.fused_depthwise_bn_relu6(
        a, w, scale, bias, mean, var, eps=1e-3, stride=stride,
        channel_tile=channel_tile))
    box = {}

    def dispatch(n):
        for _ in range(n):
            box["y"] = fn(x)

    def fence():
        return float(jnp.sum(box["y"].astype(jnp.float32)))

    steps, dt, dts = _timed(dispatch, fence)
    flops, bytes_accessed = fused_conv.depthwise_call_cost(
        batch, size, size, c, stride=stride)
    call_s = dt / steps
    return {
        "shape": [batch, size, size, c], "stride": stride,
        "channel_tile": channel_tile,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "call_ms": call_s * 1e3,
        "gflops_per_s": flops / call_s / 1e9,
        "hbm_gbytes_per_s": bytes_accessed / call_s / 1e9,
    }


EXPERIMENTS = {
    # ---- sweep 1: mobile depthwise lowering x batch ----
    **{f"mobile_{impl}_{b}": partial(measure_mobile, b, impl)
       for b in (1024, 2048, 4096)
       for impl in ("grouped", "taps", "fused")},
    # ---- sweep 2: dense block movement x batch ----
    **{f"dense_{impl}_{b}": partial(measure_dense, b, impl)
       for b in (512, 1024, 2048)
       for impl in ("packed", "concat")},
    # ---- sweep 3: channel-tile layout at the hot fused shapes ----
    **{f"tile_25x96_{t if t else 'none'}":
       partial(measure_tile, size=25, c=96, channel_tile=t)
       for t in (None, 32, 16, 8)},
    **{f"tile_13x144_{t if t else 'none'}":
       partial(measure_tile, size=13, c=144, stride=2, channel_tile=t)
       for t in (None, 48, 16)},
    "tile_25x32_stem": partial(measure_tile, size=25, c=32),
}


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if "--list" in sys.argv:
        print("\n".join(EXPERIMENTS))
        return
    if not names:
        names = list(EXPERIMENTS)

    import jax

    import bench

    dev = jax.devices()[0]
    peak = bench._peak_tflops(dev)
    bw = _peak_gbps(dev)
    print(f"device: {dev.device_kind} peak={peak} TF/s bf16, "
          f"HBM {bw} GB/s; writing {OUT}", file=sys.stderr)
    with OUT.open("a") as f:
        for name in names:
            t0 = time.time()
            try:
                r = EXPERIMENTS[name]()
                if (bw and peak and r.get("flops_per_patch")
                        and r.get("bytes_per_patch")):
                    intensity = (r["flops_per_patch"]
                                 / r["bytes_per_patch"])
                    r["arithmetic_intensity"] = round(intensity, 3)
                    r["roofline_mfu_ceiling"] = min(
                        1.0, intensity * bw * 1e9 / (peak * 1e12))
                if bw and r.get("hbm_gbytes_per_s"):
                    r["hbm_utilization"] = r["hbm_gbytes_per_s"] / bw
            except Exception as e:  # record OOMs etc. as data, keep going
                r = {"error": f"{type(e).__name__}: {e}"[:500]}
            r.update(name=name, ts=round(t0, 1),
                     wall_s=round(time.time() - t0, 1),
                     device_kind=dev.device_kind)
            line = json.dumps(r)
            print(line, flush=True)
            f.write(line + "\n")
            f.flush()


if __name__ == "__main__":
    main()
