"""Round-5 perf record: MobileNetV2 + DenseNet201 on the chip.

VERDICT r4's top ask: two of the reference's three DP training workloads
(dist_model_tf_mobile.py:119-129 — MobileNetV2 on 50x50 IDC patches;
dist_model_tf_dense.py:131-158 — DenseNet201 on 32x32 CIFAR-10) had no
throughput/MFU record; only VGG16 did.  This matrix gives each the
mfu_matrix methodology: the real fine-tune train step (phase-2 model with
bn_frozen_below=fine_tune_at, RMSprop(lr/10) under the Keras-index
fine-tune mask, bf16), XLA cost-analysis FLOPs, per-stage forward
attribution, and the levers that could plausibly move each number.

Unlike VGG (dense 3x3 convs -> MXU-bound, MFU 0.62), both of these
backbones are expected to be HBM-bandwidth-bound on TPU:

  MobileNetV2  depthwise 3x3s have NO channel contraction — nothing for
               the systolic array to reduce — and the surrounding 1x1s
               at 50x50-scale spatial dims are low-arithmetic-intensity
               matmuls.  The record therefore carries bytes-accessed and
               a roofline ceiling next to MFU: for a bandwidth-bound
               step the honest ceiling is flops/bytes * BW / peak, not
               1.0.  Lever measured: depthwise lowering (grouped conv vs
               explicit 9-tap elementwise MAC, core.depthwise_conv2d
               impl="taps").
  DenseNet201  48-deep concat stages at 2x2/1x1 spatial after CIFAR's
               32x32 input collapses — 3x3 convs with K=288..., N=32
               tiles mostly padding, and the concat chain re-reads an
               ever-growing activation.  Levers: batch, the fwd/bwd
               split, per-stage attribution.

Usage (real chip; each entry compiles fresh, ~20-40 s):

    python experiments/backbone_mfu.py             # run everything
    python experiments/backbone_mfu.py mobile_base dense_base
    python experiments/backbone_mfu.py --list

Appends one JSON line per experiment to experiments/backbone_mfu.jsonl.
`*_base` entries are measured first and last (drift bracket: the shared
chip drifts +/-10 percent over minutes — BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from mfu_matrix import _timed  # noqa: E402  (shared honest-timing loop)

from idc_models_tpu.observe.profile import program_report  # noqa: E402

OUT = Path(__file__).resolve().parent / "backbone_mfu.jsonl"

def _peak_gbps(device) -> float | None:
    """Nominal peak HBM GB/s per chip — the per-backend roofline
    registry (observe/profile.py BACKEND_ROOFS, seeded from the table
    that used to live here) is the one source of truth."""
    from idc_models_tpu.observe.profile import roofline_for

    spec = roofline_for(device)
    return spec.peak_hbm_gbps if spec else None


# ---------------------------------------------------------------------------
# the fine-tune train-step measurement, parameterized by backbone
# ---------------------------------------------------------------------------

_PRESET = {
    # model/eval shapes from the reference files cited in the module
    # docstring; lr is the phase-2 client rate (preset lr / 10).
    "mobile": dict(model_name="mobilenet_v2", image_size=50, num_outputs=1,
                   fine_tune_at=100, lr=1e-4),
    "dense": dict(model_name="densenet201", image_size=32, num_outputs=10,
                  fine_tune_at=150, lr=1e-4),
}


def measure_train(preset: str, *, batch=1024, fwd_only=False,
                  compute_dtype="bfloat16", build_kwargs=None):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_eval_step, make_train_step,
        replicate, rmsprop, shard_batch,
    )
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    cfg = _PRESET[preset]
    dtype = getattr(jnp, compute_dtype)
    mesh = meshlib.data_mesh()
    n_dev = len(jax.devices())
    spec = registry.get_model(cfg["model_name"])
    # the phase-2 model exactly as train.loop._build_model makes it:
    # BN below the fine-tune boundary permanently in inference mode
    model = spec.build(cfg["num_outputs"], 3,
                       bn_frozen_below=cfg["fine_tune_at"],
                       **(build_kwargs or {}))
    variables = model.init(jax.random.key(0))
    opt = rmsprop(cfg["lr"] / 10.0,
                  trainable_mask=spec.fine_tune_mask(variables.params,
                                                     cfg["fine_tune_at"]))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))
    loss_fn = (binary_cross_entropy if cfg["num_outputs"] == 1
               else sparse_categorical_cross_entropy)

    rng = np.random.default_rng(0)
    total = batch * n_dev
    s = cfg["image_size"]
    imgs = rng.random((total, s, s, 3), np.float32)
    labels = (rng.integers(0, max(cfg["num_outputs"], 2), total)
              .astype(np.int32))
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)

    if fwd_only:
        step = make_eval_step(model, loss_fn, compute_dtype=dtype)
        jitted = jit_data_parallel(step, mesh, donate_state=False)
        compiled = jitted.lower(state, x, y).compile()
        box = {}

        def dispatch(n):
            for _ in range(n):
                box["m"] = compiled(state, x, y)

        def fence():
            return float(box["m"]["loss"])
    else:
        step = make_train_step(model, opt, loss_fn, compute_dtype=dtype)
        jitted = jit_data_parallel(step, mesh)
        compiled = jitted.lower(state, x, y, jax.random.key(1)).compile()
        digest = jax.jit(lambda st: jnp.sum(
            st.params["head"]["kernel"].astype(jnp.float32)))
        box = {"s": state, "k": jax.random.key(1)}

        def dispatch(n):
            st, k = box["s"], box["k"]
            for _ in range(n):
                k, sub = jax.random.split(k)
                st, _ = compiled(st, x, y, sub)
            box["s"], box["k"] = st, k

        def fence():
            return float(digest(box["s"]))

    rep = program_report(compiled, name=f"{preset}.train_step")
    flops_per_step = rep.flops or 0.0
    bytes_per_step = rep.bytes_accessed or 0.0
    steps, dt, dts = _timed(dispatch, fence)
    step_s = dt / steps
    return {
        "patches_per_sec_per_chip": steps * total / dt / n_dev,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "batch_per_chip": batch,
        "flops_per_patch": flops_per_step / total if flops_per_step else None,
        "bytes_per_patch": bytes_per_step / total if bytes_per_step else None,
        "tflops_per_s": (flops_per_step * steps / dt / 1e12 / n_dev
                         if flops_per_step else None),
        "hbm_gbytes_per_s": (bytes_per_step * steps / dt / 1e9 / n_dev
                             if bytes_per_step else None),
        "step_ms": step_s * 1e3,
    }


# ---------------------------------------------------------------------------
# per-stage forward attribution (unit-range sub-models)
# ---------------------------------------------------------------------------

def _range_model(units, modules, lo, hi):
    """Minimal forward-only composition of units[lo:hi] (the experiment-
    side mirror of core.unit_backbone's internal section)."""
    import jax

    from idc_models_tpu.models import core

    names = [n for ns, _ in units[lo:hi] for n in ns]

    def init(rng):
        rngs = jax.random.split(rng, len(names))
        params, state = {}, {}
        for n, r in zip(names, rngs):
            v = modules[n].init(r)
            if v.params:
                params[n] = v.params
            if v.state:
                state[n] = v.state
        return core.Variables(params, state)

    def apply(params, state, x):
        def run(n, h):
            y, _ = modules[n].apply(params.get(n, {}), state.get(n, {}), h,
                                    train=False)
            return y

        for _, unit_fn in units[lo:hi]:
            x = unit_fn(run, x)
        return x

    return init, apply


# (group, unit range, input spatial, input channels) — shapes follow the
# topology at each preset's reference input size (50x50 mobile, 32 dense)
_MOBILE_GROUPS = {
    "stem_25": (0, 1, 50, 3),       # Conv1 s2 + block0 @25
    "blocks_13": (1, 3, 25, 16),    # blocks 1-2
    "blocks_7": (3, 6, 13, 24),     # blocks 3-5
    "blocks_4": (6, 13, 7, 32),     # blocks 6-12
    "blocks_2": (13, 17, 4, 96),    # blocks 13-16
    "top_2": (17, 18, 2, 320),      # Conv_1 1280
}
_DENSE_GROUPS = {
    "stem_8": (0, 1, 32, 3),        # 7x7 s2 + pool -> 8x8x64
    "stage2_8": (1, 8, 8, 64),      # 6 layers + transition
    "stage3_4": (8, 21, 4, 128),    # 12 layers + transition
    "stage4_2": (21, 70, 2, 256),   # 48 layers + transition
    "stage5_1": (70, 103, 1, 896),  # 32 layers + final BN
}


def measure_group(preset: str, group: str, *, batch=1024):
    import jax
    import jax.numpy as jnp

    if preset == "mobile":
        from idc_models_tpu.models import mobilenet as zoo
        groups = _MOBILE_GROUPS
        freeze = zoo.FREEZE_ALL
    else:
        from idc_models_tpu.models import densenet as zoo
        groups = _DENSE_GROUPS
        freeze = zoo.FREEZE_ALL
    lo, hi, size, c_in = groups[group]
    units, modules = zoo._units(3, freeze)  # all-BN-frozen: fused affine
    init, apply = _range_model(units, modules, lo, hi)
    variables = init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(0)
                    .random((batch, size, size, c_in), np.float32),
                    dtype=jnp.bfloat16)

    @jax.jit
    def fwd(params, state, x):
        return jnp.sum(apply(params, state, x).astype(jnp.float32))

    compiled = fwd.lower(variables.params, variables.state, x).compile()
    rep = program_report(compiled, name=f"{preset}.{group}_fwd")
    flops_per_step = rep.flops or 0.0
    bytes_per_step = rep.bytes_accessed or 0.0
    box = {}

    def dispatch(n):
        for _ in range(n):
            box["y"] = compiled(variables.params, variables.state, x)

    def fence():
        return float(box["y"])

    steps, dt, dts = _timed(dispatch, fence)
    return {
        "patches_per_sec_per_chip": steps * batch / dt,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "batch_per_chip": batch,
        "flops_per_patch": flops_per_step / batch if flops_per_step else None,
        "bytes_per_patch": bytes_per_step / batch if bytes_per_step else None,
        "tflops_per_s": (flops_per_step * steps / dt / 1e12
                         if flops_per_step else None),
        "hbm_gbytes_per_s": (bytes_per_step * steps / dt / 1e9
                             if bytes_per_step else None),
    }


EXPERIMENTS = {
    # ---- MobileNetV2 (50x50 IDC, fine_tune_at=100) ----
    "mobile_base": partial(measure_train, "mobile", batch=2048),
    "mobile_batch_1024": partial(measure_train, "mobile", batch=1024),
    "mobile_batch_4096": partial(measure_train, "mobile", batch=4096),
    "mobile_batch_8192": partial(measure_train, "mobile", batch=8192),
    "mobile_taps": partial(measure_train, "mobile", batch=2048,
                           build_kwargs={"depthwise_impl": "taps"}),
    "mobile_taps_8192": partial(measure_train, "mobile", batch=8192,
                                build_kwargs={"depthwise_impl": "taps"}),
    "mobile_f32": partial(measure_train, "mobile", batch=2048,
                          compute_dtype="float32"),
    "mobile_fwd_only": partial(measure_train, "mobile", batch=2048,
                               fwd_only=True),
    **{f"mobile_{g}_fwd": partial(measure_group, "mobile", g, batch=2048)
       for g in _MOBILE_GROUPS},
    "mobile_base_again": partial(measure_train, "mobile", batch=2048),
    # ---- DenseNet201 (32x32 CIFAR-10, fine_tune_at=150) ----
    "dense_base": partial(measure_train, "dense", batch=1024),
    "dense_batch_256": partial(measure_train, "dense", batch=256),
    "dense_batch_512": partial(measure_train, "dense", batch=512),
    "dense_batch_2048": partial(measure_train, "dense", batch=2048),
    "dense_f32": partial(measure_train, "dense", batch=1024,
                         compute_dtype="float32"),
    "dense_fwd_only": partial(measure_train, "dense", batch=1024,
                              fwd_only=True),
    **{f"dense_{g}_fwd": partial(measure_group, "dense", g, batch=1024)
       for g in _DENSE_GROUPS},
    "dense_base_again": partial(measure_train, "dense", batch=1024),
}


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if "--list" in sys.argv:
        print("\n".join(EXPERIMENTS))
        return
    if not names:
        names = list(EXPERIMENTS)

    import jax

    import bench

    dev = jax.devices()[0]
    peak = bench._peak_tflops(dev)
    bw = _peak_gbps(dev)
    print(f"device: {dev.device_kind} peak={peak} TF/s bf16, "
          f"HBM {bw} GB/s; writing {OUT}", file=sys.stderr)
    with OUT.open("a") as f:
        for name in names:
            t0 = time.time()
            try:
                r = EXPERIMENTS[name]()
                r["mfu"] = (r["tflops_per_s"] / peak
                            if peak and r.get("tflops_per_s") else None)
                # roofline: achievable MFU if the step were perfectly
                # HBM-bound at spec bandwidth — the honest ceiling for
                # low-arithmetic-intensity backbones
                if (bw and peak and r.get("flops_per_patch")
                        and r.get("bytes_per_patch")):
                    intensity = r["flops_per_patch"] / r["bytes_per_patch"]
                    r["roofline_mfu_ceiling"] = min(
                        1.0, intensity * bw * 1e9 / (peak * 1e12))
                    r["hbm_utilization"] = (r["hbm_gbytes_per_s"] / bw
                                            if r.get("hbm_gbytes_per_s")
                                            else None)
            except Exception as e:  # record OOMs etc. as data, keep going
                r = {"error": f"{type(e).__name__}: {e}"[:500]}
            r.update(name=name, ts=round(t0, 1),
                     wall_s=round(time.time() - t0, 1),
                     device_kind=dev.device_kind)
            line = json.dumps(r)
            print(line, flush=True)
            f.write(line + "\n")
            f.flush()


if __name__ == "__main__":
    main()
