"""Round-3 MFU ceiling experiment matrix for the headline benchmark.

The headline (VGG16 fine-tune, 50x50 patches, bf16, batch 2048/chip) has
measured MFU ~0.60-0.61 for two rounds.  BASELINE.md argues the step is
conv-bound from one profile; this matrix attacks the ceiling lever by
lever and RECORDS every number so "conv-bound at 0.61" becomes a
demonstrated ceiling (or falls).  Levers, mapped to the reference
workload's shape (dist_model_tf_vgg.py:119-129: VGG16, 50x50x3 IDC
patches, fine_tune_at=15):

  batch sweep      1024 / 2048 / 3072 / 4096 per chip
  first conv       input-channel zero-pad 3 -> 4 / 8 (the classic
                   3-channel MXU under-utilization probe)
  layout           logical NCHW vs NHWC dimension_numbers
  precision        default bf16 vs matmul_precision=highest vs f32
  spatial          64x64 input diagnostic (are the odd 50->25->12->6->3
                   dims the efficiency loss?)  NOT the headline workload;
                   scored by its own cost analysis.
  attribution      forward-only step + per-block forward microbenches,
                   each with its own XLA cost analysis -> per-block MFU
  cached suffix    batch 32768 / 65536 / 131072 sweep

Usage (on the real chip; each entry compiles fresh, ~20-40 s):

    python experiments/mfu_matrix.py            # run everything
    python experiments/mfu_matrix.py base pad8  # subset
    python experiments/mfu_matrix.py --list

Appends one JSON line per experiment to experiments/mfu_matrix.jsonl.
`base` is measured first and again last so the shared chip's multi-minute
drift band (+/-10%, see BASELINE.md) brackets the matrix.  MFU numbers
are drift-honest (measured flops/s over peak); cross-variant ratios are
only trustworthy to the drift band.
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from idc_models_tpu.observe.profile import program_report  # noqa: E402

OUT = Path(__file__).resolve().parent / "mfu_matrix.jsonl"


# ---------------------------------------------------------------------------
# generic honest timing (host-fetch fence; see bench.py module docstring)
# ---------------------------------------------------------------------------

def _timed(dispatch, fence, *, min_seconds=1.0, start_steps=20,
           max_steps=400, windows=4):
    """dispatch(n) enqueues n steps; fence() host-fetches a scalar that
    data-depends on the last step.  Returns (steps, best_dt, all_dts)."""
    dispatch(3)
    fence()
    steps = start_steps
    while True:
        t0 = time.perf_counter()
        dispatch(steps)
        fence()
        dt = time.perf_counter() - t0
        if dt >= min_seconds or steps >= max_steps:
            break
        steps = min(max_steps, max(steps * 2,
                                   int(steps * 1.5 * min_seconds / dt)))
    dts = [dt]
    for _ in range(windows - 1):
        t0 = time.perf_counter()
        dispatch(steps)
        fence()
        dts.append(time.perf_counter() - t0)
    return steps, min(dts), dts


# ---------------------------------------------------------------------------
# NCHW variant of the VGG16 classifier (same param tree as models.vgg so
# fine_tune_mask applies unchanged; only dimension_numbers/layout differ)
# ---------------------------------------------------------------------------

def _conv2d_nchw(features_in, features_out, name):
    import jax.numpy as jnp
    from jax import lax

    from idc_models_tpu.models import core

    def init(rng):
        fan_in = 9 * features_in
        fan_out = 9 * features_out
        k = core.glorot_uniform(rng, (3, 3, features_in, features_out),
                                fan_in, fan_out)
        return core.Variables({"kernel": k,
                               "bias": jnp.zeros((features_out,))}, {})

    def apply(params, state, x, *, train=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["kernel"].astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=("NCHW", "HWIO", "NCHW"))
        return y + params["bias"].astype(y.dtype)[None, :, None, None], state

    return core.Module(init, apply, name)


def _max_pool_nchw(name):
    import jax.numpy as jnp
    from jax import lax

    from idc_models_tpu.models import core

    def apply(params, state, x, *, train=False, rng=None):
        return lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 2, 2),
                                 (1, 1, 2, 2), "VALID"), state

    return core.Module(lambda rng: core.Variables({}, {}), apply, name)


def vgg16_nchw(num_outputs: int = 1):
    from idc_models_tpu.models import core
    from idc_models_tpu.models.vgg import _CFG

    layers = []
    c_in = 3
    for block, filters, n_convs in _CFG:
        for conv in range(1, n_convs + 1):
            layers.append(_conv2d_nchw(c_in, filters,
                                       f"block{block}_conv{conv}"))
            layers.append(core.relu(name=f"block{block}_relu{conv}"))
            c_in = filters
        layers.append(_max_pool_nchw(f"block{block}_pool"))
    backbone = core.sequential(layers, name="vgg16")
    head = core.dense(512, num_outputs, name="head")

    def init(rng):
        r1, r2 = core._split(rng, 2)
        bb, hd = backbone.init(r1), head.init(r2)
        return core.Variables({"backbone": bb.params, "head": hd.params},
                              {"backbone": bb.state})

    def apply(params, state, x, *, train=False, rng=None):
        h, bb_state = backbone.apply(params["backbone"],
                                     state.get("backbone", {}), x,
                                     train=train, rng=rng)
        h = h.mean(axis=(2, 3))  # GAP over NCHW spatial
        y, _ = head.apply(params["head"], {}, h, train=train)
        return y, {"backbone": bb_state}

    return core.Module(init, apply, "vgg16_classifier_nchw")


# ---------------------------------------------------------------------------
# the parameterized fine-tune train-step measurement
# ---------------------------------------------------------------------------

def measure_train(*, batch=2048, in_channels=3, image_size=50,
                  compute_dtype="bfloat16", matmul_precision=None,
                  layout="NHWC", fwd_only=False):
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models.vgg import fine_tune_mask, vgg16
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_eval_step, make_train_step,
        replicate, rmsprop, shard_batch,
    )
    from idc_models_tpu.train.losses import binary_cross_entropy

    dtype = getattr(jnp, compute_dtype)
    mesh = meshlib.data_mesh()
    n_dev = len(jax.devices())
    model = vgg16_nchw(1) if layout == "NCHW" else vgg16(1, in_channels)
    variables = model.init(jax.random.key(0))
    opt = rmsprop(1e-4, trainable_mask=fine_tune_mask(variables.params, 15))
    state = TrainState(step=jnp.zeros((), jnp.int32),
                       params=variables.params,
                       model_state=variables.state,
                       opt_state=opt.init(variables.params))

    rng = np.random.default_rng(0)
    total = batch * n_dev
    if layout == "NCHW":
        imgs = rng.random((total, in_channels, image_size, image_size),
                          np.float32)
    else:
        imgs = rng.random((total, image_size, image_size, in_channels),
                          np.float32)
        if in_channels > 3:  # the zero-pad probe: channels 3.. are zero
            imgs[..., 3:] = 0.0
    labels = (rng.random(total) > 0.5).astype(np.int32)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, imgs, labels)

    import contextlib
    ctx = (jax.default_matmul_precision(matmul_precision)
           if matmul_precision else contextlib.nullcontext())
    with ctx:
        if fwd_only:
            step = make_eval_step(model, binary_cross_entropy,
                                  compute_dtype=dtype)
            jitted = jit_data_parallel(step, mesh, donate_state=False)
            compiled = jitted.lower(state, x, y).compile()
            box = {}

            def dispatch(n):
                for _ in range(n):
                    box["m"] = compiled(state, x, y)

            def fence():
                return float(box["m"]["loss"])
        else:
            step = make_train_step(model, opt, binary_cross_entropy,
                                   compute_dtype=dtype)
            jitted = jit_data_parallel(step, mesh)
            compiled = jitted.lower(state, x, y, jax.random.key(1)).compile()
            digest = jax.jit(lambda s: jnp.sum(
                s.params["head"]["kernel"].astype(jnp.float32)))
            box = {"s": state, "k": jax.random.key(1)}

            def dispatch(n):
                s, k = box["s"], box["k"]
                for _ in range(n):
                    k, sub = jax.random.split(k)
                    s, _ = compiled(s, x, y, sub)
                box["s"], box["k"] = s, k

            def fence():
                return float(digest(box["s"]))

    flops_per_step = program_report(compiled,
                                    name="mfu_matrix.step").flops or 0.0
    steps, dt, dts = _timed(dispatch, fence)
    return {
        "patches_per_sec_per_chip": steps * total / dt / n_dev,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "flops_per_patch": flops_per_step / total if flops_per_step else None,
        "tflops_per_s": (flops_per_step * steps / dt / 1e12 / n_dev
                         if flops_per_step else None),
    }


# ---------------------------------------------------------------------------
# per-block forward microbenches (MFU attribution)
# ---------------------------------------------------------------------------

def measure_block_fwd(block: int, *, batch=2048):
    """Forward of one VGG block (convs+relus+pool) at its in-network input
    shape, bf16 — per-block MFU shows WHICH convs XLA runs inefficiently."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu.models import core
    from idc_models_tpu.models.vgg import _CFG

    sizes = {1: 50, 2: 25, 3: 12, 4: 6, 5: 3}
    cins = {1: 3, 2: 64, 3: 128, 4: 256, 5: 512}
    _, filters, n_convs = _CFG[block - 1]
    layers = []
    c_in = cins[block]
    for conv in range(1, n_convs + 1):
        layers.append(core.conv2d(c_in, filters, 3,
                                  name=f"block{block}_conv{conv}"))
        layers.append(core.relu(name=f"block{block}_relu{conv}"))
        c_in = filters
    layers.append(core.max_pool(2, name=f"block{block}_pool"))
    model = core.sequential(layers)
    variables = model.init(jax.random.key(0))
    s = sizes[block]
    x = jnp.asarray(
        np.random.default_rng(0).random((batch, s, s, cins[block]),
                                        np.float32).astype(np.float32),
        dtype=jnp.bfloat16)

    @jax.jit
    def fwd(params, x):
        y, _ = model.apply(params, variables.state, x)
        return jnp.sum(y.astype(jnp.float32))

    compiled = fwd.lower(variables.params, x).compile()
    flops_per_step = program_report(
        compiled, name=f"mfu_matrix.block{block}_fwd").flops or 0.0
    box = {}

    def dispatch(n):
        for _ in range(n):
            box["y"] = compiled(variables.params, x)

    def fence():
        return float(box["y"])

    steps, dt, dts = _timed(dispatch, fence)
    return {
        "patches_per_sec_per_chip": steps * batch / dt,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "flops_per_patch": flops_per_step / batch if flops_per_step else None,
        "tflops_per_s": (flops_per_step * steps / dt / 1e12
                         if flops_per_step else None),
    }


def measure_cached(*, batch):
    """The --cache-features suffix step at a given per-chip batch."""
    import jax
    import jax.numpy as jnp

    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.models import registry
    from idc_models_tpu.models.vgg import KERAS_LAYER_INDEX, vgg16
    from idc_models_tpu.train import (
        TrainState, jit_data_parallel, make_train_step, replicate, rmsprop,
        shard_batch,
    )
    from idc_models_tpu.train import feature_cache as fc
    from idc_models_tpu.train.losses import binary_cross_entropy

    n_dev = len(jax.devices())
    total = batch * n_dev
    mesh = meshlib.data_mesh()
    model = vgg16(num_outputs=1)
    spec = registry.get_model("vgg16")
    plan = fc.plan_feature_cache(model, KERAS_LAYER_INDEX, 15, 512, 1)
    variables = model.init(jax.random.key(0))
    sp, ss = fc.suffix_variables(plan, variables.params, variables.state)
    opt = rmsprop(1e-4, trainable_mask=spec.fine_tune_mask(sp, 15))
    state = TrainState(step=jnp.zeros((), jnp.int32), params=sp,
                       model_state=ss, opt_state=opt.init(sp))
    step = jit_data_parallel(
        make_train_step(plan.suffix_model, opt, binary_cross_entropy,
                        compute_dtype=jnp.bfloat16), mesh)
    rng = np.random.default_rng(0)
    feats = rng.random((total, 3, 3, 512)).astype(np.float32)
    labels = (rng.random(total) > 0.5).astype(np.int32)
    state = replicate(mesh, state)
    x, y = shard_batch(mesh, feats, labels)
    compiled = step.lower(state, x, y, jax.random.key(1)).compile()
    flops_per_step = program_report(compiled,
                                    name="mfu_matrix.cached").flops or 0.0
    digest = jax.jit(lambda s: jnp.sum(
        s.params["head"]["kernel"].astype(jnp.float32)))
    box = {"s": state, "k": jax.random.key(1)}

    def dispatch(n):
        s, k = box["s"], box["k"]
        for _ in range(n):
            k, sub = jax.random.split(k)
            s, _ = compiled(s, x, y, sub)
        box["s"], box["k"] = s, k

    def fence():
        return float(digest(box["s"]))

    steps, dt, dts = _timed(dispatch, fence)
    return {
        "patches_per_sec_per_chip": steps * total / dt / n_dev,
        "steps": steps, "best_dt": dt, "window_dts": dts,
        "flops_per_patch": flops_per_step / total if flops_per_step else None,
        "tflops_per_s": (flops_per_step * steps / dt / 1e12 / n_dev
                         if flops_per_step else None),
    }


EXPERIMENTS = {
    # headline configuration, measured first and last (drift bracket)
    "base": partial(measure_train),
    "batch_1024": partial(measure_train, batch=1024),
    "batch_3072": partial(measure_train, batch=3072),
    "batch_4096": partial(measure_train, batch=4096),
    "pad4": partial(measure_train, in_channels=4),
    "pad8": partial(measure_train, in_channels=8),
    "nchw": partial(measure_train, layout="NCHW"),
    "precision_highest": partial(measure_train, matmul_precision="highest"),
    "f32": partial(measure_train, compute_dtype="float32"),
    "input64": partial(measure_train, image_size=64),
    "fwd_only": partial(measure_train, fwd_only=True),
    "block1_fwd": partial(measure_block_fwd, 1),
    "block2_fwd": partial(measure_block_fwd, 2),
    "block3_fwd": partial(measure_block_fwd, 3),
    "block4_fwd": partial(measure_block_fwd, 4),
    "block5_fwd": partial(measure_block_fwd, 5),
    "cached_32768": partial(measure_cached, batch=32768),
    "cached_65536": partial(measure_cached, batch=65536),
    "cached_131072": partial(measure_cached, batch=131072),
    "base_again": partial(measure_train),
}


def main():
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if "--list" in sys.argv:
        print("\n".join(EXPERIMENTS))
        return
    if not names:
        names = list(EXPERIMENTS)

    import jax

    import bench

    dev = jax.devices()[0]
    peak = bench._peak_tflops(dev)
    print(f"device: {dev.device_kind} peak={peak} TF/s bf16; "
          f"writing {OUT}", file=sys.stderr)
    with OUT.open("a") as f:
        for name in names:
            t0 = time.time()
            try:
                r = EXPERIMENTS[name]()
                r["mfu"] = (r["tflops_per_s"] / peak
                            if peak and r.get("tflops_per_s") else None)
            except Exception as e:  # record OOMs etc. as data, keep going
                r = {"error": f"{type(e).__name__}: {e}"[:500]}
            r.update(name=name, ts=round(t0, 1),
                     wall_s=round(time.time() - t0, 1),
                     device_kind=dev.device_kind)
            line = json.dumps(r)
            print(line, flush=True)
            f.write(line + "\n")
            f.flush()


if __name__ == "__main__":
    main()
