"""Measure the threefry-vs-pallas crossover for the secure mask op.

The secure round's hot op per client is clip -> quantize -> add
n_clients pairwise mask streams over the flat protected buffer. Two
impls exist (secure/fedavg.py mask_impl): XLA threefry
(masking.quantize + masking.pairwise_mask) and the fused Pallas
hash-PRG kernel (ops.secure_masking_kernel). Round 3 left the kernel
non-default with a known near-tie at VGG16 size; this experiment sweeps
buffer sizes on the real chip to find the crossover that
`mask_impl="auto"` selects on (recorded in BASELINE.md and
secure/masking.py::MASK_PALLAS_MIN_ELEMS).

Methodology: the op is chained INSIDE one jit (each iteration's input
depends on the previous output through one scalar, so iterations
serialize but per-call dispatch — ~10 ms on the tunneled runtime,
bigger than the op itself below ~8M elements — vanishes), best-of-3
windows, host fetch of a dependent scalar. n_clients=8 (the
suite/bench default). Run: python experiments/mask_crossover.py
"""

import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import jax
import jax.numpy as jnp
import numpy as np

from idc_models_tpu.ops import secure_masking_kernel as smk
from idc_models_tpu.secure import masking

N_CLIENTS = 8
ITERS = 100
SB, CLIP = 14, 4.0


def main():
    key = jax.random.key(0)
    my_id = jnp.int32(3)
    rows = []
    for n in (1 << 18, 1 << 20, 1 << 22, 1 << 23, 14_700_000, 1 << 25):
        x = jax.random.normal(jax.random.key(1), (n,), jnp.float32)

        def threefry(x):
            q = masking.quantize(x, SB, clip_abs=CLIP)
            return q + masking.pairwise_mask(key, my_id, N_CLIENTS, (n,))

        seeds, signs = smk.pair_seeds_and_signs(
            jax.random.bits(key, (), jnp.uint32), my_id, N_CLIENTS)

        def pallas(x):
            return smk.fused_masked_quantize(x, seeds, signs,
                                             scale_bits=SB, clip_abs=CLIP)

        def chained(op):
            @jax.jit
            def run(x):
                def body(_, acc):
                    out = op(acc)
                    # scalar-only dependency: serializes iterations
                    # without a full extra pass over the buffer
                    return x + out[0].astype(jnp.float32) * 1e-30
                return jax.lax.fori_loop(0, ITERS, body, x)
            return run

        row = {"elements": int(n)}
        for name, fn in (("threefry", chained(threefry)),
                         ("pallas", chained(pallas))):
            out = fn(x)
            _ = float(jnp.sum(out))
            best = 1e9
            for _ in range(3):
                t0 = time.perf_counter()
                acc = fn(x)
                _ = float(jnp.sum(acc))
                best = min(best, (time.perf_counter() - t0) / ITERS)
            row[name] = best
        row["pallas_speedup"] = row["threefry"] / row["pallas"]
        rows.append(row)
        print(f"n={n:>10,}: threefry {row['threefry']*1e3:7.2f} ms  "
              f"pallas {row['pallas']*1e3:7.2f} ms  "
              f"ratio {row['pallas_speedup']:.2f}x", flush=True)
    out_path = pathlib.Path(__file__).parent / "mask_crossover.jsonl"
    with out_path.open("w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
