"""Channel-wise tensor parallelism over the "model" mesh axis.

The reference has no model parallelism — SURVEY.md §2b records DP plus
federated variants only — so this is a beyond-parity capability, built
the TPU-first way: no hand-written sharded layers. Parameters (and the
optimizer moments and BatchNorm statistics that mirror them) are
*annotated* with NamedShardings that split each weight's output-channel
(last) axis over the "model" axis, and XLA's SPMD partitioner (GSPMD)
partitions every conv/matmul and inserts the ICI collectives. One
sharding rule covers the whole zoo because the layer library is
uniformly channels-last (HWIO conv kernels, (in, out) dense kernels,
per-channel vectors — core.py docstring).

Composes with data parallelism on a 2-D ("data", "model") mesh: the
batch shards over "data", weights over "model", and XLA emits the
gradient allreduce over "data" and the activation gathers over "model".
Use when a model's weights/optimizer state/activations outgrow one
chip's HBM; for the reference zoo at 50x50 DP alone is faster — this
exists so the "model" axis is a real, tested capability rather than a
reserved name (mesh.py axis table).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu import mesh as meshlib


def has_model_axis(mesh: Mesh) -> bool:
    return (meshlib.MODEL_AXIS in mesh.axis_names
            and mesh.shape[meshlib.MODEL_AXIS] > 1)


def dp_tp_mesh(model: int, data: int | None = None) -> Mesh:
    """2-D ("data", "model") mesh: `model`-way TP, DP over the rest.

    The "model" axis is innermost (fastest-varying devices) so TP's
    activation gathers ride the shortest ICI hops, mirroring how
    TP-inside-DP meshes are laid out on real pods.
    """
    n = len(jax.devices())
    if model < 1 or n % model:
        raise ValueError(
            f"model-parallel degree {model} must divide the device "
            f"count ({n})")
    if data is None:
        data = n // model
    return meshlib.make_mesh({meshlib.DATA_AXIS: data,
                              meshlib.MODEL_AXIS: model})


def channel_spec(x, n_model: int) -> P:
    """The sharding rule: split the last (output-channel) axis over
    "model" when it divides evenly and is non-trivial; replicate
    everything else (scalars, the Dense(1) head, odd-sized leaves).

    Kept as the readable shape-form of the rule; `state_shardings`
    resolves through `CHANNEL_RULES` (partition.py) — the two are
    pinned equivalent by tests/test_partition.py.
    """
    shape = np.shape(x)
    if (len(shape) >= 1 and shape[-1] > 1 and shape[-1] % n_model == 0):
        return P(*([None] * (len(shape) - 1) + [meshlib.MODEL_AXIS]))
    return P()


def channel_rules():
    """The channel rule as a `partition.PartitionRules`: one catch-all
    whose right-aligned ``P("model")`` shards every leaf's LAST axis
    over "model" — divisibility fallback and scalar replication are the
    resolution layer's own semantics, so this reproduces `channel_spec`
    exactly while sharing the one resolution point."""
    from idc_models_tpu import partition

    return partition.PartitionRules(((r".*", P(meshlib.MODEL_AXIS)),))


def state_shardings(mesh: Mesh, tree):
    """NamedSharding pytree for a TrainState (or any param-shaped tree)
    under the channel rule. Optimizer moments share their parameter's
    shape, so the same per-leaf rule shards them consistently; scalar
    counters come out replicated. Resolved through partition.py — the
    regex->spec layer shared by train, federated, and serve."""
    return channel_rules().shardings(mesh, tree)


def place(mesh: Mesh, tree):
    """Put a pytree on the mesh under the channel rule (multi-process
    safe — each host feeds only its addressable shards)."""
    return jax.tree.map(
        lambda x, sh: meshlib.put_with_sharding(x, sh), tree,
        state_shardings(mesh, tree))
