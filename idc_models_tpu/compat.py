"""Version-compatibility shims for the jax API surface this framework
rides.

The framework targets current jax, where `shard_map` is top-level
(`jax.shard_map`) and the replication check is spelled `check_vma`.
Older runtimes (jax <= 0.4.x, e.g. a CPU-only CI container) ship the
same functionality as `jax.experimental.shard_map.shard_map` with the
check named `check_rep`. One definition here so every shard_map call
site — library and tests — works unchanged on both."""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
