"""Device-mesh construction and axis bookkeeping.

This is the foundation the rest of the framework compiles against — the
TPU-native replacement for the reference's `tf.distribute` strategy objects
(`MirroredStrategy` at dist_model_tf_vgg.py:115, device lists at
dist_model_tf_dense.py:16-24). Instead of a strategy that owns the step,
we build a `jax.sharding.Mesh` and express placement with `PartitionSpec`s;
XLA inserts the ICI/DCN collectives.

Axis conventions used throughout the framework:

- ``"data"``    batch / data-parallel axis (reference D1)
- ``"model"``   tensor-parallel axis — channel-wise weight sharding via
  GSPMD (tp.py, CLI --model-parallel); beyond reference parity
- ``"client"``  federated-client axis — one client per device (reference D3)
- ``"seq"``     sequence-parallel axis — long-context ring attention
  (ring_attention.py); beyond reference parity
"""

from __future__ import annotations

import contextlib
import os
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
CLIENT_AXIS = "client"
SEQ_AXIS = "seq"


def force_host_devices(n: int) -> None:
    """Ask XLA to expose `n` virtual CPU devices (must run before jax init).

    Test-time stand-in for a TPU pod, mirroring how the reference's federated
    code simulates clients inside one process (fed_model.py:184).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(kept)


def force_cpu_pod(n: int) -> None:
    """Force this process onto `n` virtual CPU devices.

    Must run before the first device query (backend creation). The ambient
    environment may point JAX_PLATFORMS at a real TPU chip and that env var
    is read too early to override from Python, so the platform is also
    flipped through jax.config — the XLA_FLAGS below are still honored
    because the CPU backend is only created on first use.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    force_host_devices(n)
    jax.config.update("jax_platforms", "cpu")
    # Initialize the backend now and confirm the pod actually materialized:
    # if a backend was already live, the platform flip above was silently
    # ignored and callers would otherwise run on whatever was there.
    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n:
        import warnings

        warnings.warn(
            f"force_cpu_pod({n}) ineffective: a jax backend was already "
            f"initialized ({len(devs)} {devs[0].platform} device(s)); "
            f"call it before any jax use", stacklevel=2)


def make_mesh(
    axes: dict[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named device mesh.

    ``axes`` maps axis name -> size; one size may be ``-1`` meaning "all
    remaining devices". Default is a 1-D data-parallel mesh over every
    visible device — the analogue of `MirroredStrategy()` enumerating GPUs.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes {axes}"
            )
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    grid = np.asarray(devices[:total], dtype=object).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def data_mesh(n: int | None = None) -> Mesh:
    """1-D data-parallel mesh (axis "data") over n (default: all) devices."""
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({DATA_AXIS: len(devs)}, devices=devs)


def client_mesh(n_clients: int | None = None) -> Mesh:
    """1-D federated mesh (axis "client"), one client per device."""
    devs = jax.devices()
    if n_clients is not None:
        devs = devs[:n_clients]
    return make_mesh({CLIENT_AXIS: len(devs)}, devices=devs)


def seq_mesh(n: int | None = None) -> Mesh:
    """1-D sequence-parallel mesh (axis "seq") over n (default: all)
    devices — the ring for `ring_attention` over context-sharded
    sequences."""
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({SEQ_AXIS: len(devs)}, devices=devs)


def data_seq_mesh(n_seq: int, n_data: int | None = None) -> Mesh:
    """2-D ("data", "seq") mesh: batch shards over "data", the sequence
    (ring-attention) axis over "seq". With n_data omitted, every
    remaining device joins the data axis — n_seq must then be a
    positive divisor of the device count (silently idling leftover
    devices would skew any throughput measurement; pass n_data
    explicitly to use a subset on purpose). Lay the seq axis innermost
    so ring hops ride ICI neighbors."""
    devs = jax.devices()
    if n_data is None:
        if n_seq < 1 or len(devs) % n_seq:
            raise ValueError(
                f"n_seq {n_seq} must be a positive divisor of the "
                f"device count ({len(devs)}); pass n_data explicitly "
                f"to deliberately use a device subset")
        n_data = len(devs) // n_seq
    return make_mesh({DATA_AXIS: n_data, SEQ_AXIS: n_seq},
                     devices=devs[:n_data * n_seq])


def largest_dividing_mesh(n_clients: int, n_devices: int | None = None) -> int:
    """The largest device count <= n_devices that divides n_clients —
    the mesh size for k-clients-per-device programs whose aggregation
    cannot absorb weight-0 padding (the unweighted secure mean)."""
    if n_devices is None:
        n_devices = len(jax.devices())
    return max(d for d in range(1, min(n_clients, n_devices) + 1)
               if n_clients % d == 0)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding for `spec` over `mesh` (e.g. sharding(mesh, "data"))."""
    return NamedSharding(mesh, P(*spec))


def batch_seq_spec(mesh: Mesh, axis: str = SEQ_AXIS,
                   trailing: int = 2) -> P:
    """THE sequence-parallel activation layout, defined once: batch over
    every non-`axis` mesh axis, the sequence dimension over `axis`,
    `trailing` unsharded dims after it. Shared by the ring op's
    shard_map specs ([B,T,H,D]: trailing=2), the attention model's
    residual-stream pin ([B,T,E]: trailing=1), and the decode cache
    sharding — one definition so the three surfaces cannot diverge.

    The "model" axis is excluded from the batch group: it is reserved
    for WEIGHT sharding (tp.py, partition.py rules), so activations
    and KV caches stay unsharded over it — params and KV shard
    independently on a ("data", "model", "seq") mesh."""
    bo = batch_axes(mesh, axis)
    return P(bo, axis, *([None] * trailing))


def batch_axes(mesh: Mesh, axis: str = SEQ_AXIS):
    """The axis group a leading batch dimension shards over on a
    sequence-parallel mesh: every axis except the ring `axis` and the
    weight-reserved "model" axis — None when no such axis exists. The
    one definition `batch_seq_spec` and the ring folds' shard_map
    specs share, so activations/KV and weights cannot end up fighting
    over "model"."""
    others = tuple(a for a in mesh.axis_names
                   if a not in (axis, MODEL_AXIS))
    return others if others else None


def batch_seq_sharding(mesh: Mesh, axis: str = SEQ_AXIS,
                       trailing: int = 2) -> NamedSharding:
    """`batch_seq_spec` as a NamedSharding — the one construction site
    for the [B, T, ...] activation/cache layout (the ring model's
    residual pin, ring_decode's cache layout, the serve engine's
    canonical cache spelling all call this)."""
    return NamedSharding(mesh, batch_seq_spec(mesh, axis, trailing))


def fsdp_tp_mesh(fsdp: int = 1, tp: int = 1, seq: int = 1) -> Mesh:
    """3-D ("data", "model", "seq") mesh for sharded LM configs: FSDP
    shards params + optimizer state over "data" (the batch axis — the
    gradient allreduce becomes reduce-scatter/all-gather), tensor
    parallelism shards them over "model" (partition.py rules), and
    "seq" carries the ring. Size-1 axes are kept in the mesh — the
    partition rules drop them at adaptation time, so one rule set
    serves every (fsdp, tp, seq) combination.

    Uses exactly fsdp*tp*seq devices — the degrees are the caller's
    EXPLICIT request (no -1/absorb axis), so leftover devices idle by
    design. Don't compare wall-clock against an all-devices
    `data_seq_mesh` run: the device counts differ; the sharded-config
    comparisons this mesh exists for are per-device CAPACITY
    (peak_hbm_bytes) and same-mesh step time (bench_lm_sharded)."""
    for name, v in (("fsdp", fsdp), ("tp", tp), ("seq", seq)):
        if v < 1:
            raise ValueError(f"{name} degree must be >= 1, got {v}")
    n = len(jax.devices())
    if fsdp * tp * seq > n:
        raise ValueError(
            f"mesh fsdp={fsdp} x tp={tp} x seq={seq} needs "
            f"{fsdp * tp * seq} devices, have {n}")
    return make_mesh({DATA_AXIS: fsdp, MODEL_AXIS: tp, SEQ_AXIS: seq})


def batch_axis(mesh: Mesh, axis: str | None = None) -> str:
    """The axis a leading batch dimension shards over: `axis` if given,
    else "data" when present, else the mesh's only axis (so eval and
    prefetch work on a "client" mesh too)."""
    if axis is not None:
        return axis
    if DATA_AXIS in mesh.axis_names:
        return DATA_AXIS
    if len(mesh.axis_names) == 1:
        return mesh.axis_names[0]
    raise ValueError(f"cannot infer batch axis from mesh axes "
                     f"{mesh.axis_names}; pass axis=...")


def put_with_sharding(a, sh: NamedSharding):
    """Host array -> device(s) under `sh`, multi-process safe.

    `jax.device_put` onto a sharding that spans other processes' devices
    runs a cross-process value-equality collective (and requires every
    process to hold the full array); production multi-host wants each
    host to feed only its local shards anyway. `make_array_from_callback`
    does exactly that: this process materializes only the index slices
    belonging to its addressable devices.
    """
    if isinstance(a, jax.Array) and a.sharding == sh:
        return a  # already placed — don't round-trip through host
    if sh.is_fully_addressable:
        return jax.device_put(a, sh)
    arr = np.asarray(a)
    return jax.make_array_from_callback(arr.shape, sh,
                                        lambda idx: arr[idx])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager installing `mesh` as the ambient mesh."""
    with mesh:
        yield mesh


def local_device_count() -> int:
    return jax.local_device_count()


def process_index() -> int:
    return jax.process_index()


def initialize_multihost(coordinator: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Initialize `jax.distributed` for multi-host (DCN) pods.

    Replaces the reference's implicit single-process assumption: the
    reference never runs multi-node (SURVEY.md §4); here multi-host is
    first-class — after this call, `jax.devices()` spans the pod and every
    mesh built above rides ICI within a host and DCN across hosts.
    No-ops when running single-process (e.g. tests, single-chip bench).
    """
    if num_processes is None and coordinator is None:
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
