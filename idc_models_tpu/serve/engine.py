"""Fixed-slot continuous-batching decode engine — the device half of the
serving subsystem.

PR 1's `Generator` (models/lm.py) serves ONE request start-to-finish:
ring prefill, then a fused scan emitting that request's tokens. Under
concurrent traffic that leaves every other request queued head-of-line
and the decode batch at 1. This engine applies iteration-level
scheduling (Orca) with slot recycling (vLLM): `n_slots` requests decode
TOGETHER, one batch row each, and whenever a row finishes (EOS, budget,
deadline) the scheduler drops a freshly prefilled request into the
vacated row while the other rows keep decoding — the batch never drains
to refill.

ALL per-slot state is device-resident and donated through the whole
serve loop: per-block ring caches `[S, t_max, H, D]` (one row per slot,
the training-layout ring sharding), last-token logits `[S, V]`, rng key
data `[S, 2]`, positions `[S]`, remaining token budgets `[S]`, and stop
ids `[S]`. The host keeps a SHADOW of positions/budgets it can update by
pure arithmetic from the fetched tokens — no per-window state fetch.
Three compiled programs drive the device:

- **masked fused window** — ONE dispatch emits up to W tokens for every
  slot: per scan step, each live slot splits its OWN rng stream, samples
  with the exact serial `pick` math (a `[1, V]` row per slot), and runs
  the shared per-token forward (`models/lm._token_forward`) with the
  batched ring fold (`ring_decode.make_batched_ring_decode`) — finished
  slots emit `pad_id` and their cache rows are bit-untouched. Budgets
  count down and EOS hits zero them ON DEVICE, so rows retire mid-window
  with no host in the loop; positions advance only while live.
- **prefill** — the SAME bucketed program the serial `Generator` runs
  (`models/lm._serving_fns`): prompts pad to `prefill_bucket` shapes
  with the true length traced, so admitting arbitrary prompt lengths
  compiles nothing new after warmup AND a request's prefill is
  bit-identical to a serial call's.
- **insert** — a jitted batch-axis scatter admitting one request: the
  fresh `[1, t_max, H, D]` caches, `[1, V]` logits, and the slot's
  position/budget/stop-id/key rows all land via `dynamic_update_slice`
  with the slot index TRACED — one executable for every slot, zero
  recompilation on recycle.

The window API is a TWO-DEEP PIPELINE: `begin_window` dispatches a
window and returns immediately (jax dispatch is async); `collect` blocks
on the PREVIOUS window's tokens. The scheduler admits and does its host
bookkeeping while the in-flight window computes — on the tunneled TPU
runtime this hides the ~4 ms dispatch the same way the PR-1 fused scan
hides per-token dispatch. `step_window` (= begin + collect) keeps the
synchronous contract for direct use.

Token parity (gated by tests/test_serve.py): because prefill, the
per-token forward, the fold (row-wise bit-equal to the scalar fold), and
the sampling rule are all the serial definitions, a request's output
through this engine is bit-identical to a serial `Generator` call with
the same prompt/seed — including a request admitted into a slot another
request vacated mid-run.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.observe import trace
from idc_models_tpu.models.lm import (
    _attn_residual, _chunk_batch_forward, _final_logits, _make_pick,
    _mlp_residual, _place_params, _project_qkv, _serve_config,
    _serving_fns, _token_forward, check_prefill_chunk,
    make_adapter_head_hook, prefill_bucket, prefill_buckets,
)
from idc_models_tpu.ring_decode import (
    make_batched_chunk_ring_decode, make_batched_ring_decode,
    make_paged_batched_chunk_ring_decode, make_paged_batched_ring_decode,
    make_paged_chunk_ring_decode,
)
from idc_models_tpu.serve.pages import PageAllocator, PageExhausted


def _key_data(rng) -> np.ndarray:
    """A request's rng as host uint32 key data. Integer seeds take the
    host fast path — bit-identical to `key_data(jax.random.key(seed))`
    under the default threefry2x32 impl (verified on first use), without
    the per-admission device dispatch + fetch an eager key build
    costs."""
    if isinstance(rng, (int, np.integer)):
        seed = int(rng)
        if seed < 0:
            raise ValueError(f"need a non-negative seed, got {seed}")
        if not _key_data._checked:
            probe = np.asarray(
                jax.random.key_data(jax.random.key(0x12345)))
            want = np.array([0, 0x12345], np.uint32)
            if not np.array_equal(probe, want):
                raise RuntimeError(
                    "non-threefry2x32 default PRNG: pass explicit "
                    "jax.random keys instead of integer seeds")
            _key_data._checked = True
        return np.array([(seed >> 32) & 0xffffffff, seed & 0xffffffff],
                        np.uint32)
    return np.asarray(jax.random.key_data(rng))


_key_data._checked = False


class _AotWindow:
    """AOT decode-window executable plus the static step count it was
    compiled at. The jit dispatch site passes the step count as the
    last positional argument (a `static_argnums` entry); a Compiled
    executable takes only the array arguments, so this shim drops it —
    after checking it MATCHES. A different window size is a different
    program: it falls through to the jitted function (which compiles
    it), exactly what a compile-cache miss means."""

    def __init__(self, exe, n_steps: int, base):
        self._exe = exe
        self._n = int(n_steps)
        self._base = base

    def __call__(self, *args):
        if int(args[-1]) != self._n:
            return self._base(*args)
        return self._exe(*args[:-1])


class _AotPrograms:
    """Per-engine dispatch-table proxy installed by a cache-backed
    warmup: program names the persistent compile cache covered resolve
    to AOT executables; everything else falls through to the shared
    jitted namespace. A proxy — never a mutation — because the
    underlying `_engine_fns`/`_serving_fns` namespaces are
    `lru_cache`-shared across every engine with the same config
    (canary clones, cluster replicas on one device): planting one
    engine's device-bound executables there would corrupt its
    siblings. Introspection (`cache_sizes`, `program_costs`) reaches
    the jitted originals through `_base`."""

    def __init__(self, base, overlay: dict):
        self._base = base
        self._overlay = dict(overlay)

    def __getattr__(self, name):
        ov = self.__dict__["_overlay"].get(name)
        if ov is not None:
            return ov
        return getattr(self.__dict__["_base"], name)


class _PendingPrefill:
    """Host-side record of one chunked prefill in flight: the prompt,
    the single-request caches being extended chunk by chunk, and where
    the next chunk starts (past any prefix-cache hit). On a PAGED
    engine `caches` is None (chunks write the slot's granted pool
    pages directly) and `pages` holds the grant, of which the first
    `shared` ids are prefix-cache pages this request only references."""

    __slots__ = ("prompt", "budget", "rng", "eos_id", "caches", "logits",
                 "next_start", "tag", "pages", "shared", "tid")

    def __init__(self, *, prompt, budget, rng, eos_id, caches, logits,
                 next_start, tag=None, pages=None, shared=0, tid=0):
        self.pages = pages
        self.shared = shared
        self.tid = tid
        self.prompt = prompt
        self.budget = budget
        self.rng = rng
        self.eos_id = eos_id
        self.caches = caches
        self.logits = logits
        self.next_start = next_start
        self.tag = tag


class _EngineFns(NamedTuple):
    init_caches: object
    init_scales: object
    window: object    # (params, caches, logits, kd, pos, rem, eos,
    #                    kscales, vscales, W); paged engines take the
    #                    page table after the pools
    insert: object    # (state..., new_caches, new_logits, slot, ...);
    #                   paged engines scatter scalars/logits only (the
    #                   prompt K/V is already in the pool)
    health: object    # (logits) -> [S] int32 fault code
    verify: object    # (params, state..., drafts, vlive) ->
    #                   (toks, n_emit, n_acc, state...); None unless
    #                   the engine was built with draft_k
    # paged-mode programs (None on contiguous engines): rewrite one
    # slot's page-table row, stamp granted decode pages' dequant
    # scales from a source page (int8), and the direct-to-pool chunk
    # prefill
    page_row: object = None
    stamp_scales: object = None
    prefill_chunk: object = None


# a last-token logit past this magnitude is corruption, not a model
# output: real logits live within a few hundred even on poorly scaled
# models, and the finite-garbage fault class (bit flips, a blown-up
# matmul) is exactly what a pure isfinite check is blind to
_HEALTH_LOGIT_LIMIT = 1e30
HEALTH_KINDS = {1: "nonfinite_logits", 2: "logit_magnitude"}


def _window_core(cfg, pick, pad_id, params, caches, logits, kd, pos,
                 remaining, eos, n_steps, step_fn, pin_state,
                 eff=None):
    """THE masked fused-window scan — sampling rule, rng advance,
    budget/EOS retirement — shared verbatim by the contiguous and the
    paged engines (only `step_fn`, the per-token forward + cache fold,
    differs), so paged token streams are bit-identical to contiguous
    ones by construction rather than by parallel maintenance.

    `eff` (None = identity) maps each step's base logits to the
    EFFECTIVE pick logits — the per-tenant adapter hook
    (models/lm.make_adapter_head_hook): the delta is applied at the
    token pick only, while the carried logits state stays base, so
    every stored row remains tenant-agnostic."""
    def body(carry, _):
        caches, logits, kd, pos, remaining = carry
        live = remaining > 0
        pl = logits if eff is None else eff(logits)
        if cfg.temperature == 0.0:
            # greedy consumes NO randomness (serial pick ignores its
            # key too) — skip the S per-slot threefry splits, which
            # otherwise dominate the per-step cost at small batch
            toks = jax.vmap(lambda lg: pick(lg[None, :], None)[0])(
                pl)
        else:
            pair = jax.vmap(jax.random.split)(
                jax.random.wrap_key_data(kd))        # [S, 2] keys
            # per-slot sampling over a [1, V] row — the EXACT serial
            # pick call shape, so seeded sampling matches bit-for-bit
            toks = jax.vmap(lambda lg, k: pick(lg[None, :], k)[0])(
                pl, pair[:, 1])
        toks = jnp.where(live, toks, pad_id).astype(jnp.int32)
        if cfg.temperature > 0.0:
            # the stream advances once per EMITTED token, same as the
            # serial decode loop's one split per step
            kd = jnp.where(live[:, None],
                           jax.random.key_data(pair[:, 0]), kd)
        new_logits, caches = step_fn(params, caches, toks, pos, live)
        logits = jnp.where(live[:, None], new_logits, logits)
        pos = jnp.where(live, pos + 1, pos)
        remaining = jnp.where(live, remaining - 1, remaining)
        hit = live & (eos >= 0) & (toks == eos)
        remaining = jnp.where(hit, 0, remaining)
        return (caches, logits, kd, pos, remaining), toks

    (caches, logits, kd, pos, remaining), toks = lax.scan(
        body, (caches, logits, kd, pos, remaining), None,
        length=n_steps)
    caches, logits = pin_state(caches, logits)
    return (jnp.moveaxis(toks, 0, 1), caches, logits, kd, pos,
            remaining)


def _verify_core(cfg, pick, pad_id, K, t_max, params, caches, logits,
                 kd, pos, remaining, eos, drafts, vlive, chunk_forward,
                 tok_forward, pin_state, eff=None):
    # SPECULATIVE VERIFY — one dispatch turns K drafted tokens per
    # slot into between 1 and K+1 EMITTED tokens per participating
    # slot:
    #   1. run all K drafts through the per-token forward widened to
    #      K positions (the batched chunk fold appends their K/V and
    #      attends with per-query causality), yielding the model's
    #      next-token logits after each draft prefix;
    #   2. accept the longest draft prefix the model itself would
    #      have emitted (the pick rule per position — greedy argmax,
    #      or the seeded sample along the request's exact key chain),
    #      then take the model's OWN pick at the first disagreement
    #      as a bonus token — so even a total draft miss emits
    #      exactly the token a 1-step window would, bit-identically;
    #   3. run ONE masked token step for the bonus (its K/V lands at
    #      pos + accepted, overwriting the rejected draft's row) —
    #      the logits every slot decodes from next, restoring the
    #      window invariant exactly.
    # Rejected-suffix cache rows beyond each slot's new frontier hold
    # dead draft K/V, masked out of every later attend by the
    # positional visibility rule and overwritten before they ever
    # become visible — the same discipline as the batched decode
    # path's dead rows. All accept/budget/EOS bookkeeping happens ON
    # DEVICE; the host learns the outcome from the fetched
    # (toks, n_emit, n_acc) rows. Shared verbatim by the contiguous
    # and paged engines — only the two forwards' cache folds differ.
    s_rows = drafts.shape[0]
    live = jnp.asarray(vlive, jnp.bool_) & (remaining > 0)
    L, caches = chunk_forward(params, caches, drafts, pos, live)
    # K+1 candidate distributions along the accepted path:
    # cand[:, 0] is the slot's incoming logits (predicting the first
    # draft position), cand[:, j] the logits after drafts[:, :j]
    cand = jnp.concatenate(
        [logits.astype(L.dtype)[:, None], L], axis=1)
    # the per-tenant adapter hook, applied to the CANDIDATE
    # distributions the picks see ([S, K+1, V] — one gather for all
    # K+1 positions); the stored state (`after`, the bonus logits)
    # stays base, same discipline as the window's per-step pick
    cand_p = cand if eff is None else eff(cand)
    if cfg.temperature == 0.0:
        flat = cand_p.reshape(-1, cand_p.shape[-1])
        g = jax.vmap(lambda lg: pick(lg[None, :], None)[0])(
            flat).reshape(s_rows, K + 1).astype(jnp.int32)
        kd_chain = None
    else:
        # the request's exact serial key chain: one split per
        # candidate step, token j sampled with split j's sub —
        # identical math and order to the fused window's per-step
        # vmapped split + pick
        def samp(kd_c, lg_j):
            pair = jax.vmap(jax.random.split)(
                jax.random.wrap_key_data(kd_c))
            t = jax.vmap(
                lambda lg, kk: pick(lg[None, :], kk)[0])(
                lg_j, pair[:, 1])
            kd_n = jax.random.key_data(pair[:, 0])
            return kd_n, (t, kd_n)

        _, (g_t, chain) = lax.scan(samp, kd,
                                   jnp.moveaxis(cand_p, 0, 1))
        g = jnp.moveaxis(g_t, 0, 1).astype(jnp.int32)
        kd_chain = jnp.moveaxis(chain, 0, 1)     # [S, K+1, 2]
    # accepted prefix length m, the bonus pick g[m], and the emitted
    # count n_f after budget + EOS truncation
    matches = drafts.astype(jnp.int32) == g[:, :K]
    m = jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1),
                axis=1)
    b = jnp.take_along_axis(g, m[:, None], axis=1)[:, 0]
    cand_n = jnp.where(live,
                       jnp.minimum(m + 1, remaining), 0)
    ar = jnp.arange(K + 1, dtype=jnp.int32)[None, :]
    drafts_ext = jnp.concatenate(
        [drafts.astype(jnp.int32),
         jnp.zeros((s_rows, 1), jnp.int32)], axis=1)
    emitted = jnp.where(
        ar < m[:, None], drafts_ext,
        jnp.where(ar == m[:, None], b[:, None], pad_id))
    is_eos = ((eos[:, None] >= 0) & (emitted == eos[:, None])
              & (ar < cand_n[:, None]))
    any_eos = jnp.any(is_eos, axis=1)
    first = jnp.argmax(is_eos, axis=1).astype(cand_n.dtype)
    n_f = jnp.where(any_eos, first + 1, cand_n)
    n_acc = jnp.minimum(m, n_f)
    toks = jnp.where(ar < n_f[:, None], emitted,
                     pad_id).astype(jnp.int32)
    # the bonus token's own masked step (appends at pos + m)
    bonus_live = live & (n_f == m + 1)
    bpos = jnp.clip(pos + m, 0, t_max - 1)
    b_logits, caches = tok_forward(params, caches, b, bpos,
                                   bonus_live)
    after = jnp.take_along_axis(
        cand, jnp.clip(n_f, 0, K)[:, None, None], axis=1)[:, 0]
    new_logits = jnp.where(bonus_live[:, None],
                           b_logits.astype(logits.dtype),
                           after.astype(logits.dtype))
    logits = jnp.where(live[:, None], new_logits, logits)
    pos = jnp.where(live, pos + n_f, pos)
    remaining = jnp.where(
        live, jnp.where(any_eos, 0, remaining - n_f), remaining)
    if kd_chain is not None:
        kd_take = jnp.take_along_axis(
            kd_chain, jnp.clip(n_f - 1, 0, K)[:, None, None],
            axis=1)[:, 0]
        kd = jnp.where(live[:, None], kd_take, kd)
    caches, logits = pin_state(caches, logits)
    return (toks, n_f.astype(jnp.int32), n_acc.astype(jnp.int32),
            caches, logits, kd, pos, remaining)


@functools.lru_cache(maxsize=16)
def _engine_fns(cfg, pad_id: int, quant: bool = False,
                draft_k: int | None = None) -> _EngineFns:
    """Compile-once engine programs per decode configuration — the same
    process-wide sharing discipline as `models/lm._serving_fns`: params
    are explicit arguments, so two engines with one config share every
    executable. With ``quant`` the batch caches hold int8 K/V plus
    per-(slot, head) float32 scales (one pair of [S, H] arrays per
    block): insert quantizes the prefilled float caches (absmax/127 per
    head) and the window's fold dequantizes by factoring the scales out
    of the contractions — see `ring_decode.make_batched_ring_decode`."""
    mesh, t_max = cfg.mesh, cfg.t_max
    head_dim = cfg.embed_dim // cfg.num_heads
    fold = make_batched_ring_decode(mesh, jit=False, quantized=quant)
    ln = core.layer_norm(cfg.embed_dim)
    pick = _make_pick(cfg)
    # the TRAILING-NONE-FREE spelling of the ring cache layout: jit
    # normalizes trailing Nones out of output PartitionSpecs, and the
    # jit cache keys on spec EQUALITY — P(None, "seq", None, None) and
    # P(None, "seq") describe one layout but are different keys, which
    # would recompile the window once when its input caches switch from
    # init_cache's spelling to a previous program's output (observed)
    cache_sh = meshlib.batch_seq_sharding(mesh, trailing=0)
    rep = meshlib.replicated(mesh)

    def pin_state(caches, logits):
        # every program returns the engine state under ONE canonical
        # sharding: jit executables are cached per input sharding, so
        # letting GSPMD re-derive layouts per program would make e.g.
        # insert-output caches a different cache key than init_cache's
        # and recompile the window once per producer (observed)
        caches = tuple(
            (lax.with_sharding_constraint(kc, cache_sh),
             lax.with_sharding_constraint(vc, cache_sh))
            for kc, vc in caches)
        return caches, lax.with_sharding_constraint(logits, rep)

    def init_caches(n_slots: int):
        # same zeroed layout as ring_decode.init_cache, but placed under
        # the engine's canonical (normalized) sharding spelling; int8
        # when quantized — HALF the HBM of the bf16 rows, which is what
        # lets n_slots scale at a fixed budget
        def mk():
            return meshlib.put_with_sharding(
                np.zeros((n_slots, t_max, cfg.num_heads, head_dim),
                         jnp.int8 if quant
                         else jnp.dtype(cfg.cache_dtype)), cache_sh)

        return tuple((mk(), mk()) for _ in range(cfg.num_blocks))

    def init_scales(n_slots: int):
        # per-(slot, head) dequant scales, one (k, v) pair per block;
        # () on the float path so every signature stays uniform
        if not quant:
            return ()

        def mk():
            return meshlib.put_with_sharding(
                np.zeros((n_slots, cfg.num_heads), np.float32), rep)

        return tuple((mk(), mk()) for _ in range(cfg.num_blocks))

    def masked_step(params, caches, tok, pos, live, scales):
        def block_fold(i, kc, vc, q, k, v):
            extra = (scales[i] if quant else ())
            return fold(kc, vc, q, k, v, pos, live, *extra)

        return _token_forward(cfg, ln, params, caches, tok, pos,
                              block_fold)

    def window_body(params, caches, logits, kd, pos, remaining, eos,
                    scales, adapters, tslot, n_steps):
        # the whole window is ONE device program, like the serial fused
        # scan — but each slot carries its own position, budget, and rng
        # stream, and dead slots ride along as bit-level no-ops.
        # `adapters` is () (no tenancy — the historical program, pytree
        # structure keeps the jit cache keys distinct) or the stacked
        # (u [T, V, r], v [T, r, V]) tenant adapter bank, gathered by
        # the traced per-slot tenant ids `tslot` — tenant ARRIVAL
        # PATTERNS are values, never shapes, so a mixed-tenant batch
        # stays one executable (gated by test)
        def step_fn(params, caches, toks, pos, live):
            return masked_step(params, caches, toks, pos, live, scales)

        eff = (make_adapter_head_hook(*adapters, tslot) if adapters
               else None)
        return _window_core(cfg, pick, pad_id, params, caches, logits,
                            kd, pos, remaining, eos, n_steps, step_fn,
                            pin_state, eff=eff)

    # eos (argnum 6), the dequant scales (argnum 7), the adapter bank
    # (argnum 8) and the tenant-slot ids (argnum 9) are read-only
    # across windows and deliberately NOT donated — the same device
    # arrays feed every window until an admission replaces them
    window = jax.jit(window_body, static_argnums=(10,),
                     donate_argnums=(1, 2, 3, 4, 5))

    def insert_body(caches, logits, kd, pos, rem, eos, tslot, scales,
                    new_caches, new_logits, slot, p_len, budget, eos_id,
                    tid, kd_row):
        # batch-axis scatter with the slot index (and every per-slot
        # scalar) TRACED: one compiled program admits any request into
        # any slot
        out, out_scales = [], []
        for i, ((kc, vc), (nk, nv)) in enumerate(zip(caches,
                                                     new_caches)):
            if quant:
                ks_row, vs_row = scales[i]
                nk, k_s = _quantize_row(nk)
                nv, v_s = _quantize_row(nv)
                ks_row = lax.dynamic_update_slice(ks_row, k_s[None],
                                                  (slot, 0))
                vs_row = lax.dynamic_update_slice(vs_row, v_s[None],
                                                  (slot, 0))
                out_scales.append((
                    lax.with_sharding_constraint(ks_row, rep),
                    lax.with_sharding_constraint(vs_row, rep)))
            kc = lax.dynamic_update_slice(kc, nk.astype(kc.dtype),
                                          (slot, 0, 0, 0))
            vc = lax.dynamic_update_slice(vc, nv.astype(vc.dtype),
                                          (slot, 0, 0, 0))
            out.append((kc, vc))
        logits = lax.dynamic_update_slice(
            logits, new_logits.astype(logits.dtype), (slot, 0))
        kd = lax.dynamic_update_slice(kd, kd_row[None], (slot, 0))
        pos = pos.at[slot].set(p_len)
        rem = rem.at[slot].set(budget)
        eos = eos.at[slot].set(eos_id)
        tslot = tslot.at[slot].set(tid)
        caches, logits = pin_state(tuple(out), logits)
        return (caches, logits, kd, pos, rem, eos, tslot,
                tuple(out_scales) if quant else ())

    def _quantize_row(x):
        # [1, t_max, H, D] float -> (int8 values, [H] per-head scale):
        # absmax/127 over every (position, dim) of the row, clamped so
        # an all-zero row (fresh cache tail) divides safely
        xf = x.astype(jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(xf), axis=(0, 1, 3)),
                        1e-8) / 127.0                      # [H]
        q = jnp.clip(jnp.round(xf / s[None, None, :, None]),
                     -127, 127).astype(jnp.int8)
        return q, s

    insert = jax.jit(insert_body,
                     donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))

    def health_body(logits):
        # per-slot fault codes in ONE tiny reduce + fetch ([S] int32):
        # 1 = non-finite logits, 2 = finite but magnitude-blown, 0 = ok.
        # Runs once per scheduler cycle when health checks are armed,
        # on the last-token logits every window reads next — the state
        # a poisoned slot corrupts first.
        lf = logits.astype(jnp.float32)
        nonfinite = jnp.any(~jnp.isfinite(lf), axis=1)
        huge = jnp.any(jnp.abs(lf) > _HEALTH_LOGIT_LIMIT, axis=1)
        return jnp.where(nonfinite, 1,
                         jnp.where(huge, 2, 0)).astype(jnp.int32)

    health = jax.jit(health_body)

    verify = None
    if draft_k is not None:
        K = int(draft_k)
        chunk_fold = make_batched_chunk_ring_decode(mesh, jit=False,
                                                    quantized=quant)

        def verify_body(params, caches, logits, kd, pos, remaining,
                        eos, scales, adapters, tslot, drafts, vlive):
            def chunk_forward(params, caches, drafts, pos, live):
                def block_chunk_fold(i, kc, vc, q, k, v):
                    extra = (scales[i] if quant else ())
                    return chunk_fold(kc, vc, q, k, v, pos, live,
                                      *extra)

                return _chunk_batch_forward(cfg, ln, params, caches,
                                            drafts, pos,
                                            block_chunk_fold)

            def tok_forward(params, caches, b, bpos, bonus_live):
                def block_tok_fold(i, kc, vc, q, k, v):
                    extra = (scales[i] if quant else ())
                    return fold(kc, vc, q, k, v, bpos, bonus_live,
                                *extra)

                return _token_forward(cfg, ln, params, caches, b,
                                      bpos, block_tok_fold)

            eff = (make_adapter_head_hook(*adapters, tslot)
                   if adapters else None)
            return _verify_core(cfg, pick, pad_id, K, t_max, params,
                                caches, logits, kd, pos, remaining,
                                eos, drafts, vlive, chunk_forward,
                                tok_forward, pin_state, eff=eff)

        verify = jax.jit(verify_body, donate_argnums=(1, 2, 3, 4, 5))

    return _EngineFns(init_caches, init_scales, window, insert, health,
                      verify)


class _DrafterFns(NamedTuple):
    init_caches: object   # (n_slots) -> per-block ring pairs
    insert: object        # (dcaches, new_caches, slot) — row scatter
    ingest: object        # (dparams, dcaches, toks, pos0, live)
    propose: object       # (dparams, dcaches, adapters, tslot, toks,
    #                        n_new, pos0, live) -> (dcaches, drafts)


@functools.lru_cache(maxsize=16)
def _drafter_fns(dcfg, pad_id: int, draft_k: int) -> _DrafterFns:
    """Compile-once LEARNED-DRAFTER programs (models/draft_lm.py) — the
    device half of batched proposal. The drafter keeps its own small
    per-slot ring KV caches ([S, t_max, Hd, Dd] at the DRAFT model's
    dims, positions mirroring the target's), and `propose` turns every
    running slot's un-ingested emitted tokens into `draft_k` greedy
    proposals in ONE dispatch: a chunk ingest of the pending tokens
    (`_chunk_batch_forward` + the batched chunk fold) followed by a
    K-1-step autoregressive scan of the shared per-token forward.

    The ingest chunk width is FIXED at C = draft_k + 1 — the most a
    verify emits per slot per cycle, so the steady state is one
    propose dispatch per cycle; a backlog (plain windows wider than C,
    a fresh admission's deferred token) drains through `ingest`
    rounds first. C also bounds the ring writes: the scheduler only
    proposes for slots with verify room (pos + K + 1 <= t_max), so
    every chunk splice and speculative append lands inside t_max, and
    positions past a slot's committed frontier hold dead K/V that the
    next ingest overwrites before the visibility mask could ever
    reveal it — the same dead-row discipline as the decode window.

    `adapters`/`tslot` are the per-tenant drafter HEADS (the PR 14
    traced-tid gather, models/lm.make_adapter_head_hook): tenant mixes
    steer a gather by VALUE, so mixed-tenant batches stay one
    executable. Greedy only — a draft is a proposal, not a sample, and
    the verify re-picks with the request's real rule either way."""
    mesh, t_max = dcfg.mesh, dcfg.t_max
    head_dim = dcfg.embed_dim // dcfg.num_heads
    C = int(draft_k) + 1
    K = int(draft_k)
    fold = make_batched_ring_decode(mesh, jit=False)
    chunk_fold = make_batched_chunk_ring_decode(mesh, jit=False)
    ln = core.layer_norm(dcfg.embed_dim)
    cache_sh = meshlib.batch_seq_sharding(mesh, trailing=0)

    def pin(caches):
        # same canonical-sharding discipline as _engine_fns.pin_state:
        # one spelling for every producer keeps one jit cache key
        return tuple(
            (lax.with_sharding_constraint(kc, cache_sh),
             lax.with_sharding_constraint(vc, cache_sh))
            for kc, vc in caches)

    def init_caches(n_slots: int):
        def mk():
            return meshlib.put_with_sharding(
                np.zeros((n_slots, t_max, dcfg.num_heads, head_dim),
                         jnp.dtype(dcfg.cache_dtype)), cache_sh)

        return tuple((mk(), mk()) for _ in range(dcfg.num_blocks))

    def chunk_step(params, caches, toks, pos0, live):
        def block_fold(i, kc, vc, q, k, v):
            return chunk_fold(kc, vc, q, k, v, pos0, live)

        return _chunk_batch_forward(dcfg, ln, params, caches, toks,
                                    pos0, block_fold)

    def ingest_body(params, caches, toks, pos0, live):
        # backlog drain: splice one C-chunk of pending tokens per live
        # row, logits discarded (only the FINAL chunk's feed a draft)
        _, caches = chunk_step(params, caches, toks, pos0, live)
        return pin(caches)

    ingest = jax.jit(ingest_body, donate_argnums=(1,))

    def propose_body(params, caches, adapters, tslot, toks, n_new,
                     pos0, live):
        # final chunk + autoregressive rollout, one program: the chunk
        # forward yields logits at EVERY position, so the last REAL
        # pending token's logits (index n_new - 1) seed draft 0 with
        # no extra dispatch; K - 1 masked token steps then extend the
        # drafter's own stream speculatively
        L, caches = chunk_step(params, caches, toks, pos0, live)
        idx = jnp.clip(n_new - 1, 0, C - 1)
        lg = jnp.take_along_axis(L, idx[:, None, None], axis=1)[:, 0]
        eff = (make_adapter_head_hook(*adapters, tslot) if adapters
               else None)

        def pick_tok(row):
            pl = row if eff is None else eff(row)
            return jnp.argmax(pl, axis=-1).astype(jnp.int32)

        d0 = pick_tok(lg)
        front = pos0 + n_new

        def step(carry, j):
            caches, cur = carry
            p = jnp.clip(front + j, 0, t_max - 1)

            def block_fold(i, kc, vc, q, k, v):
                return fold(kc, vc, q, k, v, p, live)

            lg2, caches = _token_forward(dcfg, ln, params, caches,
                                         cur, p, block_fold)
            return (caches, pick_tok(lg2)), cur

        (caches, last), ys = lax.scan(
            step, (caches, d0), jnp.arange(K - 1, dtype=jnp.int32))
        drafts = jnp.concatenate(
            [jnp.moveaxis(ys, 0, 1).astype(jnp.int32),
             last[:, None]], axis=1)
        return pin(caches), drafts

    propose = jax.jit(propose_body, donate_argnums=(1,))

    def insert_body(caches, new_caches, slot):
        # admission row scatter, slot TRACED — one executable for
        # every slot, the same recycle discipline as the target insert
        out = []
        for (kc, vc), (nk, nv) in zip(caches, new_caches):
            kc = lax.dynamic_update_slice(kc, nk.astype(kc.dtype),
                                          (slot, 0, 0, 0))
            vc = lax.dynamic_update_slice(vc, nv.astype(vc.dtype),
                                          (slot, 0, 0, 0))
            out.append((kc, vc))
        return pin(tuple(out))

    insert = jax.jit(insert_body, donate_argnums=(0,))

    return _DrafterFns(init_caches, insert, ingest, propose)


@functools.lru_cache(maxsize=16)
def _paged_engine_fns(cfg, pad_id: int, quant: bool, draft_k,
                      page_size: int, n_pages: int,
                      n_slots: int) -> _EngineFns:
    """Compile-once programs for a PAGED engine configuration — the
    paged twin of `_engine_fns`, same process-wide sharing discipline.
    The cache state is a per-block page POOL `[n_pages, page_size, H,
    D]` (K and V) shared by every slot plus ONE `[S, t_max/page_size]`
    int32 page table; the window/verify/chunk programs resolve slot
    positions through the table via gather (the page-table-indirect
    folds in ring_decode.py), and the sampling/retirement/accept math
    is the SAME `_window_core`/`_verify_core` the contiguous programs
    run — paged outputs are bit-identical to contiguous ones on a
    1-device mesh because only the cache indirection differs. With
    ``quant`` the pools hold int8 pages with per-(page, head) float32
    scales: finer-grained than the contiguous per-slot scales, so int8
    parity is gated on determinism + bounded drift, not bits
    (docs/LONG_CONTEXT.md "Paged KV")."""
    mesh, t_max = cfg.mesh, cfg.t_max
    head_dim = cfg.embed_dim // cfg.num_heads
    l_pages = t_max // page_size
    fold = make_paged_batched_ring_decode(mesh, page_size=page_size,
                                          jit=False, quantized=quant)
    pchunk_fold = make_paged_chunk_ring_decode(
        mesh, page_size=page_size, jit=False, quantized=quant)
    ln = core.layer_norm(cfg.embed_dim)
    pick = _make_pick(cfg)
    pool_sh = meshlib.sharding(mesh, meshlib.SEQ_AXIS)
    rep = meshlib.replicated(mesh)

    def pin_state(pools, logits):
        # one canonical sharding spelling for every program's outputs,
        # same jit-cache-stability discipline as the contiguous
        # pin_state
        pools = tuple(
            (lax.with_sharding_constraint(kp, pool_sh),
             lax.with_sharding_constraint(vp, pool_sh))
            for kp, vp in pools)
        return pools, lax.with_sharding_constraint(logits, rep)

    def pin_scales(scales):
        return tuple((lax.with_sharding_constraint(ks, rep),
                      lax.with_sharding_constraint(vs, rep))
                     for ks, vs in scales)

    def init_caches(_n_slots: int):
        # the POOL replaces the per-slot rows: page count — not slot
        # count — is what a fixed HBM budget buys, which is the whole
        # capacity story
        def mk():
            return meshlib.put_with_sharding(
                np.zeros((n_pages, page_size, cfg.num_heads, head_dim),
                         jnp.int8 if quant
                         else jnp.dtype(cfg.cache_dtype)), pool_sh)

        return tuple((mk(), mk()) for _ in range(cfg.num_blocks))

    def init_scales(_n_slots: int):
        if not quant:
            return ()

        def mk():
            return meshlib.put_with_sharding(
                np.zeros((n_pages, cfg.num_heads), np.float32), rep)

        return tuple((mk(), mk()) for _ in range(cfg.num_blocks))

    def masked_step(params, pools, pt, tok, pos, live, scales):
        def block_fold(i, kp, vp, q, k, v):
            extra = (scales[i] if quant else ())
            return fold(kp, vp, pt, q, k, v, pos, live, *extra)

        return _token_forward(cfg, ln, params, pools, tok, pos,
                              block_fold)

    def window_body(params, pools, pt, logits, kd, pos, remaining,
                    eos, scales, adapters, tslot, n_steps):
        def step_fn(params, pools, toks, pos, live):
            return masked_step(params, pools, pt, toks, pos, live,
                               scales)

        eff = (make_adapter_head_hook(*adapters, tslot) if adapters
               else None)
        return _window_core(cfg, pick, pad_id, params, pools, logits,
                            kd, pos, remaining, eos, n_steps, step_fn,
                            pin_state, eff=eff)

    # pt (argnum 2), eos, the scales, the adapter bank and the tenant-
    # slot ids are read-only across windows and NOT donated —
    # page-table rewrites go through the page_row program at grant
    # time only
    window = jax.jit(window_body, static_argnums=(11,),
                     donate_argnums=(1, 3, 4, 5, 6))

    def insert_body(logits, kd, pos, rem, eos, tslot, new_logits, slot,
                    p_len, budget, eos_id, tid, kd_row):
        # the paged admission scatter touches NO cache state: the
        # prompt's K/V already sits in the slot's granted pages
        # (written there by the direct-to-pool chunk program), so
        # admitting a request is a handful of scalar/row updates
        logits = lax.dynamic_update_slice(
            logits, new_logits.astype(logits.dtype), (slot, 0))
        kd = lax.dynamic_update_slice(kd, kd_row[None], (slot, 0))
        pos = pos.at[slot].set(p_len)
        rem = rem.at[slot].set(budget)
        eos = eos.at[slot].set(eos_id)
        tslot = tslot.at[slot].set(tid)
        return (lax.with_sharding_constraint(logits, rep), kd, pos,
                rem, eos, tslot)

    insert = jax.jit(insert_body, donate_argnums=(0, 1, 2, 3, 4, 5))

    def page_row_body(pt, slot, row, rem, kill):
        # one program serves both grant-time rewrites (kill=0) and the
        # release-time KILL (kill=1, row=-1s): a released slot's device
        # budget must hit zero IN THE SAME dispatch its page-table row
        # clears, because its freed pages may be re-granted before the
        # row's leftover device budget runs out — a still-live zombie
        # row appending through a stale table would corrupt the new
        # owner's pages (the contiguous mode's harmless-ride-along
        # contract does NOT transfer to a shared pool)
        pt = lax.dynamic_update_slice(pt, row[None].astype(pt.dtype),
                                      (slot, 0))
        rem = jnp.where(kill > 0, rem.at[slot].set(0), rem)
        return lax.with_sharding_constraint(pt, rep), rem

    page_row = jax.jit(page_row_body, donate_argnums=(0, 3))

    stamp_scales = None
    if quant:
        def stamp_body(scales, src, dst):
            # copy the source page's per-head scale onto freshly
            # granted decode pages (dst padded with n_pages = OOB,
            # dropped): decode appends quantize with their page's
            # scale, and a fresh page has no content to derive one
            # from yet
            out = []
            for ks, vs in scales:
                kv = jnp.broadcast_to(ks[src][None],
                                      (dst.shape[0], ks.shape[1]))
                vv = jnp.broadcast_to(vs[src][None],
                                      (dst.shape[0], vs.shape[1]))
                out.append((ks.at[dst].set(kv, mode="drop",
                                           unique_indices=True),
                            vs.at[dst].set(vv, mode="drop",
                                           unique_indices=True)))
            return pin_scales(tuple(out))

        stamp_scales = jax.jit(stamp_body, donate_argnums=(0,))

    def chunk_body(params, pools, pt, scales, slot, tokens, start,
                   p_end):
        # one prompt CHUNK through every block, written STRAIGHT into
        # the slot's granted pool pages — the paged engine's admission
        # path never materializes a contiguous [1, t_max] cache.
        # Structure mirrors models/lm.chunk_body with the paged chunk
        # fold (page splice + gathered per-query attend + ring merge)
        # in place of the contiguous one; `slot` is traced, so one
        # executable serves every slot and every chunk incl. the
        # ragged tail.
        b, c = tokens.shape
        pt_row = lax.dynamic_slice(pt, (slot, 0), (1, l_pages))
        pos_tab = lax.dynamic_slice_in_dim(params["pos"], start, c,
                                           axis=0)
        h = jnp.take(params["embed"], tokens, axis=0) + pos_tab
        new_pools, new_scales = [], []
        for i in range(cfg.num_blocks):
            p = params[f"block{i}"]
            kp, vp = pools[i]
            q, k, v = _project_qkv(cfg, ln, p, h, (c,))
            if quant:
                ks, vs = scales[i]
                o, kp, vp, ks, vs = pchunk_fold(kp, vp, pt_row, q, k,
                                                v, start, p_end, ks,
                                                vs)
                new_scales.append((ks, vs))
            else:
                o, kp, vp = pchunk_fold(kp, vp, pt_row, q, k, v,
                                        start, p_end)
            h = _attn_residual(p, h, o.reshape(b, c, cfg.embed_dim))
            h = _mlp_residual(ln, p, h)
            new_pools.append((kp, vp))
        h_last = lax.dynamic_slice_in_dim(h, p_end - start - 1, 1,
                                          axis=1)[:, 0]
        logits = _final_logits(ln, params, h_last)
        pools, logits = pin_state(tuple(new_pools), logits)
        return (logits, pools,
                pin_scales(tuple(new_scales)) if quant else ())

    prefill_chunk = jax.jit(chunk_body, donate_argnums=(1, 3))

    def health_body(logits):
        lf = logits.astype(jnp.float32)
        nonfinite = jnp.any(~jnp.isfinite(lf), axis=1)
        huge = jnp.any(jnp.abs(lf) > _HEALTH_LOGIT_LIMIT, axis=1)
        return jnp.where(nonfinite, 1,
                         jnp.where(huge, 2, 0)).astype(jnp.int32)

    health = jax.jit(health_body)

    verify = None
    if draft_k is not None:
        K = int(draft_k)
        pbchunk_fold = make_paged_batched_chunk_ring_decode(
            mesh, page_size=page_size, jit=False, quantized=quant)

        def verify_body(params, pools, pt, logits, kd, pos, remaining,
                        eos, scales, adapters, tslot, drafts, vlive):
            def chunk_forward(params, pools, drafts, pos, live):
                def block_chunk_fold(i, kp, vp, q, k, v):
                    extra = (scales[i] if quant else ())
                    return pbchunk_fold(kp, vp, pt, q, k, v, pos,
                                        live, *extra)

                return _chunk_batch_forward(cfg, ln, params, pools,
                                            drafts, pos,
                                            block_chunk_fold)

            def tok_forward(params, pools, b, bpos, bonus_live):
                def block_tok_fold(i, kp, vp, q, k, v):
                    extra = (scales[i] if quant else ())
                    return fold(kp, vp, pt, q, k, v, bpos, bonus_live,
                                *extra)

                return _token_forward(cfg, ln, params, pools, b, bpos,
                                      block_tok_fold)

            eff = (make_adapter_head_hook(*adapters, tslot)
                   if adapters else None)
            return _verify_core(cfg, pick, pad_id, K, t_max, params,
                                pools, logits, kd, pos, remaining,
                                eos, drafts, vlive, chunk_forward,
                                tok_forward, pin_state, eff=eff)

        verify = jax.jit(verify_body, donate_argnums=(1, 3, 4, 5, 6))

    return _EngineFns(init_caches, init_scales, window, insert, health,
                      verify, page_row, stamp_scales, prefill_chunk)


class SlotEngine:
    """`n_slots` concurrent decode rows over one parameter tree.

    The host-side contract: `free_slots()` lists vacant rows;
    `admit(slot, prompt, budget, ...)` prefills and scatters a request
    into a row; `begin_window`/`collect` run the two-deep pipelined
    masked windows (`step_window` is the synchronous pair); `finished`/
    `release` recycle rows. Scheduling policy (queueing, deadlines,
    interleave) lives in serve/scheduler.py — this class owns only the
    device state machine.

    The host never fetches per-slot state: positions and budgets are
    shadowed by arithmetic on the fetched token rows (the device rule —
    live steps are a prefix, EOS zeroes the budget — is replayed
    exactly), so a window costs ONE host transfer: its tokens.
    """

    def __init__(self, params, *, embed_dim: int, num_heads: int,
                 num_blocks: int, t_max: int, n_slots: int = 4,
                 mesh=None, cache_dtype=jnp.bfloat16,
                 block_impl: str = "jnp", temperature: float = 0.0,
                 top_k: int | None = None, pad_id: int = 0,
                 eos_id: int | None = None,
                 prefill_chunk: int | None = None,
                 prefix_cache=None, kv_dtype: str | None = None,
                 draft_k: int | None = None,
                 kv_page_size: int | None = None,
                 kv_pages: int | None = None,
                 kv_decode_reserve: int | None = None,
                 adapter_bank=None, partition_rules=None,
                 draft_model=None, draft_partition_rules=None):
        if n_slots < 1:
            raise ValueError(f"need n_slots >= 1, got {n_slots}")
        # paged KV mode (ISSUE 11): the per-slot [t_max, H, D] ring
        # rows are replaced by a pool of kv_pages fixed-size pages plus
        # per-slot page tables — HBM holds tokens actually resident,
        # not slots' worst cases. kv_decode_reserve bounds how many
        # decode tokens are PRE-reserved at admission (default: the
        # full budget — never exhausts mid-decode); a smaller reserve
        # admits more optimistically and grows grants mid-decode,
        # which can exhaust honestly (scheduler quarantine).
        if (kv_page_size is None) != (kv_pages is None):
            raise ValueError(
                "paged KV needs BOTH kv_page_size and kv_pages (or "
                "neither for the contiguous per-slot ring rows)")
        self.paged = kv_page_size is not None
        if self.paged:
            kv_page_size, kv_pages = int(kv_page_size), int(kv_pages)
            if prefill_chunk is None:
                raise ValueError(
                    "paged KV needs chunked prefill (prefill_chunk=C):"
                    " prompts stream straight into pool pages chunk by"
                    " chunk — there is no monolithic [1, t_max] cache "
                    "to insert from")
            if kv_page_size < 1 or t_max % kv_page_size:
                raise ValueError(
                    f"kv_page_size {kv_page_size} must be >= 1 and "
                    f"divide t_max {t_max} so logical pages tile the "
                    f"position space")
            if int(prefill_chunk) % kv_page_size:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} must be a multiple"
                    f" of kv_page_size {kv_page_size}: chunk "
                    f"boundaries must land on the page grid so "
                    f"completed pages are never rewritten (the prefix-"
                    f"cache sharing invariant)")
            if kv_pages * kv_page_size < t_max:
                raise ValueError(
                    f"kv_pages {kv_pages} x kv_page_size "
                    f"{kv_page_size} < t_max {t_max}: one full-length "
                    f"request could never be admitted")
            if kv_decode_reserve is not None and kv_decode_reserve < 1:
                raise ValueError(f"need kv_decode_reserve >= 1, got "
                                 f"{kv_decode_reserve}")
        elif kv_decode_reserve is not None:
            raise ValueError("kv_decode_reserve needs paged KV "
                             "(kv_page_size/kv_pages)")
        self.kv_page_size = kv_page_size
        self.kv_pages = kv_pages
        self.kv_decode_reserve = kv_decode_reserve
        # draft_k arms speculative decoding: the engine compiles ONE
        # extra fixed-shape program (verify at exactly K draft tokens
        # per slot) and exposes begin_verify as an alternative window
        # dispatch; None keeps the historical engine bit-for-bit
        if draft_k is not None:
            draft_k = int(draft_k)
            if not 1 <= draft_k <= t_max - 2:
                raise ValueError(
                    f"draft_k {draft_k} outside [1, t_max - 2]: a "
                    f"verify needs room for K drafts + the bonus "
                    f"token inside the {t_max}-slot cache")
        self.draft_k = draft_k
        # kv_dtype: None/"bf16" keeps the float ring cache rows
        # (cache_dtype, the historical path bit-for-bit); "int8" stores
        # quantized rows + per-(slot, head) scales — ~2x the slots per
        # HBM byte, with the accuracy caveat documented in
        # docs/LONG_CONTEXT.md
        if kv_dtype not in (None, "bf16", "int8"):
            raise ValueError(f"kv_dtype must be None, 'bf16' or 'int8', "
                             f"got {kv_dtype!r}")
        self.kv_int8 = kv_dtype == "int8"
        if prefix_cache is not None and prefill_chunk is None:
            raise ValueError(
                "a prefix cache needs chunked prefill (prefill_chunk=C):"
                " snapshots live on chunk boundaries and only the chunk "
                "program can extend a cached prefix")
        self._cfg = _serve_config(
            params, embed_dim=embed_dim, num_heads=num_heads,
            num_blocks=num_blocks, t_max=t_max, mesh=mesh,
            cache_dtype=cache_dtype, block_impl=block_impl,
            temperature=temperature, top_k=top_k)
        self.prefill_chunk = (None if prefill_chunk is None
                              else check_prefill_chunk(prefill_chunk,
                                                       t_max))
        self.prefix_cache = prefix_cache
        if (prefix_cache is not None
                and prefix_cache.chunk != self.prefill_chunk):
            raise ValueError(
                f"prefix cache chunk {prefix_cache.chunk} != engine "
                f"prefill_chunk {self.prefill_chunk}")
        cache_is_paged = bool(getattr(prefix_cache, "is_paged", False))
        if prefix_cache is not None and cache_is_paged != self.paged:
            raise ValueError(
                "prefix-cache flavor must match the engine: a paged "
                "engine shares pool pages with PagedPrefixCache "
                "snapshots; a contiguous engine stores array snapshots "
                "in PrefixCache")
        if prefix_cache is not None and not cache_is_paged:
            # store snapshots TRUNCATED to the prefix length (positions
            # past it are zeros by construction — storing the full
            # [1, t_max] row would inflate every snapshot's budget cost
            # by t_max/prefix); a hit pads back and re-places under the
            # ring sharding, so the chunk program sees exactly the
            # layout it was warmed with (fresh arrays — never the
            # stored master) and the resume is bit-identical
            from idc_models_tpu.ring_decode import cache_sharding

            sh = cache_sharding(self._cfg.mesh)
            pad_to = t_max

            def _pack(caches, n_tokens):
                return jax.tree.map(lambda a: a[:, :n_tokens], caches)

            def _unpack(caches):
                def grow(a):
                    a = jnp.asarray(a)
                    a = jnp.pad(a, ((0, 0), (0, pad_to - a.shape[1]),
                                    (0, 0), (0, 0)))
                    return meshlib.put_with_sharding(a, sh)

                return jax.tree.map(grow, caches)

            prefix_cache.set_packer(_pack, _unpack)
        # the "model" axis is legal WITH partition rules: weights shard
        # over it (registry.LM_RULES) while batch_seq_spec keeps the
        # slot/KV layout off it — params and KV shard independently.
        # Batch-bearing axes stay banned: requests prefill one at a
        # time and [1, P] batches cannot shard.
        non_seq = [a for a in self._cfg.mesh.axis_names
                   if a not in (meshlib.SEQ_AXIS, meshlib.MODEL_AXIS)
                   and self._cfg.mesh.shape[a] > 1]
        if non_seq:
            raise ValueError(
                f"serving mesh must be seq-only (plus an optional "
                f"'model' weight axis): requests prefill one at a time "
                f"([1, P] batches cannot shard over axes {non_seq}); "
                f"build the engine on mesh.seq_mesh(n) or "
                f"mesh.fsdp_tp_mesh(1, tp, seq)")
        if (meshlib.MODEL_AXIS in self._cfg.mesh.axis_names
                and self._cfg.mesh.shape[meshlib.MODEL_AXIS] > 1
                and partition_rules is None):
            raise ValueError(
                "a 'model' mesh axis without partition_rules would "
                "idle every device past the first ring: pass the "
                "model's rule set (models/registry.py "
                "get_partition_rules) so the params actually shard "
                "over it")
        self._sfns = _serving_fns(self._cfg)
        self._n_ring = self._cfg.mesh.shape[meshlib.SEQ_AXIS]
        if self.paged:
            if self.kv_pages % self._n_ring:
                raise ValueError(
                    f"kv_pages {self.kv_pages} must divide by the ring"
                    f" size {self._n_ring}: the pool shards over the "
                    f"page dim")
            self._efns = _paged_engine_fns(
                self._cfg, int(pad_id), self.kv_int8, self.draft_k,
                self.kv_page_size, self.kv_pages, n_slots)
        else:
            self._efns = _engine_fns(self._cfg, int(pad_id),
                                     self.kv_int8, self.draft_k)
        self._params = _place_params(params, self._cfg.mesh,
                                     rules=partition_rules)
        # kept for hot weight swap (swap_params): a candidate tree is
        # placed under the SAME mesh/rules so the swapped-in leaves
        # carry identical shardings and no program recompiles
        self._partition_rules = partition_rules
        self.t_max = t_max
        self.n_slots = n_slots
        self.pad_id = int(pad_id)
        self.eos_id = eos_id
        self.temperature = float(temperature)
        vocab = params["head"]["kernel"].shape[1]
        # the serving vocab, public: the scheduler's draft validation
        # bounds proposed ids by it, the CLI's --draft-ckpt gate
        # compares against it
        self.vocab = int(vocab)
        # dtype only — never np.asarray the head: on a real model that
        # is a multi-hundred-MB device→host fetch per engine build
        ldtype = jnp.result_type(params["head"]["kernel"].dtype)
        rep = meshlib.replicated(self._cfg.mesh)
        # per-tenant adapter bank (serve/tenancy.py, ISSUE 14): the
        # stacked [T, V, r]/[T, r, V] logit-adapter factors, placed
        # replicated ONCE and fed read-only to every window/verify —
        # the programs gather each slot's tenant row by the traced
        # tslot ids, so tenant mixes are values, never shapes
        self._adapters = ()
        self.n_tenants = 0
        if adapter_bank is not None:
            u = np.asarray(adapter_bank.u, np.float32)
            v = np.asarray(adapter_bank.v, np.float32)
            if (u.ndim != 3 or v.ndim != 3 or u.shape[1] != vocab
                    or v.shape != (u.shape[0], u.shape[2], vocab)):
                raise ValueError(
                    f"adapter bank shapes must be u [T, V, r] / "
                    f"v [T, r, V] with V = the model vocab {vocab}, "
                    f"got {u.shape} / {v.shape} — a tenant adapter "
                    f"trained against a different head cannot serve "
                    f"this model")
            self.n_tenants = u.shape[0]
            self._adapters = (meshlib.put_with_sharding(u, rep),
                              meshlib.put_with_sharding(v, rep))
        # learned drafter (models/draft_lm.py, ROADMAP 2): its own
        # small per-slot ring caches + the batched propose/ingest
        # programs, riding the same insert/recycle/export-import
        # lifecycle as the target's state
        self._dcfg = self._dfns = self._dsfns = None
        self._draft_partition_rules = draft_partition_rules
        if draft_model is None:
            if draft_partition_rules is not None:
                raise ValueError(
                    "draft_partition_rules without draft_model: the "
                    "rules shard the learned drafter's params — pass "
                    "draft_model (models/draft_lm.DraftLM.learned) or "
                    "drop the rules")
        else:
            if self.draft_k is None:
                raise ValueError(
                    "a draft_model needs draft_k: its proposals feed "
                    "the speculative verify program, which only exists "
                    "on a spec-armed engine — build with draft_k=K")
            dparams = draft_model.params
            dconfig = draft_model.config
            dvocab = int(dparams["embed"].shape[0])
            if dvocab != vocab:
                raise ValueError(
                    f"draft model vocab {dvocab} != target vocab "
                    f"{vocab}: speculation verifies draft token IDS "
                    f"against the target's own picks, so the two "
                    f"models must share one tokenizer/vocab — distill "
                    f"the drafter from THIS target "
                    f"(models/draft_lm.distill_draft_lm)")
            d_seq = int(dparams["pos"].shape[0])
            if d_seq < t_max:
                raise ValueError(
                    f"draft model position table {d_seq} < engine "
                    f"t_max {t_max}: the drafter's ring mirrors the "
                    f"target's positions up to t_max — distill with "
                    f"draft_config(seq_len >= t_max)")
            self._dcfg = _serve_config(
                dparams, embed_dim=dconfig["embed_dim"],
                num_heads=dconfig["num_heads"],
                num_blocks=dconfig["num_blocks"], t_max=t_max,
                mesh=self._cfg.mesh, cache_dtype=cache_dtype,
                block_impl=block_impl, temperature=0.0, top_k=None)
            self._dfns = _drafter_fns(self._dcfg, int(pad_id),
                                      self.draft_k)
            self._dsfns = _serving_fns(self._dcfg)
            self._dparams = _place_params(dparams, self._dcfg.mesh,
                                          rules=draft_partition_rules)
            self._dadapters = ()
            dad = getattr(draft_model, "adapters", None)
            if dad is not None:
                du = np.asarray(dad[0], np.float32)
                dv = np.asarray(dad[1], np.float32)
                if self.n_tenants and du.shape[0] != self.n_tenants:
                    raise ValueError(
                        f"drafter adapter bank has {du.shape[0]} "
                        f"tenant rows but the engine serves "
                        f"{self.n_tenants} tenants — the traced-tid "
                        f"gather indexes both banks by the same slot "
                        f"tenant ids")
                self._dadapters = (meshlib.put_with_sharding(du, rep),
                                   meshlib.put_with_sharding(dv, rep))
            self._dcaches = self._dfns.init_caches(n_slots)
            # host-side drafter stream bookkeeping: _dfront[s] tokens
            # of slot s's history are ingested into the drafter ring;
            # _dpend[s] holds emitted-but-not-yet-ingested tokens
            # (invariant: _dfront + len(_dpend) == the slot's history
            # length == its target position)
            self._dpend: list[list[int]] = [[] for _ in range(n_slots)]
            self._dfront = np.zeros(n_slots, np.int64)
        # device state — placed under the canonical shardings every
        # engine program pins its outputs to (one jit cache key for the
        # whole loop), donated through every window/insert
        self._caches = self._efns.init_caches(n_slots)
        self._logits = meshlib.put_with_sharding(
            np.zeros((n_slots, vocab), ldtype), rep)
        self._kd = meshlib.put_with_sharding(
            np.zeros((n_slots, 2), np.uint32), rep)
        self._pos = meshlib.put_with_sharding(
            np.zeros(n_slots, np.int32), rep)
        self._rem = meshlib.put_with_sharding(
            np.zeros(n_slots, np.int32), rep)
        self._eos = meshlib.put_with_sharding(
            np.full(n_slots, -1, np.int32), rep)
        # per-slot tenant ids ([S] int32, tid 0 = the default tenant):
        # always present (a tiny row) so the insert scatter has ONE
        # signature; it only steers the adapter gather when a bank is
        # armed
        self._tslot = meshlib.put_with_sharding(
            np.zeros(n_slots, np.int32), rep)
        self._scales = self._efns.init_scales(n_slots)
        # host shadows (never fetched back from device)
        self._pos_h = np.zeros(n_slots, np.int64)
        self._rem_h = np.zeros(n_slots, np.int64)
        self._eos_h = np.full(n_slots, -1, np.int64)
        self._occupied = np.zeros(n_slots, bool)
        self._pending = None     # (toks_dev, rem_snapshot, occ_snapshot)
        # rollup of the most recently COLLECTED verify dispatch
        # ({drafted, accepted, emitted, slots}); None after a plain
        # window — the scheduler's metrics hook reads it per collect
        self.last_spec = None
        # in-progress chunked prefills: slot -> _PendingPrefill. These
        # slots are RESERVED (excluded from free_slots, not yet decoded
        # by windows) until the final chunk lands and insert scatters
        # the request into the batch row.
        self._prefills: dict[int, _PendingPrefill] = {}
        # paged-mode state: the host free-list allocator, the device
        # page table ([S, t_max/page_size] int32, -1 = unallocated),
        # and per-slot grant bookkeeping (page ids + token capacity)
        self._alloc = None
        if self.paged:
            self._alloc = PageAllocator(self.kv_pages,
                                        self.kv_page_size)
            self._l_pages = t_max // self.kv_page_size
            self._pt = meshlib.put_with_sharding(
                np.full((n_slots, self._l_pages), -1, np.int32), rep)
            self._slot_pages: dict[int, list[int]] = {}
            self._alloc_tokens = np.zeros(n_slots, np.int64)
            if prefix_cache is not None:
                prefix_cache.bind(self._alloc, self.kv_page_bytes())

    # -- slot lifecycle -------------------------------------------------

    def free_slots(self) -> list[int]:
        """Slots safe to admit into NOW. A slot released after a window
        was dispatched (deadline cancel) stays excluded until that
        window is collected — its in-flight tokens would otherwise be
        attributed to the newly admitted request."""
        in_flight = (self._pending[1][1] if self._pending is not None
                     else None)
        return [s for s in range(self.n_slots)
                if not self._occupied[s]
                and s not in self._prefills
                and (in_flight is None or not in_flight[s])]

    def occupancy(self) -> float:
        return float(self._occupied.sum()) / self.n_slots

    def finished(self, slot: int) -> bool:
        return bool(self._occupied[slot]) and self._rem_h[slot] == 0

    def release(self, slot: int) -> None:
        """Vacate a slot (EOS/budget done, or a deadline cancel). The
        row's device state is left as-is: a cancelled row at worst
        decodes its bounded remaining budget as a dead ride-along, and
        the next admit's insert overwrites the full row (dead rows never
        append or influence live ones — gated by test). On a paged
        engine the slot is first KILLED on device (page-table row
        cleared + device budget zeroed in one dispatch) and only then
        are its page references returned — the freed pages may be
        re-granted immediately, and a cancelled row with leftover
        device budget writing through a stale table would corrupt the
        new owner (the contiguous ride-along contract does not
        transfer to a shared pool; gated by test). Pages a
        prefix-cache snapshot still holds survive via their
        refcounts."""
        self._occupied[slot] = False
        self._rem_h[slot] = 0
        if self._dfns is not None:
            # the drafter row's dead K/V stays, like the target row's:
            # the next admission's _draft_admit insert overwrites it
            self._dpend[slot] = []
            self._dfront[slot] = 0
        if self.paged:
            if slot in self._slot_pages:
                self._set_page_row(slot, [], kill=True)
            self._alloc.release(self._slot_pages.pop(slot, []))
            self._alloc_tokens[slot] = 0

    # -- mid-decode slot migration (elastic drain, ROADMAP 3) -----------

    @property
    def supports_slot_migration(self) -> bool:
        """True when a RUNNING slot's state can travel to a peer engine
        bit-exactly: contiguous float-KV rows only. Paged engines have
        no slot-granular KV export (pages belong to one shared pool and
        land through grant-time scatter, not a row insert), and int8
        rows would pass back through the insert path's quantization —
        neither can honor the bit-identity contract, so a drain on them
        finishes requests in place instead of migrating."""
        return not self.paged and not self.kv_int8

    def export_slot(self, slot: int) -> dict:
        """Snapshot a RUNNING slot as host numpy — the prefix
        registry's packed-KV handoff generalized past chunk boundaries
        to mid-decode: per-block K/V rows truncated to the slot's
        position, the last-token logits row, and the slot's raw rng KEY
        DATA mid-chain. The key data — not a seed — is the point: a
        seeded stream must resume exactly where the source's per-token
        splits left it for the migrated output to stay bit-identical to
        an unmigrated run (greedy consumes no randomness either way).
        A peer engine's `import_slot` resumes the request; the caller
        (scheduler/router) owns releasing this slot and the journal
        protocol around the gap.

        Needs the engine dispatch-idle (`Scheduler.quiesce()` is the
        safe point): after `begin_window` the host shadows lag the
        donated device state by one window, and a snapshot taken in
        that gap would pair post-window caches with pre-window
        positions."""
        if not self.supports_slot_migration:
            raise RuntimeError(
                "slot export needs a contiguous float-KV engine: paged "
                "pools have no slot-granular export program and int8 "
                "rows would re-quantize on import, breaking the "
                "bit-identity contract — drain this replica to "
                "completion instead of migrating")
        if self._pending is not None:
            raise RuntimeError(
                "export_slot with a window in flight would snapshot "
                "post-window caches against pre-window host shadows — "
                "quiesce() the scheduler first")
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.n_slots})")
        if not self._occupied[slot]:
            raise ValueError(f"slot {slot} is not occupied — only a "
                             f"running request has state to export")
        p = int(self._pos_h[slot])
        head_dim = self._cfg.embed_dim // self._cfg.num_heads
        snap = {
            "pos": p,
            "rem": int(self._rem_h[slot]),
            "eos": int(self._eos_h[slot]),
            "num_heads": self._cfg.num_heads,
            "head_dim": head_dim,
            "kd": np.asarray(self._kd[slot]).astype(np.uint32),
            "logits": np.asarray(self._logits[slot]),
            # truncated to the written positions (the packer idiom):
            # everything past `pos` in the source row is zeros the
            # import's pad re-creates, and masked out regardless
            "caches": tuple((np.asarray(kc[slot:slot + 1, :p]),
                             np.asarray(vc[slot:slot + 1, :p]))
                            for kc, vc in self._caches),
        }
        if self._dfns is not None:
            # the learned drafter's shadow state rides the same
            # handoff: ring rows truncated to the DRAFTER frontier
            # (everything past it is dead K/V) plus the host-side
            # frontier/pending-token shadows, so a migrated slot's
            # proposals are bit-identical to an unmigrated run
            df = int(self._dfront[slot])
            snap["draft"] = {
                "front": df,
                "pend": [int(t) for t in self._dpend[slot]],
                "num_heads": self._dcfg.num_heads,
                "head_dim": self._dcfg.embed_dim // self._dcfg.num_heads,
                "caches": tuple((np.asarray(kc[slot:slot + 1, :df]),
                                 np.asarray(vc[slot:slot + 1, :df]))
                                for kc, vc in self._dcaches),
            }
        return snap

    def import_slot(self, slot: int, snap: dict, *, tid: int = 0) -> None:
        """Adopt an exported slot snapshot into free `slot` through the
        NORMAL admission insert: the K/V rows pad back to `[1, t_max]`
        (`jnp.pad`, zeros past the position — exactly the layout the
        source row held) and land under this engine's ring sharding, so
        the executable is the one admission already compiled — zero new
        programs — and the resumed decode is bit-identical to never
        having moved (gated by test). The snapshot's position stands in
        for a fresh prefill's prompt length and its remaining budget
        for max_new_tokens; the raw key data resumes the rng chain
        mid-stream."""
        if not self.supports_slot_migration:
            raise RuntimeError(
                "slot import needs a contiguous float-KV engine (same "
                "restriction as export_slot) — this replica cannot "
                "adopt migrated slots")
        if self._pending is not None:
            raise RuntimeError(
                "import_slot with a window in flight — the caches were "
                "donated to the dispatch; quiesce()/collect first")
        if self._occupied[slot] or slot in self._prefills:
            raise ValueError(f"slot {slot} is not free")
        pos, rem, eos = int(snap["pos"]), int(snap["rem"]), int(snap["eos"])
        if rem < 1:
            raise ValueError(
                "snapshot has no remaining budget — the request already "
                "finished; deliver its Result instead of migrating it")
        if pos < 1 or pos + rem > self.t_max:
            raise ValueError(
                f"snapshot position {pos} + remaining budget {rem} does "
                f"not fit this engine's t_max {self.t_max} — migrate to "
                f"a replica with a cache at least as long as the source")
        head_dim = self._cfg.embed_dim // self._cfg.num_heads
        if (len(snap["caches"]) != self._cfg.num_blocks
                or snap["num_heads"] != self._cfg.num_heads
                or snap["head_dim"] != head_dim):
            raise ValueError(
                f"snapshot geometry (blocks={len(snap['caches'])}, "
                f"heads={snap['num_heads']}, head_dim="
                f"{snap['head_dim']}) does not match this engine "
                f"(blocks={self._cfg.num_blocks}, "
                f"heads={self._cfg.num_heads}, head_dim={head_dim}) — "
                f"slots only migrate between config-identical replicas")
        dsnap = snap.get("draft")
        if dsnap is None and self._dfns is not None:
            raise ValueError(
                "snapshot carries no learned-drafter state but this "
                "engine has a draft_model armed — resuming here would "
                "propose from an empty drafter cache and silently "
                "change acceptance; migrate between replicas with the "
                "same drafter configuration (or export from an engine "
                "with the drafter armed)")
        if dsnap is not None and self._dfns is None:
            raise ValueError(
                "snapshot carries learned-drafter state but this "
                "engine has no draft_model — its frontier and ring "
                "rows would be dropped and the resumed request would "
                "stop speculating; migrate between replicas with the "
                "same drafter configuration")
        if dsnap is not None:
            dhd = self._dcfg.embed_dim // self._dcfg.num_heads
            if (len(dsnap["caches"]) != self._dcfg.num_blocks
                    or dsnap["num_heads"] != self._dcfg.num_heads
                    or dsnap["head_dim"] != dhd):
                raise ValueError(
                    f"snapshot drafter geometry (blocks="
                    f"{len(dsnap['caches'])}, heads="
                    f"{dsnap['num_heads']}, head_dim="
                    f"{dsnap['head_dim']}) does not match this "
                    f"engine's draft model (blocks="
                    f"{self._dcfg.num_blocks}, heads="
                    f"{self._dcfg.num_heads}, head_dim={dhd}) — "
                    f"slots only migrate between config-identical "
                    f"replicas, drafter included")
        self._check_tid(tid)
        from idc_models_tpu.ring_decode import cache_sharding
        sh = cache_sharding(self._cfg.mesh)

        def _grow(a):
            a = jnp.pad(jnp.asarray(np.asarray(a), self._cfg.cache_dtype),
                        ((0, 0), (0, self.t_max - a.shape[1]),
                         (0, 0), (0, 0)))
            return meshlib.put_with_sharding(a, sh)

        caches1 = tuple((_grow(kc), _grow(vc))
                        for kc, vc in snap["caches"])
        logits1 = meshlib.put_with_sharding(
            np.asarray(snap["logits"])[None],
            meshlib.replicated(self._cfg.mesh))
        kd_row = np.asarray(snap["kd"], np.uint32).reshape(2)
        (self._caches, self._logits, self._kd, self._pos, self._rem,
         self._eos, self._tslot, self._scales) = self._efns.insert(
            self._caches, self._logits, self._kd, self._pos,
            self._rem, self._eos, self._tslot, self._scales,
            caches1, logits1, np.int32(slot), np.int32(pos),
            np.int32(rem), np.int32(eos), np.int32(tid), kd_row)
        self._pos_h[slot] = pos
        self._rem_h[slot] = rem
        self._eos_h[slot] = eos
        self._occupied[slot] = True
        if dsnap is not None:
            def _dgrow(a):
                a = jnp.pad(
                    jnp.asarray(np.asarray(a), self._dcfg.cache_dtype),
                    ((0, 0), (0, self.t_max - a.shape[1]),
                     (0, 0), (0, 0)))
                return meshlib.put_with_sharding(a, sh)

            drow = tuple((_dgrow(kc), _dgrow(vc))
                         for kc, vc in dsnap["caches"])
            self._dcaches = self._dfns.insert(self._dcaches, drow,
                                              np.int32(slot))
            self._dfront[slot] = int(dsnap["front"])
            self._dpend[slot] = [int(t) for t in dsnap["pend"]]

    def _validate_admit(self, slot, prompt, max_new_tokens, rng):
        """The one admission contract, shared by the monolithic and
        chunked paths: [1, P] int32 prompt, within-budget lengths, an
        rng when sampling, a genuinely free slot."""
        if self._occupied[slot]:
            raise ValueError(f"slot {slot} is occupied")
        if slot in self._prefills:
            raise ValueError(f"slot {slot} has a prefill in progress")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        if prompt.ndim != 2 or prompt.shape[0] != 1 or prompt.shape[1] < 1:
            raise ValueError(f"admit takes ONE non-empty [1, P] prompt, "
                             f"got shape {prompt.shape}")
        p_len = prompt.shape[1]
        if p_len > self.t_max:
            raise ValueError(f"prompt length {p_len} exceeds t_max "
                             f"{self.t_max}")
        if max_new_tokens < 1:
            raise ValueError(f"need max_new_tokens >= 1, got "
                             f"{max_new_tokens}")
        if p_len + max_new_tokens > self.t_max:
            raise ValueError(
                f"prompt {p_len} + max_new_tokens {max_new_tokens} "
                f"exceeds t_max {self.t_max} — the cache cannot grow at "
                f"decode time")
        if self.temperature > 0.0 and rng is None:
            raise ValueError("sampling (temperature > 0) needs an rng "
                             "key (or integer seed) per request")
        return prompt

    def _check_tid(self, tid: int) -> None:
        """With an adapter bank armed, an out-of-range tenant id would
        gather a CLAMPED tenant's adapter (jnp.take clamps OOB
        indices) — silently serving the wrong tenant's head; caught at
        admission instead. Without a bank the tslot row steers nothing
        and any id is inert bookkeeping."""
        if self.n_tenants and not 0 <= tid < self.n_tenants:
            raise ValueError(
                f"tenant id {tid} out of range [0, {self.n_tenants}): "
                f"the adapter bank was built with {self.n_tenants} "
                f"tenants")

    def _insert(self, slot, caches1, logits1, p_len, max_new_tokens,
                eos_id, rng, tid: int = 0, prompt=None) -> None:
        """Scatter a fully prefilled request into the batch row — the
        shared tail of both admission paths. `tid` is the request's
        tenant id (0 = default): a traced scalar into the tslot row,
        steering the window/verify adapter gather for this slot.
        `prompt` (the [P] token row) seeds the learned drafter's state
        for this slot when one is armed — both admission paths pass
        it; `import_slot` restores drafter state from its snapshot
        instead."""
        eos = self.eos_id if eos_id is None else eos_id
        eos = -1 if eos is None else int(eos)
        kd_row = (_key_data(rng) if rng is not None
                  else np.zeros(2, np.uint32))
        if self.paged:
            # the prompt K/V already lives in the slot's pages — the
            # paged insert is a scalar/row scatter only
            (self._logits, self._kd, self._pos, self._rem,
             self._eos, self._tslot) = self._efns.insert(
                self._logits, self._kd, self._pos, self._rem,
                self._eos, self._tslot, logits1, np.int32(slot),
                np.int32(p_len), np.int32(max_new_tokens),
                np.int32(eos), np.int32(tid), kd_row)
        else:
            (self._caches, self._logits, self._kd, self._pos, self._rem,
             self._eos, self._tslot, self._scales) = self._efns.insert(
                self._caches, self._logits, self._kd, self._pos,
                self._rem, self._eos, self._tslot, self._scales,
                caches1, logits1, np.int32(slot), np.int32(p_len),
                np.int32(max_new_tokens), np.int32(eos),
                np.int32(tid), kd_row)
        self._pos_h[slot] = p_len
        self._rem_h[slot] = max_new_tokens
        self._eos_h[slot] = eos
        self._occupied[slot] = True
        if self._dfns is not None and prompt is not None:
            self._draft_admit(slot, np.asarray(prompt, np.int32).ravel())

    def _draft_admit(self, slot: int, prompt: np.ndarray) -> None:
        """Seed the learned drafter's row for a fresh admission: prefill
        the prompt MINUS its last token through the drafter's own
        bucketed prefill (the draft-dim `_serving_fns` — compile-once,
        any length), scatter the row in, and leave the last prompt
        token PENDING. Deferring that token is what makes the drafter
        stateless beyond its ring: the propose program's chunk ingest
        always has >= 1 pending token whose position-indexed logits
        seed draft 0, so no per-slot drafter logits row exists to
        carry, migrate, or invalidate."""
        p_len = prompt.shape[0]
        if p_len <= 1:
            row = self._dsfns.init_caches(1)
            front = 0
        else:
            bucket = prefill_bucket(p_len - 1, self.t_max, self._n_ring)
            padded = np.zeros((1, bucket), np.int32)
            padded[:, :p_len - 1] = prompt[None, :p_len - 1]
            _, row = self._dsfns.prefill(self._dparams, padded,
                                         np.int32(p_len - 1))
            front = p_len - 1
        self._dcaches = self._dfns.insert(self._dcaches, row,
                                          np.int32(slot))
        self._dfront[slot] = front
        self._dpend[slot] = [int(prompt[-1])]

    def admit(self, slot: int, prompt, max_new_tokens: int, *,
              rng=None, eos_id: int | None = None, tag=None,
              tid: int = 0) -> None:
        """Prefill `prompt` ([P] or [1, P]) and scatter it into `slot`,
        while every other slot's state stays put. `rng` seeds this
        REQUEST's sampling stream — an integer seed or the exact key a
        serial `Generator.decode` call would take. May be called while a
        window is in flight: the insert lands after it, and the slot
        (vacant in the flying window) starts decoding on the next one.

        Without `prefill_chunk` this is one bucketed prefill dispatch +
        one insert. With it, the whole prompt still lands in ONE call —
        ceil(P/C) chunk dispatches driven to completion here — which is
        the convenience path; a scheduler that wants to interleave
        chunks with decode windows drives `start_prefill`/`prefill_step`
        itself.

        `tag` is an opaque request label (the scheduler passes the rid)
        stamped onto the prefill spans, tying them into the request's
        lifecycle chain; the span TREE parenting (under serve.admit)
        is unchanged."""
        if self.prefill_chunk is not None:
            self.start_prefill(slot, prompt, max_new_tokens, rng=rng,
                               eos_id=eos_id, tag=tag, tid=tid)
            while not self.prefill_step(slot):
                pass
            return
        prompt = self._validate_admit(slot, prompt, max_new_tokens, rng)
        self._check_tid(tid)
        p_len = prompt.shape[1]
        # host-side prompt prep (the eager-jnp equivalent costs ~6 tiny
        # device dispatches per ADMISSION — measured to be a third of
        # the whole serve loop's wall at smoke scale): numpy pad to the
        # prefill bucket, hand the jitted prefill the numpy array
        bucket = prefill_bucket(p_len, self.t_max, self._n_ring)
        with trace.span("serve.prefill", slot=slot, p_len=p_len,
                        bucket=bucket, rid=tag):
            padded = np.zeros((1, bucket), np.int32)
            padded[:, :p_len] = prompt
            logits1, caches1 = self._sfns.prefill(self._params, padded,
                                                  np.int32(p_len))
            self._insert(slot, caches1, logits1, p_len, max_new_tokens,
                         eos_id, rng, tid, prompt=prompt[0])

    # -- chunked prefill --------------------------------------------------

    def start_prefill(self, slot: int, prompt, max_new_tokens: int, *,
                      rng=None, eos_id: int | None = None,
                      tag=None, tid: int = 0) -> None:
        """Reserve `slot` and register a chunked prefill for `prompt`
        WITHOUT dispatching anything: each later `prefill_step(slot)`
        runs exactly one chunk (the scheduler interleaves one per decode
        window, so a 16k-token prompt no longer stalls in-flight decodes
        behind one monolithic dispatch). Consults the prefix cache for
        the longest cached prefix — the suffix is all that will prefill.
        The slot is excluded from `free_slots` until the final chunk's
        insert (or `cancel_prefill`)."""
        if self.prefill_chunk is None:
            raise RuntimeError("engine built without prefill_chunk")
        prompt = self._validate_admit(slot, prompt, max_new_tokens, rng)
        self._check_tid(tid)
        if self.paged:
            self._start_prefill_paged(slot, prompt, max_new_tokens,
                                      rng, eos_id, tag, tid)
            return
        start, caches, logits = 0, None, None
        if self.prefix_cache is not None:
            start, caches, logits = self.prefix_cache.lookup(prompt[0])
            start = min(start, prompt.shape[1])
        if caches is None:
            caches = self._sfns.init_caches(1)
        self._prefills[slot] = _PendingPrefill(
            prompt=prompt, budget=int(max_new_tokens), rng=rng,
            eos_id=eos_id, caches=caches, logits=logits,
            next_start=start, tag=tag, tid=tid)

    def _pages_for(self, p_len: int, budget: int) -> int:
        """Pages an admission reserves: the prompt plus the decode
        reservation (the full budget unless kv_decode_reserve bounds
        it), on the page grid."""
        eff = (budget if self.kv_decode_reserve is None
               else min(budget, self.kv_decode_reserve))
        tokens = min(p_len + eff, self.t_max)
        return -(-tokens // self.kv_page_size)

    def pages_for_admission(self, p_len: int, budget: int) -> int:
        """Pages an admission of (p_len, budget) would reserve — 0 on
        contiguous engines. The scheduler's per-tenant page-budget
        accounting unit (serve/tenancy.py): exact under the default
        full-budget decode reserve, the admission-time floor under an
        optimistic `kv_decode_reserve` (mid-decode grant growth is not
        re-charged — documented in docs/MULTITENANCY.md)."""
        if not self.paged:
            return 0
        return self._pages_for(p_len, budget)

    def can_admit_pages(self, p_len: int, budget: int) -> bool:
        """The scheduler's page-aware admission gate: True when pages
        for `p_len` prompt tokens plus the decode reservation exist
        (reclaiming LRU prefix-cache snapshots if the free list alone
        is short). Conservative — a prefix-cache hit at the actual
        admission can only REDUCE the fresh-page need — so a True here
        guarantees `start_prefill` succeeds. Always True on a
        contiguous engine (slot availability is the only gate there).

        Evictions only happen when they can actually make the head
        admissible: a blocked head re-asking every cycle must not
        grind the whole cache away for zero admission benefit, so the
        gate first checks how many pages eviction could genuinely
        free (snapshot pages no live slot shares)."""
        if not self.paged:
            return True
        need = self._pages_for(p_len, budget)
        free = self._alloc.free_count()
        if free >= need:
            return True
        if (self.prefix_cache is None
                or free + self.prefix_cache.reclaimable_pages() < need):
            return False
        self.prefix_cache.reclaim(need - free)
        return self._alloc.free_count() >= need

    def _set_page_row(self, slot: int, pages: list[int], *,
                      kill: bool = False) -> None:
        row = np.full(self._l_pages, -1, np.int32)
        row[:len(pages)] = pages
        self._pt, self._rem = self._efns.page_row(
            self._pt, np.int32(slot), row, self._rem,
            np.int32(1 if kill else 0))

    def _stamp_decode_scales(self, pages: list[int], src: int) -> None:
        """int8 pools: freshly granted decode pages inherit the slot's
        last content-bearing page's per-head scale — a fresh page has
        no content to derive one from, and the append path quantizes
        with its target page's scale."""
        if not self.kv_int8 or not pages:
            return
        dst = np.full(self._l_pages, self.kv_pages, np.int32)
        dst[:len(pages)] = pages
        self._scales = self._efns.stamp_scales(self._scales,
                                               np.int32(src), dst)

    def _start_prefill_paged(self, slot, prompt, max_new_tokens, rng,
                             eos_id, tag, tid=0) -> None:
        """Paged admission: grant pages for prompt + reservation (the
        prefix-cache hit contributes its pages SHARED — refcounted,
        read-only, zero-copy), write the slot's page-table row, and
        register the pending prefill; chunks then stream straight into
        the granted pages."""
        p_len = prompt.shape[1]
        start, shared, logits = 0, [], None
        if self.prefix_cache is not None:
            start, shared, logits = self.prefix_cache.lookup(prompt[0])
            shared = list(shared or [])
        if shared:
            # the slot takes its OWN reference on the snapshot's pages:
            # release() drops it symmetrically whether or not the
            # snapshot is evicted while this request runs
            self._alloc.retain(shared)
        fresh_n = self._pages_for(p_len, max_new_tokens) - len(shared)
        fresh = self._alloc.alloc(fresh_n)
        if (fresh is None and self.prefix_cache is not None
                and (self._alloc.free_count()
                     + self.prefix_cache.reclaimable_pages())
                >= fresh_n):
            self.prefix_cache.reclaim(fresh_n
                                      - self._alloc.free_count())
            fresh = self._alloc.alloc(fresh_n)
        if fresh is None:
            if shared:
                self._alloc.release(shared)
            raise PageExhausted(
                f"admission needs {fresh_n} fresh pages, only "
                f"{self._alloc.free_count()} free — gate admissions "
                f"on can_admit_pages()")
        pages = shared + fresh
        self._set_page_row(slot, pages)
        self._alloc_tokens[slot] = len(pages) * self.kv_page_size
        self._prefills[slot] = _PendingPrefill(
            prompt=prompt, budget=int(max_new_tokens), rng=rng,
            eos_id=eos_id, caches=None, logits=logits,
            next_start=start, tag=tag, pages=pages,
            shared=len(shared), tid=tid)

    def prefill_step(self, slot: int) -> bool:
        """Advance `slot`'s pending prefill by ONE chunk dispatch;
        returns True when the request is fully admitted (final chunk +
        insert happen together — the insert is a cheap scatter). Each
        completed full-chunk boundary snapshots into the prefix cache,
        so the NEXT request sharing the prefix prefills only its
        suffix."""
        pend = self._prefills.get(slot)
        if pend is None:
            raise ValueError(f"slot {slot} has no prefill in progress")
        p_len = pend.prompt.shape[1]
        c = self.prefill_chunk
        if pend.next_start >= p_len:
            # whole prompt served from the prefix cache (p_len on a
            # chunk boundary): nothing to prefill, insert directly
            done = True
        else:
            end = min(pend.next_start + c, p_len)
            with trace.span("serve.prefill_chunk", slot=slot,
                            start=pend.next_start, end=end,
                            p_len=p_len, rid=pend.tag):
                padded = np.zeros((1, c), np.int32)
                padded[:, :end - pend.next_start] = pend.prompt[
                    :, pend.next_start:end]
                if self.paged:
                    # direct-to-pool: the chunk program resolves the
                    # slot's pages through the table and writes K/V
                    # straight into them — no [1, t_max] intermediate
                    pend.logits, self._caches, new_scales = (
                        self._efns.prefill_chunk(
                            self._params, self._caches, self._pt,
                            self._scales, np.int32(slot), padded,
                            np.int32(pend.next_start), np.int32(end)))
                    if self.kv_int8:
                        self._scales = new_scales
                else:
                    pend.logits, pend.caches = self._sfns.prefill_chunk(
                        self._params, pend.caches, padded,
                        np.int32(pend.next_start), np.int32(end))
                pend.next_start = end
                if (self.prefix_cache is not None and end % c == 0):
                    if self.paged:
                        # the snapshot IS the slot's pages [0, end):
                        # page-aligned, fully written, never written
                        # again — sharing them costs refcounts, not
                        # copies
                        self.prefix_cache.insert(
                            pend.prompt[0, :end],
                            pend.pages[:end // self.kv_page_size],
                            pend.logits)
                    else:
                        self.prefix_cache.insert(pend.prompt[0, :end],
                                                 pend.caches,
                                                 pend.logits)
            done = pend.next_start >= p_len
        if done:
            del self._prefills[slot]
            if self.paged:
                self._slot_pages[slot] = pend.pages
                n_prompt = -(-p_len // self.kv_page_size)
                self._stamp_decode_scales(pend.pages[n_prompt:],
                                          pend.pages[n_prompt - 1])
            self._insert(slot, pend.caches, pend.logits, p_len,
                         pend.budget, pend.eos_id, pend.rng, pend.tid,
                         prompt=pend.prompt)
        return done

    def cancel_prefill(self, slot: int) -> None:
        """Drop a pending prefill (deadline hit while still chunking):
        the partial caches are discarded and the slot returns to
        free_slots immediately — nothing ever reached the batch row.
        A paged engine returns the grant to the allocator (snapshot-
        shared pages survive via their cache refs)."""
        pend = self._prefills.pop(slot, None)
        if pend is not None and self.paged and pend.pages:
            # the slot's device row is already dead (it never reached
            # insert), but its table row points at the dying grant —
            # clear it before the pages can be re-granted
            self._set_page_row(slot, [], kill=True)
            self._alloc.release(pend.pages)
            self._alloc_tokens[slot] = 0

    def prefilling(self) -> list[int]:
        """Slots with a chunked prefill in progress, admission order."""
        return list(self._prefills)

    # -- decode ---------------------------------------------------------

    def begin_window(self, n_steps: int) -> None:
        """Dispatch ONE fused masked window (async) — up to `n_steps`
        tokens per slot. `collect` returns its tokens; at most one
        window may be in flight."""
        if self._pending is not None:
            raise RuntimeError("a window is already in flight — "
                               "collect() it first")
        if n_steps < 1:
            raise ValueError(f"need n_steps >= 1, got {n_steps}")
        snapshot = (self._rem_h.copy(), self._occupied.copy(),
                    self._eos_h.copy())
        if self.paged:
            (toks, self._caches, self._logits, self._kd, self._pos,
             self._rem) = self._efns.window(
                self._params, self._caches, self._pt, self._logits,
                self._kd, self._pos, self._rem, self._eos,
                self._scales, self._adapters, self._tslot, n_steps)
        else:
            (toks, self._caches, self._logits, self._kd, self._pos,
             self._rem) = self._efns.window(
                self._params, self._caches, self._logits, self._kd,
                self._pos, self._rem, self._eos, self._scales,
                self._adapters, self._tslot, n_steps)
        self._pending = (toks, snapshot)

    def spec_room(self, slot: int) -> bool:
        """True when `slot` has cache room for a full verify — K draft
        appends plus the bonus token's append all land inside t_max.
        Slots without room (within draft_k tokens of the cache edge,
        hence within draft_k + 1 of finishing) must decode through
        plain windows instead; the scheduler's policy falls back for
        the whole batch so no slot starves behind its speculating
        neighbors."""
        if self.draft_k is None:
            return False
        return bool(self._pos_h[slot] + self.draft_k + 1 <= self.t_max)

    def propose_all(self):
        """LEARNED proposals for every speculating slot in ONE device
        round-trip: drain each slot's pending emitted tokens (queued by
        collect(), see `_note_emitted`) into the drafter's ring caches,
        then roll the drafter `draft_k` greedy steps for ALL qualifying
        slots in a single jitted dispatch. Returns `(drafts, live)` —
        int32 [n_slots, draft_k] proposals plus the bool mask of rows
        they are real for — or None when no slot qualifies this cycle.

        The steady state (every slot emitted <= draft_k + 1 tokens
        last cycle, the verify maximum) is exactly one `propose`
        dispatch; a deeper backlog (plain-window fallback cycles, a
        fresh admission's deferred prompt token) drains through
        fixed-width `ingest` rounds first, REMAINDER-FIRST per slot so
        every live slot's final chunk lands in the single shared final
        round with 1..C real tokens. Only slots with `spec_room` are
        proposed for — beyond keeping proposals useful, that bound is
        what keeps every chunk splice inside t_max (pos0 + C <= t_max
        needs pos + draft_k + 1 <= t_max)."""
        if self._dfns is None:
            raise RuntimeError(
                "propose_all() requires a learned drafter: build the "
                "engine with draft_model= (a models/draft_lm.DraftLM) "
                "— host-side drafters (NGramDrafter) propose via "
                "their own propose(history) instead")
        if self._pending is not None:
            raise RuntimeError("a window is already in flight — "
                               "collect() it first")
        C = self.draft_k + 1
        live = np.array([
            bool(self._occupied[s]) and self._rem_h[s] >= 1
            and len(self._dpend[s]) > 0 and self.spec_room(s)
            for s in range(self.n_slots)])
        if not live.any():
            return None
        pend = {int(s): np.asarray(self._dpend[s], np.int32)
                for s in np.flatnonzero(live)}
        offs = dict.fromkeys(pend, 0)
        rounds = max(-(-len(p) // C) for p in pend.values())
        with trace.span("serve.propose", slots=int(live.sum()),
                        rounds=rounds):
            for r in range(rounds - 1):
                left = rounds - r
                toks = np.zeros((self.n_slots, C), np.int32)
                pos0 = np.zeros(self.n_slots, np.int32)
                rlive = np.zeros(self.n_slots, bool)
                for s, p in pend.items():
                    remaining = len(p) - offs[s]
                    if remaining <= (left - 1) * C:
                        continue
                    n = remaining - (left - 1) * C
                    toks[s, :n] = p[offs[s]:offs[s] + n]
                    pos0[s] = self._dfront[s] + offs[s]
                    rlive[s] = True
                    offs[s] += n
                self._dcaches = self._dfns.ingest(
                    self._dparams, self._dcaches, toks, pos0, rlive)
            toks = np.zeros((self.n_slots, C), np.int32)
            pos0 = np.zeros(self.n_slots, np.int32)
            n_new = np.zeros(self.n_slots, np.int32)
            for s, p in pend.items():
                n = len(p) - offs[s]
                toks[s, :n] = p[offs[s]:]
                pos0[s] = self._dfront[s] + offs[s]
                n_new[s] = n
            self._dcaches, drafts = self._dfns.propose(
                self._dparams, self._dcaches, self._dadapters,
                self._tslot, toks, n_new, pos0, live)
            drafts = np.asarray(drafts)
        for s in pend:
            self._dfront[s] += len(self._dpend[s])
            self._dpend[s] = []
        return drafts.astype(np.int32), live

    def ensure_decode_room(self, n_tokens: int) -> list[int]:
        """Paged engines only (contiguous rooms are sized at admission
        — returns []): grow every occupied slot's page grant so the
        next dispatch can emit up to min(n_tokens, remaining budget)
        tokens without writing an unallocated page. Returns the slots
        that could NOT be granted after exhausting the free list and
        the prefix cache's reclaimable snapshots — the scheduler
        quarantines those (finish or retry honestly) BEFORE
        dispatching, so a starved slot can never corrupt a neighbor's
        pages (an unallocated append would be dropped, not misplaced,
        but the emitted token would be attention-blind to it — hence
        the hard gate). With the default full-budget reservation this
        is a no-op; it only grants when kv_decode_reserve admitted
        optimistically."""
        if not self.paged:
            return []
        failed = []
        ps = self.kv_page_size
        for slot in range(self.n_slots):
            if not self._occupied[slot] or self._rem_h[slot] < 1:
                continue
            target = int(self._pos_h[slot]
                         + min(int(n_tokens), int(self._rem_h[slot])))
            if target <= self._alloc_tokens[slot]:
                continue
            need = -(-(target - int(self._alloc_tokens[slot])) // ps)
            fresh = self._alloc.alloc(need)
            if (fresh is None and self.prefix_cache is not None
                    and (self._alloc.free_count()
                         + self.prefix_cache.reclaimable_pages())
                    >= need):
                self.prefix_cache.reclaim(need
                                          - self._alloc.free_count())
                fresh = self._alloc.alloc(need)
            if fresh is None:
                failed.append(slot)
                continue
            pages = self._slot_pages[slot]
            self._stamp_decode_scales(fresh, pages[-1])
            pages.extend(fresh)
            self._set_page_row(slot, pages)
            self._alloc_tokens[slot] = len(pages) * ps
        return failed

    def begin_verify(self, drafts, vlive, proposed=None) -> None:
        """Dispatch ONE speculative verify (async, collected like a
        window): `drafts` is int32 [n_slots, draft_k] and `vlive` bool
        [n_slots] marks the participating rows. Every vlive row must
        be occupied, have budget left, and satisfy `spec_room`;
        non-participating rows ride along bit-untouched. Each vlive
        row emits between 1 and draft_k + 1 tokens — the accepted
        draft prefix plus the model's own pick at the first
        disagreement — so a row whose drafts all miss still advances
        exactly one (bit-identical) token.

        `proposed` (bool [n_slots], default = vlive, must be a subset
        of it) marks the rows whose drafts came from a REAL drafter
        proposal rather than the scheduler's ride-along placeholder —
        only those rows enter the `last_spec` drafted/accepted ledger,
        so acceptance rate and tokens-per-dispatch score speculation
        itself, undiluted by slots that merely rode along for their
        one window-equivalent token."""
        if self.draft_k is None:
            raise RuntimeError("engine built without draft_k — "
                               "speculative decoding is not armed")
        if self._pending is not None:
            raise RuntimeError("a window is already in flight — "
                               "collect() it first")
        drafts = np.asarray(drafts, np.int32)
        vlive = np.asarray(vlive, bool)
        if drafts.shape != (self.n_slots, self.draft_k):
            raise ValueError(
                f"drafts must be [{self.n_slots}, {self.draft_k}], "
                f"got {drafts.shape}")
        if vlive.shape != (self.n_slots,):
            raise ValueError(f"vlive must be [{self.n_slots}], got "
                             f"{vlive.shape}")
        proposed = (vlive if proposed is None
                    else np.asarray(proposed, bool))
        if proposed.shape != vlive.shape or (proposed & ~vlive).any():
            raise ValueError("proposed must be a [n_slots] subset of "
                             "vlive")
        for s in np.flatnonzero(vlive):
            if not self._occupied[s] or self._rem_h[s] < 1:
                raise ValueError(f"verify slot {int(s)} is not "
                                 f"occupied with budget left")
            if not self.spec_room(int(s)):
                raise ValueError(
                    f"verify slot {int(s)} at pos {self._pos_h[s]} "
                    f"lacks room for {self.draft_k} drafts + the "
                    f"bonus before t_max {self.t_max}")
        snapshot = (self._rem_h.copy(), self._occupied.copy(),
                    self._eos_h.copy())
        if self.paged:
            (toks, n_emit, n_acc, self._caches, self._logits, self._kd,
             self._pos, self._rem) = self._efns.verify(
                self._params, self._caches, self._pt, self._logits,
                self._kd, self._pos, self._rem, self._eos,
                self._scales, self._adapters, self._tslot, drafts,
                vlive)
        else:
            (toks, n_emit, n_acc, self._caches, self._logits, self._kd,
             self._pos, self._rem) = self._efns.verify(
                self._params, self._caches, self._logits, self._kd,
                self._pos, self._rem, self._eos, self._scales,
                self._adapters, self._tslot, drafts, vlive)
        self._pending = (toks, snapshot, (n_emit, n_acc, vlive,
                                          proposed))

    def abort_window(self) -> None:
        """Discard an in-flight window without collecting it — the
        failure-cleanup hook (scheduler._abort_running): after an
        engine error the window's results are lost either way, but a
        window still marked in flight would wedge idle()/collect()
        forever. The host budget/position shadows keep their
        pre-dispatch values (the window never 'happened')."""
        self._pending = None

    def collect(self) -> dict[int, list[int]]:
        """Block on the in-flight window's tokens ({} if none) and
        replay the device retirement rule onto the host shadows: live
        steps are a prefix of the window (budgets only count down), and
        an EOS hit zeroes the remaining budget after emitting. Returns
        {slot: tokens emitted} for slots occupied when the window was
        dispatched."""
        # reset FIRST: a no-op collect (or a window's) must not leave a
        # previous verify's rollup answering for it — warmup's dead
        # verify would otherwise leak a zero-slot record into the
        # first real cycle's metrics
        self.last_spec = None
        if self._pending is None:
            return {}
        toks, (rem_before, occupied, eos_h), *spec = self._pending
        self._pending = None
        # the ONE host transfer — and the point where the serve loop
        # BLOCKS on the in-flight window's device execution, so it is
        # bracketed as device.sync for step-time attribution
        # (observe/profile.py DeviceTimeline; no-op span when no
        # tracer is armed)
        with trace.span("device.sync"):
            toks = np.asarray(toks)
            if spec:
                n_emit = np.asarray(spec[0][0])
                n_acc = np.asarray(spec[0][1])
        out = {}
        if spec:
            # verify collect: the device already applied budget + EOS
            # truncation (n_emit is the exact emitted count, EOS
            # inclusive); the host replays the same retirement rule on
            # its shadows from the fetched counts
            vlive, proposed = spec[0][2], spec[0][3]
            # ledger over PROPOSED rows only: ride-along placeholders
            # would dilute the acceptance figures operators tune by
            self.last_spec = {
                "drafted": int(proposed.sum()) * self.draft_k,
                "accepted": int(n_acc[proposed].sum()),
                "emitted": int(n_emit[proposed].sum()),
                "slots": int(proposed.sum()),
            }
            for s in range(self.n_slots):
                if not occupied[s]:
                    continue
                if not vlive[s]:
                    out[s] = []          # rode along bit-untouched
                    continue
                n = int(n_emit[s])
                row = [int(t) for t in toks[s, :n]]
                if eos_h[s] >= 0 and eos_h[s] in row:
                    self._rem_h[s] = 0
                else:
                    self._rem_h[s] = rem_before[s] - n
                self._pos_h[s] += n
                out[s] = row
            self._note_emitted(out)
            return out
        for s in range(self.n_slots):
            if not occupied[s]:
                continue
            n = int(min(rem_before[s], toks.shape[1]))
            row = [int(t) for t in toks[s, :n]]
            if eos_h[s] >= 0 and eos_h[s] in row:
                row = row[:row.index(int(eos_h[s])) + 1]
                self._rem_h[s] = 0
            else:
                self._rem_h[s] = rem_before[s] - len(row)
            self._pos_h[s] += len(row)
            out[s] = row
        self._note_emitted(out)
        return out

    def _note_emitted(self, out: dict[int, list[int]]) -> None:
        """Queue this cycle's emitted tokens for the learned drafter.
        The drafter's ring caches ingest them lazily — one chunked
        dispatch for ALL slots at the start of the next propose_all()
        — so collect() never touches the device on the drafter's
        behalf and spec-off serving pays nothing."""
        if self._dfns is None:
            return
        for s, row in out.items():
            if row:
                self._dpend[s].extend(row)

    def step_window(self, n_steps: int) -> dict[int, list[int]]:
        """Synchronous window: begin + collect in one call."""
        self.begin_window(n_steps)
        return self.collect()

    # -- resilience hooks -----------------------------------------------

    def slot_health(self) -> np.ndarray:
        """Per-slot fault codes ([n_slots] int32, see `HEALTH_KINDS`):
        0 healthy, 1 non-finite last-token logits, 2 finite but
        magnitude-blown. One tiny jitted reduce + one [S]-int fetch —
        the scheduler runs it once per cycle when health checks are
        armed, BEFORE the next window dispatch, so a poisoned slot is
        quarantined before a single token is sampled from its
        corrupted logits."""
        return np.asarray(self._efns.health(self._logits))

    def slot_invariants_ok(self, slot: int) -> bool:
        """Host-shadow sanity for one slot: position within the cache,
        budget non-negative. Free (no device traffic) — the scheduler
        folds it into the same per-cycle health pass."""
        return bool(0 <= self._pos_h[slot] <= self.t_max
                    and self._rem_h[slot] >= 0)

    def inject_slot_fault(self, slot: int, kind: str) -> None:
        """Fault-injection hook (serve/faults.py, default-off): corrupt
        `slot`'s last-token logits row in place — NaN for
        ``nan_logits``, huge-but-finite (1e32, past the health bound
        but inside every float dtype's range) for ``garbage_logits``.
        The host round-trip is fine here: this runs only when a fault
        plan fires, never on the clean path."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range "
                             f"[0, {self.n_slots})")
        try:
            val = {"nan_logits": float("nan"),
                   "garbage_logits": 1e32}[kind]
        except KeyError:
            raise ValueError(
                f"inject_slot_fault kind must be 'nan_logits' or "
                f"'garbage_logits', got {kind!r}") from None
        rep = meshlib.replicated(self._cfg.mesh)
        logits = np.array(self._logits)      # blocks on any in-flight window
        logits[slot, :] = val
        self._logits = meshlib.put_with_sharding(logits, rep)

    # -- hot weight rollout (ROADMAP 4) ---------------------------------

    def swap_params(self, params) -> None:
        """Hot-swap the serving weights. The candidate tree must match
        the live one leaf-for-leaf in name/shape/dtype — it is placed
        under the SAME mesh and partition rules, so every compiled
        program keys identically and the swap costs zero recompiles.
        Safe with a dispatch in flight: the dispatched window holds
        immutable references to the old leaves and lands its tokens
        untouched; the NEXT dispatch reads the new weights. In-flight
        slots keep their KV caches — their remaining tokens decode
        under the new weights (the zero-downtime contract: no slot
        dropped, no request re-prefilled)."""
        from idc_models_tpu import partition

        live = {n: (tuple(a.shape), jnp.result_type(a.dtype))
                for n, a in partition.tree_paths(self._params)}
        cand = {n: (tuple(np.shape(a)),
                    jnp.result_type(getattr(a, "dtype", np.asarray(a).dtype)))
                for n, a in partition.tree_paths(params)}
        if live != cand:
            only_live = sorted(set(live) - set(cand))
            only_cand = sorted(set(cand) - set(live))
            diff = sorted(n for n in set(live) & set(cand)
                          if live[n] != cand[n])
            raise ValueError(
                f"swap_params candidate does not match the serving "
                f"tree: live-only leaves {only_live}, candidate-only "
                f"{only_cand}, shape/dtype mismatches "
                f"{[(n, live[n], cand[n]) for n in diff]} — a rollout "
                f"swaps WEIGHTS, not architectures; rebuild the server "
                f"for a different model")
        self._params = _place_params(params, self._cfg.mesh,
                                     rules=self._partition_rules)

    def swap_adapters(self, u, v) -> None:
        """Per-tenant adapter hot-swap — the cheap first rung of a
        rollout: replace the [T, V, r]/[T, r, V] logit-adapter bank.
        Safe mid-dispatch for the same reason as swap_params (the
        in-flight window holds the old bank by reference). T must
        equal the serving tenant count (the bank rows are gathered by
        registered tenant id) and V/r must match the armed bank's
        shapes (shapes are jit cache keys — a different rank would
        recompile every window mid-traffic)."""
        if self.n_tenants == 0:
            raise ValueError(
                "adapter hot-swap needs a multi-tenant server: this "
                "engine was built without an adapter bank (tenancy), "
                "so there are no adapter rows to replace — roll out "
                "full params instead (swap_params)")
        u = np.asarray(u, np.float32)
        v = np.asarray(v, np.float32)
        old_u, old_v = self._adapters
        if u.shape != old_u.shape or v.shape != old_v.shape:
            raise ValueError(
                f"adapter swap shapes {u.shape} / {v.shape} must equal "
                f"the armed bank's {tuple(old_u.shape)} / "
                f"{tuple(old_v.shape)} (T = registered tenants, V = "
                f"model vocab, r = adapter rank are all compiled "
                f"shapes) — retrain/re-export at the serving shapes, "
                f"or rebuild the server to change them")
        rep = meshlib.replicated(self._cfg.mesh)
        self._adapters = (meshlib.put_with_sharding(u, rep),
                          meshlib.put_with_sharding(v, rep))

    def spot_check_params(self, params) -> dict:
        """Greedy spot-check of CANDIDATE weights on this engine's
        already-compiled prefill program and scratch state — no live
        slot, cache row, or logit is touched (paged engines replay the
        warmup's bit-level no-op chunk, p_end=0, so every pool write
        drops). The staging gate of a rollout: bad weights (NaN/inf,
        blown magnitudes) are caught HERE, before a single client
        request routes onto them. Returns {"ok", "code", "max_abs"}
        with codes mirroring slot_health: 0 healthy, 1 non-finite
        logits, 2 finite but magnitude-blown (> 1e30). On a PAGED
        engine the check replays the pool-state chunk program, so it
        needs the engine dispatch-idle (Scheduler.quiesce() collects
        the in-flight window without starting another)."""
        placed = _place_params(params, self._cfg.mesh,
                               rules=self._partition_rules)
        if self.paged:
            if self._pending is not None:
                raise RuntimeError(
                    "spot_check_params on a paged engine needs the "
                    "in-flight dispatch collected first (the pool "
                    "caches were donated to it) — call the "
                    "scheduler's quiesce() and retry")
            c = self.prefill_chunk
            logits, self._caches, sc = self._efns.prefill_chunk(
                placed, self._caches, self._pt, self._scales,
                np.int32(0), np.zeros((1, c), np.int32),
                np.int32(0), np.int32(0))
            if self.kv_int8:
                self._scales = sc
        elif self.prefill_chunk is not None:
            c = self.prefill_chunk
            caches1 = self._sfns.init_caches(1)
            logits, _ = self._sfns.prefill_chunk(
                placed, caches1, np.zeros((1, c), np.int32),
                np.int32(0), np.int32(c))
        else:
            b = prefill_buckets(self.t_max, self._n_ring)[0]
            logits, _ = self._sfns.prefill(
                placed, np.zeros((1, b), np.int32), np.int32(b))
        row = np.asarray(jax.device_get(logits)).astype(np.float64)
        max_abs = float(np.max(np.abs(row[np.isfinite(row)]))
                        if np.isfinite(row).any() else np.inf)
        if not np.isfinite(row).all():
            return {"ok": False, "code": 1, "max_abs": max_abs}
        if max_abs > 1e30:
            return {"ok": False, "code": 2, "max_abs": max_abs}
        return {"ok": True, "code": 0, "max_abs": max_abs}

    # -- observability --------------------------------------------------

    @property
    def _efns_jit(self):
        """The shared jitted engine namespace, through any AOT overlay
        — introspection (`_cache_size`, `.lower`) lives on the jitted
        functions, not on deserialized executables."""
        return getattr(self._efns, "_base", self._efns)

    @property
    def _sfns_jit(self):
        return getattr(self._sfns, "_base", self._sfns)

    @property
    def _dfns_jit(self):
        return getattr(self._dfns, "_base", self._dfns)

    @property
    def _dsfns_jit(self):
        return getattr(self._dsfns, "_base", self._dsfns)

    def cache_sizes(self) -> dict:
        """Jit-cache entry counts for the no-recompile contract: after
        warmup, admitting requests of ANY prompt length/budget into any
        slot must not grow these (gated by test). With an AOT compile
        cache armed the overlaid programs never enter the jit cache at
        all — their counts stay 0 and the no-growth contract holds
        trivially."""
        efns, sfns = self._efns_jit, self._sfns_jit
        out = {"window": efns.window._cache_size(),
               "insert": efns.insert._cache_size(),
               "health": efns.health._cache_size()}
        if self.paged:
            # the paged admission path: direct-to-pool chunks + the
            # grant-path programs (no bucketed monolithic prefill)
            out["prefill_chunk"] = efns.prefill_chunk._cache_size()
            out["page_row"] = efns.page_row._cache_size()
            if self.kv_int8:
                out["stamp_scales"] = (
                    efns.stamp_scales._cache_size())
        else:
            out["prefill"] = sfns.prefill._cache_size()
            if self.prefill_chunk is not None:
                out["prefill_chunk"] = (
                    sfns.prefill_chunk._cache_size())
        if self.draft_k is not None:
            out["verify"] = efns.verify._cache_size()
        if self._dfns is not None:
            # the learned drafter's programs ride the same contract:
            # mixed draft-hit patterns (deep backlogs, fresh
            # admissions, all-miss cycles) must not grow these
            out["propose"] = self._dfns_jit.propose._cache_size()
            out["draft_ingest"] = self._dfns_jit.ingest._cache_size()
            out["draft_insert"] = self._dfns_jit.insert._cache_size()
            out["draft_prefill"] = self._dsfns_jit.prefill._cache_size()
        return out

    def program_costs(self, window: int) -> dict:
        """Cost/memory accounts of the engine's compiled programs
        (observe/profile.py ProgramCost): the fused masked decode
        window at `window` steps and the admission prefill (the chunk
        program when chunked, else the full-bucket monolithic shape).
        Lowers ACCOUNTING copies against the live state shapes —
        suppressed from the compile watchdog, registered in the
        process PROGRAMS table. The profile CLI verb's serve mode
        feeds these into its roofline verdicts."""
        from idc_models_tpu.observe import profile as prof

        out = {}
        with prof.compiling(None):
            if self.paged:
                # paged programs register under their own names so the
                # profile serve verb can put the gather-indirection
                # cost NEXT TO the contiguous serve.window figure
                out["serve.window_paged"] = prof.register_program(
                    "serve.window_paged",
                    self._efns_jit.window.lower(
                        self._params, self._caches, self._pt,
                        self._logits, self._kd, self._pos, self._rem,
                        self._eos, self._scales, self._adapters,
                        self._tslot, window).compile())
                out["serve.insert_paged"] = prof.register_program(
                    "serve.insert_paged",
                    self._efns_jit.insert.lower(
                        self._logits, self._kd, self._pos, self._rem,
                        self._eos, self._tslot,
                        jnp.zeros((1, self._logits.shape[1]),
                                  self._logits.dtype),
                        np.int32(0), np.int32(0), np.int32(0),
                        np.int32(-1), np.int32(0),
                        np.zeros(2, np.uint32)).compile())
                c = self.prefill_chunk
                out["serve.prefill_chunk_paged"] = prof.register_program(
                    "serve.prefill_chunk_paged",
                    self._efns_jit.prefill_chunk.lower(
                        self._params, self._caches, self._pt,
                        self._scales, np.int32(0),
                        np.zeros((1, c), np.int32), np.int32(0),
                        np.int32(c)).compile())
                if self.draft_k is not None:
                    out["lm.verify"] = prof.register_program(
                        "lm.verify",
                        self._efns_jit.verify.lower(
                            self._params, self._caches, self._pt,
                            self._logits, self._kd, self._pos,
                            self._rem, self._eos, self._scales,
                            self._adapters, self._tslot,
                            np.zeros((self.n_slots, self.draft_k),
                                     np.int32),
                            np.zeros(self.n_slots, bool)).compile())
                self._register_propose_cost(out, prof)
                return out
            out["serve.window"] = prof.register_program(
                "serve.window",
                self._efns_jit.window.lower(
                    self._params, self._caches, self._logits, self._kd,
                    self._pos, self._rem, self._eos, self._scales,
                    self._adapters, self._tslot, window).compile())
            if self.prefill_chunk is not None:
                c = self.prefill_chunk
                caches1 = self._sfns.init_caches(1)
                out["serve.prefill_chunk"] = prof.register_program(
                    "serve.prefill_chunk",
                    self._sfns_jit.prefill_chunk.lower(
                        self._params, caches1,
                        np.zeros((1, c), np.int32), np.int32(0),
                        np.int32(c)).compile())
            else:
                out["serve.prefill"] = prof.register_program(
                    "serve.prefill",
                    self._sfns_jit.prefill.lower(
                        self._params,
                        np.zeros((1, self.t_max), np.int32),
                        np.int32(self.t_max)).compile())
            if self.draft_k is not None:
                # the speculative verify — the model-level draft-check
                # forward (models/lm._chunk_batch_forward + the bonus
                # token step), named alongside lm.prefill/lm.decode so
                # the profile verb's roofline verdicts cover it
                out["lm.verify"] = prof.register_program(
                    "lm.verify",
                    self._efns_jit.verify.lower(
                        self._params, self._caches, self._logits,
                        self._kd, self._pos, self._rem, self._eos,
                        self._scales, self._adapters, self._tslot,
                        np.zeros((self.n_slots, self.draft_k),
                                 np.int32),
                        np.zeros(self.n_slots, bool)).compile())
            self._register_propose_cost(out, prof)
        return out

    def _register_propose_cost(self, out: dict, prof) -> None:
        """Register the learned drafter's batched propose program
        (when armed) alongside window/verify — the profile serve
        verb's roofline verdicts then cover the drafter's per-cycle
        overhead with the same accounting as the programs it rides
        between. No-op without a draft model. Caller holds the
        `prof.compiling(None)` suppression."""
        if self._dfns is None:
            return
        zc = np.zeros((self.n_slots, self.draft_k + 1), np.int32)
        zi = np.zeros(self.n_slots, np.int32)
        zb = np.zeros(self.n_slots, bool)
        out["serve.propose"] = prof.register_program(
            "serve.propose",
            self._dfns_jit.propose.lower(
                self._dparams, self._dcaches, self._dadapters,
                self._tslot, zc, zi, zi, zb).compile())

    def cache_fingerprint(self) -> dict:
        """The identity an AOT-serialized executable is valid for: the
        full compiled-program config (every `_ServeConfig` field plus
        the engine knobs that reach tracing) AND the mesh's device
        assignment — a serialized executable replays onto the exact
        devices it was compiled against, so a different device set must
        read as a cache MISS, never a mis-placed load. compile_cache.py
        layers program name + jax/jaxlib/backend versions on top."""
        mesh = self._cfg.mesh
        return {
            "embed_dim": self._cfg.embed_dim,
            "num_heads": self._cfg.num_heads,
            "num_blocks": self._cfg.num_blocks,
            "t_max": self.t_max,
            "n_slots": self.n_slots,
            "vocab": int(self._logits.shape[1]),
            "cache_dtype": str(jnp.dtype(self._cfg.cache_dtype)),
            "logits_dtype": str(self._logits.dtype),
            "block_impl": self._cfg.block_impl,
            "temperature": self._cfg.temperature,
            "top_k": self._cfg.top_k,
            "pad_id": self.pad_id,
            "kv_int8": self.kv_int8,
            "draft_k": self.draft_k,
            "prefill_chunk": self.prefill_chunk,
            "kv_page_size": self.kv_page_size,
            "kv_pages": self.kv_pages,
            "n_tenants": self.n_tenants,
            "adapter_rank": (int(self._adapters[0].shape[2])
                             if self._adapters else 0),
            "partition_rules": repr(self._partition_rules),
            # the learned drafter compiles its own programs against
            # its own dims — a same-target engine with a different
            # (or no) drafter must read as a MISS for them
            "draft_model": (None if self._dcfg is None else {
                "embed_dim": self._dcfg.embed_dim,
                "num_heads": self._dcfg.num_heads,
                "num_blocks": self._dcfg.num_blocks,
                "cache_dtype": str(jnp.dtype(self._dcfg.cache_dtype)),
                "partition_rules": repr(self._draft_partition_rules),
            }),
            "mesh_axes": {str(k): int(v)
                          for k, v in self._cfg.mesh.shape.items()},
            "devices": [f"{d.platform}:{d.id}"
                        for d in mesh.devices.flat],
        }

    def _warm_aot(self, n_steps: int, cache) -> None:
        """Load-or-compile the serve loop's fixed-shape programs
        through a persistent `CompileCache` and install them as this
        engine's dispatch table (`_AotPrograms`). Warm replica spin-up:
        a fresh process deserializes executables instead of re-running
        XLA. Cold path honesty: a miss compiles AOT via
        `.lower().compile()` — the same route a hit replays — and
        stores the result, so cold-vs-warm comparisons measure the
        cache, not the in-process jit memo. Compiles that do happen
        here are attributed to ``replica.spinup`` in the compile
        watchdog.

        Covered programs: the masked window at `n_steps`, the
        admission insert, and the prefill chunk (when chunked) — the
        fixed-shape programs that dominate spin-up. Monolithic bucketed
        prefill shapes and the speculative verify still jit-compile in
        the warmup dispatches below."""
        from idc_models_tpu.observe import profile as prof

        fp = self.cache_fingerprint()
        fp["window_steps"] = int(n_steps)
        efns, sfns = self._efns_jit, self._sfns_jit
        vocab = int(self._logits.shape[1])
        logits1 = jnp.zeros((1, vocab), self._logits.dtype)
        kd0 = np.zeros(2, np.uint32)

        def undonated(jitted, static_argnums=()):
            # The cached executables must NOT donate: on jaxlib's CPU
            # backend, chaining deserialized executables whose donated
            # outputs feed the next dispatch's donated inputs (the
            # chunk->chunk->insert->window steady state) intermittently
            # frees live buffers — glibc heap aborts and, worse,
            # silently wrong tokens. The donation metadata itself
            # round-trips (a single deserialized donating program is
            # fine); only the chained replay is unsound. So the cache
            # stores donation-free twins of the jitted bodies — an
            # extra buffer copy per dispatch on the AOT path, bounded
            # by the engine state size, in exchange for executables
            # that are safe to replay from any process. The in-process
            # jit path (no cache, or a window-size fallthrough) keeps
            # donation.
            return jax.jit(jitted.__wrapped__,
                           static_argnums=static_argnums)

        plans = []
        if self.paged:
            c = self.prefill_chunk
            w_nd = undonated(efns.window, (11,))
            i_nd = undonated(efns.insert)
            p_nd = undonated(efns.prefill_chunk)
            plans = [
                ("window", "e", "window", lambda: w_nd.lower(
                    self._params, self._caches, self._pt, self._logits,
                    self._kd, self._pos, self._rem, self._eos,
                    self._scales, self._adapters, self._tslot, n_steps)),
                ("insert", "e", "insert", lambda: i_nd.lower(
                    self._logits, self._kd, self._pos, self._rem,
                    self._eos, self._tslot, logits1, np.int32(0),
                    np.int32(1), np.int32(1), np.int32(-1), np.int32(0),
                    kd0)),
                ("prefill_chunk", "e", "prefill_chunk", lambda: p_nd.lower(
                    self._params, self._caches, self._pt, self._scales,
                    np.int32(0), np.zeros((1, c), np.int32),
                    np.int32(0), np.int32(0))),
            ]
        else:
            w_nd = undonated(efns.window, (10,))
            plans = [("window", "e", "window", lambda: w_nd.lower(
                self._params, self._caches, self._logits, self._kd,
                self._pos, self._rem, self._eos, self._scales,
                self._adapters, self._tslot, n_steps))]
            if self.prefill_chunk is not None:
                c = self.prefill_chunk
                caches1 = sfns.init_caches(1)
                p_nd = undonated(sfns.prefill_chunk)
                i_nd = undonated(efns.insert)
                plans.append(
                    ("prefill_chunk", "s", "prefill_chunk",
                     lambda: p_nd.lower(
                        self._params, caches1, np.zeros((1, c), np.int32),
                        np.int32(0), np.int32(c))))
                plans.append(("insert", "e", "insert", lambda: i_nd.lower(
                    self._caches, self._logits, self._kd, self._pos,
                    self._rem, self._eos, self._tslot, self._scales,
                    caches1, logits1, np.int32(0), np.int32(1),
                    np.int32(1), np.int32(-1), np.int32(0), kd0)))
        if self._dfns is not None:
            # the learned drafter's per-cycle programs: propose +
            # backlog ingest, cached under DRAFTER-distinct names (the
            # target's "insert" already claims that key under this
            # fingerprint). The draft insert stays in-process jit like
            # the bucketed prefills — its inputs come from two
            # producers (drafter prefill, init_caches) whose layouts
            # an AOT executable could only match one of.
            dfns = self._dfns_jit
            zc = np.zeros((self.n_slots, self.draft_k + 1), np.int32)
            zi = np.zeros(self.n_slots, np.int32)
            zb = np.zeros(self.n_slots, bool)
            pr_nd = undonated(dfns.propose)
            g_nd = undonated(dfns.ingest)
            plans.append(("propose", "d", "propose",
                          lambda: pr_nd.lower(
                              self._dparams, self._dcaches,
                              self._dadapters, self._tslot, zc, zi,
                              zi, zb)))
            plans.append(("draft_ingest", "d", "ingest",
                          lambda: g_nd.lower(
                              self._dparams, self._dcaches, zc, zi,
                              zb)))
        overlay_e, overlay_s, overlay_d = {}, {}, {}
        with prof.naming_compiles("replica.spinup"):
            for name, ns, attr, lower in plans:
                key = cache.key(program=name, fingerprint=fp)
                exe = cache.load(key)
                if exe is None:
                    exe = cache.compile_and_store(key, lower())
                if name == "window":
                    exe = _AotWindow(exe, n_steps, efns.window)
                {"e": overlay_e, "s": overlay_s,
                 "d": overlay_d}[ns][attr] = exe
        if overlay_e:
            self._efns = _AotPrograms(efns, overlay_e)
        if overlay_s:
            self._sfns = _AotPrograms(sfns, overlay_s)
        if overlay_d:
            self._dfns = _AotPrograms(self._dfns_jit, overlay_d)

    def warmup(self, n_steps: int, compile_cache=None) -> None:
        """Compile every program the serve loop will touch — so
        admission traffic after this triggers ZERO XLA compilations:
        the prefill shapes the admission path uses (every bucket length
        monolithically, the ONE chunk shape when chunked — both
        chunk-from-fresh and chunk-from-chunk chains), the insert, and
        the masked window at `n_steps`. Runs on the real (empty) engine
        state with a ZERO budget, so every row stays dead and the
        warmup dispatches are bit-level no-ops.

        With `compile_cache` (serve/compile_cache.py) the fixed-shape
        programs AOT-load from disk first (`_warm_aot`) and the warmup
        dispatches below run through the loaded executables — a warm
        process skips their XLA compiles entirely."""
        if compile_cache is not None:
            self._warm_aot(n_steps, compile_cache)
        if self.paged:
            # two chunk steps against the live pool with an
            # all-unallocated page table and p_end == start == 0:
            # every page write drops, so the dispatches are bit-level
            # no-ops that compile the chunk-from-fresh AND the
            # chunk-from-chunk chains (pools flow through EVERY paged
            # program under one pinned sharding)
            c = self.prefill_chunk
            logits1 = None
            for _ in range(2):
                logits1, self._caches, sc = self._efns.prefill_chunk(
                    self._params, self._caches, self._pt, self._scales,
                    np.int32(0), np.zeros((1, c), np.int32),
                    np.int32(0), np.int32(0))
                if self.kv_int8:
                    self._scales = sc
            caches1 = None
        elif self.prefill_chunk is not None:
            c = self.prefill_chunk
            caches1 = self._sfns.init_caches(1)
            # two chunk steps: the first consumes init_caches' arrays,
            # the second the chunk program's own (pinned) outputs — the
            # steady-state chain every multi-chunk prompt runs
            logits1, caches1 = self._sfns.prefill_chunk(
                self._params, caches1, np.zeros((1, c), np.int32),
                np.int32(0), np.int32(c))
            if 2 * c <= self.t_max:
                logits1, caches1 = self._sfns.prefill_chunk(
                    self._params, caches1, np.zeros((1, c), np.int32),
                    np.int32(c), np.int32(2 * c))
        else:
            logits1 = caches1 = None
            for b in prefill_buckets(self.t_max, self._n_ring):
                logits1, caches1 = self._sfns.prefill(
                    self._params, np.zeros((1, b), np.int32), np.int32(b))
        # two full insert->window cycles: the steady-state inputs of
        # each program are the (sharding-pinned) OUTPUTS of the others,
        # so the second cycle warms exactly the executables the serve
        # loop reuses forever
        for _ in range(2):
            if self.paged:
                (self._logits, self._kd, self._pos, self._rem,
                 self._eos, self._tslot) = self._efns.insert(
                    self._logits, self._kd, self._pos, self._rem,
                    self._eos, self._tslot, logits1, np.int32(0),
                    np.int32(1), np.int32(0), np.int32(-1),
                    np.int32(0), np.zeros(2, np.uint32))
            else:
                (self._caches, self._logits, self._kd, self._pos,
                 self._rem, self._eos, self._tslot,
                 self._scales) = self._efns.insert(
                    self._caches, self._logits, self._kd, self._pos,
                    self._rem, self._eos, self._tslot, self._scales,
                    caches1, logits1, np.int32(0), np.int32(1),
                    np.int32(0), np.int32(-1), np.int32(0),
                    np.zeros(2, np.uint32))
            self.step_window(n_steps)
            if self.draft_k is not None:
                # the verify program at its ONE fixed shape, chained
                # off both the insert's and the window's (pinned)
                # outputs; every row dead, so the dispatch is a
                # bit-level no-op like the warmup windows
                self.begin_verify(
                    np.zeros((self.n_slots, self.draft_k), np.int32),
                    np.zeros(self.n_slots, bool))
                self.collect()
        if self.paged:
            # the grant/release-path program: a page-row rewrite with
            # the unallocated row slot 0 already holds (and the kill
            # branch exercised — slot 0's budget is already 0) plus,
            # int8, the scale stamp with every target out of bounds —
            # all bit-level no-ops at the real executables' shapes
            self._set_page_row(0, [], kill=True)
            if self.kv_int8:
                self._scales = self._efns.stamp_scales(
                    self._scales, np.int32(0),
                    np.full(self._l_pages, self.kv_pages, np.int32))
        if self._dfns is not None:
            # the learned drafter's chain, interleaved like the target
            # loop above so every program sees every producer's
            # (pinned) outputs: admission rows from BOTH producers (a
            # fresh init_caches row for <=1-token prompts, a
            # prefill-bucket row for the rest) scattered into state
            # that has flowed through ingest AND propose — the serve
            # loop's steady state admits into propose-output caches.
            # Every row is dead (live all-False, slot 0 free), so the
            # dispatches are bit-level no-ops and slot 0's garbage row
            # is overwritten by any real admission's insert.
            zc = np.zeros((self.n_slots, self.draft_k + 1), np.int32)
            zi = np.zeros(self.n_slots, np.int32)
            zb = np.zeros(self.n_slots, bool)
            drow = self._dsfns.init_caches(1)
            self._dcaches = self._dfns.insert(self._dcaches, drow,
                                              np.int32(0))
            for b in prefill_buckets(self.t_max, self._n_ring):
                _, drow = self._dsfns.prefill(
                    self._dparams, np.zeros((1, b), np.int32),
                    np.int32(b))
                self._dcaches = self._dfns.ingest(
                    self._dparams, self._dcaches, zc, zi, zb)
                self._dcaches, _ = self._dfns.propose(
                    self._dparams, self._dcaches, self._dadapters,
                    self._tslot, zc, zi, zi, zb)
                self._dcaches = self._dfns.insert(
                    self._dcaches, drow, np.int32(0))
            self._dcaches = self._dfns.ingest(
                self._dparams, self._dcaches, zc, zi, zb)
            self._dcaches, _ = self._dfns.propose(
                self._dparams, self._dcaches, self._dadapters,
                self._tslot, zc, zi, zi, zb)
        # the health reduce is part of the armed serve loop's steady
        # state (one dispatch per cycle) — warm it with everything else
        self.slot_health()

    def kv_bytes_per_slot(self) -> int:
        """HBM bytes of ring-cache state per decode slot (K + V rows
        across blocks, plus dequant scales when int8) — the denominator
        of the int8 capacity claim: slots_at_budget = budget // this.
        On a PAGED engine this is the WORST CASE (a full-t_max
        request's pages); the live figure is `kv_bytes_resident`,
        because short requests no longer reserve t_max."""
        if self.paged:
            return self._l_pages * self.kv_page_bytes()
        per = 0
        for kc, vc in self._caches:
            per += (kc.nbytes + vc.nbytes) // self.n_slots
        for pair in self._scales:
            for s in pair:
                per += s.nbytes // self.n_slots
        return per

    def kv_page_bytes(self) -> int:
        """HBM bytes ONE page costs across every block's K + V pools,
        plus its per-(page, head) dequant scales when int8 — the unit
        the tokens-per-HBM-byte capacity claim divides by."""
        head_dim = self._cfg.embed_dim // self._cfg.num_heads
        item = (1 if self.kv_int8
                else jnp.dtype(self._cfg.cache_dtype).itemsize)
        per = (self._cfg.num_blocks * 2 * self.kv_page_size
               * self._cfg.num_heads * head_dim * item)
        if self.kv_int8:
            per += self._cfg.num_blocks * 2 * self._cfg.num_heads * 4
        return per

    def kv_bytes_resident(self) -> int:
        """HBM bytes of KV state currently RESERVED: the paged
        counterpart of `kv_bytes_per_slot` — used pages times page
        bytes. A contiguous engine reserves every slot's full row up
        front, so its figure is constant at n_slots * per-slot bytes;
        the ratio of the two under mixed-length traffic IS the paged
        capacity win."""
        if not self.paged:
            return self.n_slots * self.kv_bytes_per_slot()
        return self._alloc.used_count() * self.kv_page_bytes()

    def tokens_resident(self) -> int:
        """Tokens of KV actually held on device right now: decoded
        positions of occupied slots plus prefilled positions of
        pending chunked admissions. tokens_resident /
        kv_bytes_resident is the tokens-per-HBM-byte figure the paged
        engine exists to raise."""
        toks = int(sum(int(self._pos_h[s]) for s in range(self.n_slots)
                       if self._occupied[s]))
        toks += int(sum(p.next_start for p in self._prefills.values()))
        return toks

    def page_stats(self) -> dict:
        """The per-cycle page/occupancy rollup the scheduler feeds to
        ServingMetrics.on_pages (paged engines only — None tells the
        caller the engine is contiguous)."""
        if not self.paged:
            return None
        return {
            "pages_total": self.kv_pages,
            "pages_used": self._alloc.used_count(),
            "pages_cached": (self.prefix_cache.cached_pages()
                             if self.prefix_cache is not None else 0),
            "resident_tokens": self.tokens_resident(),
            "resident_bytes": self.kv_bytes_resident(),
        }
