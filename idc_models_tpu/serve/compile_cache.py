"""Persistent compiled-program cache — warm replica spin-up.

BENCH_r06 prices what a cold replica pays before it serves a single
token: minutes of XLA compilation for programs this process (or a
sibling) has compiled before. This module makes that cost durable-once
per (program, config, mesh, toolchain): `SlotEngine._warm_aot` lowers
each fixed-shape serve program AOT, and the resulting executable is
serialized to disk (`jax.experimental.serialize_executable`); the next
replica with the SAME key deserializes it in milliseconds instead of
re-running XLA.

The key is everything an executable is valid for, nothing more:

- the engine's `cache_fingerprint()` — every `_ServeConfig` field,
  the engine knobs that reach tracing (slots, chunk, quant, draft,
  pages, tenants, partition rules), the mesh axes AND the concrete
  device assignment (serialized executables replay onto the exact
  devices they were compiled against — a different device set is a
  MISS, never a mis-placed load);
- the program name and its static shape parameters (window steps);
- jax + jaxlib versions and the backend platform — a toolchain bump
  invalidates every entry by keying it out, no sweeper needed.

Entries are one file per key, written atomically (tmp + `os.replace`)
so a concurrently spinning-up replica never reads a torn blob; a blob
that still fails to deserialize (truncated disk, foreign toolchain
writing under the same path) is EVICTED and counted as a miss — spin-up
falls back to a real compile and overwrites it. That handler is the one
deliberate swallow in this module (documented in the static-scan
allowlist): a corrupt best-effort cache must never be able to take a
replica down.

`enable_persistent_xla_cache` additionally arms jax's own
compilation-cache knob under a sibling directory — that layer caches
XLA IR→binary for EVERY jit in the process (training steps included),
complementing the executable store, which skips tracing/lowering too.

Counters (hits/misses/stores/evictions, deserialize + compile seconds)
feed the `serve_compile_cache_*` gauges (serve/metrics.py) and the
`stats` CLI rollup, so warm-vs-cold is visible in the epilogue, not
just in bench_serving_elastic.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from pathlib import Path

import jax


def enable_persistent_xla_cache(path) -> Path:
    """Arm jax's built-in compilation cache under `path` — the
    IR-level layer below the executable store: every jit compile in
    the process (serve AND train programs) writes/reads it. Returns
    the directory. Idempotent; safe to call before any engine
    exists."""
    p = Path(path)
    p.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(p))
    return p


class CompileCache:
    """On-disk store of AOT-serialized executables, one file per key.

    `key()` hashes the full validity fingerprint; `load()` returns a
    ready-to-call Compiled (hit) or None (miss); `compile_and_store()`
    finishes a miss by compiling the caller's Lowered and persisting
    the result. All counters are cumulative for the life of this
    handle — `summary()` is what metrics/bench read."""

    def __init__(self, path, *, logger=None):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.logger = logger
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evicted_corrupt = 0
        self.deserialize_s = 0.0
        self.compile_s = 0.0

    def _log(self, **kw) -> None:
        if self.logger is not None:
            self.logger.log(**kw)

    def key(self, *, program: str, fingerprint: dict) -> str:
        """Content-address of one executable: program name + engine
        fingerprint + toolchain (jax/jaxlib/backend). Any drift in any
        component is a different key — invalidation IS the key."""
        material = {
            # schema 2: entries are donation-free twins of the jitted
            # bodies (see SlotEngine._warm_aot) — blobs serialized
            # with donated buffers replay unsoundly cross-process on
            # CPU, so they must key out, not load
            "schema": 2,
            "jax": jax.__version__,
            "jaxlib": jax.lib.__version__,
            "backend": jax.default_backend(),
            "program": program,
            "fingerprint": fingerprint,
        }
        blob = json.dumps(material, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:32]

    def _file(self, key: str) -> Path:
        return self.path / f"{key}.jaxexe"

    def load(self, key: str):
        """Deserialize the stored executable for `key`, or None on a
        miss. A file that exists but cannot load (torn write survived
        a crash, foreign-toolchain blob under a colliding path) is
        evicted and reported as a miss: the cache is best-effort by
        contract — spin-up must fall back to a real compile, never
        die on a bad cache entry (the rebuilt entry then replaces
        it)."""
        from jax.experimental import serialize_executable as se

        f = self._file(key)
        if not f.exists():
            self.misses += 1
            self._log(event="compile_cache", outcome="miss", key=key)
            return None
        t0 = time.perf_counter()
        try:
            payload, in_tree, out_tree = pickle.loads(f.read_bytes())
            exe = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            f.unlink(missing_ok=True)
            self.evicted_corrupt += 1
            self.misses += 1
            self._log(event="compile_cache", outcome="evict_corrupt",
                      key=key, error=f"{type(e).__name__}: {e}")
            return None
        dt = time.perf_counter() - t0
        self.deserialize_s += dt
        self.hits += 1
        self._log(event="compile_cache", outcome="hit", key=key,
                  deserialize_ms=round(dt * 1e3, 3))
        return exe

    def compile_and_store(self, key: str, lowered):
        """Finish a miss: compile the Lowered, serialize, and persist
        atomically (tmp + `os.replace` — a reader either sees the old
        complete file or the new complete file, never a torn one; two
        replicas racing the same key write identical content and last
        one wins). Returns the compiled executable, so the cold path
        runs the SAME AOT object a warm hit would — cold-vs-warm
        timings compare the cache, not dispatch mechanisms."""
        from jax.experimental import serialize_executable as se

        t0 = time.perf_counter()
        exe = lowered.compile()
        dt = time.perf_counter() - t0
        self.compile_s += dt
        payload, in_tree, out_tree = se.serialize(exe)
        f = self._file(key)
        tmp = f.with_name(f.name + f".tmp{os.getpid()}")
        tmp.write_bytes(pickle.dumps((payload, in_tree, out_tree)))
        os.replace(tmp, f)
        self.stores += 1
        self._log(event="compile_cache", outcome="store", key=key,
                  compile_ms=round(dt * 1e3, 3),
                  bytes=f.stat().st_size)
        return exe

    def summary(self) -> dict:
        """The frozen-schema rollup metrics and bench read."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "evicted_corrupt": self.evicted_corrupt,
            "deserialize_s": round(self.deserialize_s, 6),
            "compile_s": round(self.compile_s, 6),
        }
