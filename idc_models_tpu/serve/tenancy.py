"""Multi-tenant serving: N tenants resident on ONE engine — shared
base model, per-tenant adapter deltas, per-tenant quotas, SLOs, and
brownout stages (ROADMAP item 5a).

The deployment story the paper implies — many hospitals/clients served
by one trained service — needs several tenants' models resident at
once without N forked engines. Two shapes exist:

- **Full checkpoint per tenant**: build one `LMServer` per tenant
  (serve/cluster already routes across servers). Right when tenants'
  models genuinely differ (different architectures, deltas that touch
  attention/MLP weights) — and priced accordingly: N copies of
  params + KV + compiled programs. docs/MULTITENANCY.md spells out
  when this is still the better trade.
- **Shared base + per-tenant adapter deltas** (this module, the
  S-LoRA/Punica-shaped path): ONE parameter tree, one KV pool, one
  set of compiled programs; each tenant optionally carries a low-rank
  HEAD adapter, and a mixed-tenant decode batch stays ONE dispatch —
  the engine gathers each slot's tenant delta by a traced `[n_slots]`
  tenant-index array inside the fused window/verify programs, so
  tenant arrival patterns are VALUES, not shapes, and compile nothing
  (gated by test).

**Adapter semantics** (the one deliberate design decision here): a
tenant's adapter is a LOGIT-SPACE low-rank delta — effective logits =
`logits + (logits @ U_t) @ V_t`, i.e. an effective head
`W(I + U_t V_t)` — applied at SAMPLING time inside the fused
window/verify programs (`models/lm.make_adapter_head_hook`, the one
definition both programs share). Because the delta is a pure function
of the BASE logits, every piece of stored state stays tenant-agnostic:
prefill programs are unchanged, the engine's per-slot logits state
holds base logits, and prefix-cache snapshots (K/V + boundary logits)
remain shareable across tenants — a hospital's system prompt prefills
once for everyone, with zero cross-tenant state. An adapter that must
touch attention/MLP projections cannot take this form; that is the
full-checkpoint-per-tenant boundary (docs/MULTITENANCY.md).

**Isolation** (the noisy-neighbor story): per-tenant quotas — resident
slots, queued requests, KV pages — are enforced at admission by the
scheduler; a tenant's TTFT SLO (`observe/slo.py` burn-rate alerting,
objective name ``ttft:<tenant>``) drives that tenant's OWN brownout
controller, so a flooding tenant clamps and then sheds while its
neighbors stay at stage ``normal``. `SLOEngine.breached("ttft:<t>")`
is exactly the admission signal PR 7 built it to be.

Cross-tenant discipline: every accessor on this module's classes takes
ONE tenant and reads only that tenant's state; the few methods that
legitimately see all tenants (registration, the stacked-adapter build,
fleet rollups) are enumerated in
`tests/test_static_robustness.TENANCY_CROSS_TENANT_ALLOWLIST` and the
AST scan fails on any new cross-tenant read outside it.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission bounds; None = unlimited on that axis.

    - `max_resident_slots`: decode slots (running + prefilling) the
      tenant may hold at once — the floor other tenants keep under a
      flood.
    - `max_queued`: admission-queue entries; beyond it the tenant's
      submits are refused (status ``rejected``) without touching the
      shared queue budget. Doubles as the tenant brownout's queue
      watermark.
    - `kv_page_budget`: KV pool pages the tenant's ADMISSION
      reservations may hold (paged engines; exact under the default
      full-budget decode reserve — mid-decode grant growth under an
      optimistic `kv_decode_reserve` is not re-charged, documented in
      docs/MULTITENANCY.md).
    """

    max_resident_slots: int | None = None
    max_queued: int | None = None
    kv_page_budget: int | None = None

    def __post_init__(self):
        for field in ("max_resident_slots", "max_queued",
                      "kv_page_budget"):
            v = getattr(self, field)
            if v is not None and (not isinstance(v, int) or v < 1):
                raise ValueError(
                    f"TenantQuota.{field} must be None (unlimited) or "
                    f"an int >= 1, got {v!r} — a quota of 0 would "
                    f"admit nothing ever; unregister the tenant "
                    f"instead")


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One registered tenant: stable integer id (the engine's gather
    index), quota, optional adapter factors, optional TTFT SLO."""

    name: str
    tid: int
    quota: TenantQuota
    adapter: tuple | None = None         # (u [V, r], v [r, V]) host
    slo_ttft_p95_ms: float | None = None

    @property
    def slo_name(self) -> str | None:
        return (f"ttft:{self.name}"
                if self.slo_ttft_p95_ms is not None else None)


class TenantRegistry:
    """Declarative tenant set: `register(...)` each tenant, then
    `build(...)` once into the runtime `Tenancy` the server wires in.
    The FIRST registered tenant is the default for untagged requests
    (override with ``default=`` at construction)."""

    def __init__(self, *, default: str | None = None):
        self._tenants: dict[str, Tenant] = {}
        self._default = default
        self._built = False

    def register(self, name: str, *, adapter=None, quota=None,
                 slo_ttft_p95_ms: float | None = None) -> Tenant:
        """Add one tenant. `adapter` is an optional `(u, v)` pair of
        low-rank logit-adapter factors with shapes ``[V, r]`` /
        ``[r, V]`` (every registered adapter must agree on both V and
        r — the engine stacks them into one gather table);
        `slo_ttft_p95_ms` declares the tenant's TTFT p95 objective
        (burn-rate alerted, and the tenant's brownout trigger)."""
        if self._built:
            raise ValueError(
                "TenantRegistry is already built — tenants register "
                "before build(); a running server's tenant set is "
                "fixed (rebuild the server to change it)")
        if not isinstance(name, str) or not name:
            raise ValueError(f"tenant name must be a non-empty string, "
                             f"got {name!r}")
        if name in self._tenants:
            raise ValueError(
                f"tenant {name!r} is already registered — tenant "
                f"names are identities; re-registering would silently "
                f"replace its adapter/quota")
        if quota is None:
            quota = TenantQuota()
        elif not isinstance(quota, TenantQuota):
            raise ValueError(f"quota must be a TenantQuota, got "
                             f"{type(quota).__name__}")
        if slo_ttft_p95_ms is not None and slo_ttft_p95_ms <= 0:
            raise ValueError(f"slo_ttft_p95_ms must be > 0, got "
                             f"{slo_ttft_p95_ms}")
        if adapter is not None:
            adapter = self._check_adapter(name, adapter)
        t = Tenant(name=name, tid=len(self._tenants), quota=quota,
                   adapter=adapter, slo_ttft_p95_ms=slo_ttft_p95_ms)
        self._tenants[name] = t
        return t

    def _check_adapter(self, name: str, adapter) -> tuple:
        """Shape discipline at REGISTRATION (build re-checks against
        the model's vocab): (u [V, r], v [r, V]) with one (V, r)
        across every tenant — the stacked gather table needs one
        shape."""
        try:
            u, v = adapter
        except (TypeError, ValueError):
            raise ValueError(
                f"tenant {name!r}: adapter must be a (u, v) pair of "
                f"arrays with shapes [V, r] and [r, V], got "
                f"{type(adapter).__name__}") from None
        u = np.asarray(u, np.float32)
        v = np.asarray(v, np.float32)
        if u.ndim != 2 or v.ndim != 2 or u.shape[::-1] != v.shape:
            raise ValueError(
                f"tenant {name!r}: adapter shapes must be u [V, r] "
                f"and v [r, V] (transposes of each other), got "
                f"{u.shape} / {v.shape}")
        for other in self._tenants.values():
            if other.adapter is None:
                continue
            ou = other.adapter[0]
            if ou.shape != u.shape:
                raise ValueError(
                    f"tenant {name!r}: adapter shape {u.shape} != "
                    f"tenant {other.name!r}'s {ou.shape} — every "
                    f"tenant's adapter must share one [V, r] so the "
                    f"engine can stack them into a single slot-"
                    f"indexed gather table (pad the rank or register "
                    f"a zero adapter)")
            break
        return (u, v)

    def names(self) -> list[str]:
        """Registration order — tid order by construction."""
        return list(self._tenants)

    def build(self, *, vocab: int | None = None, logger=None,
              registry=None, clock=time.monotonic,
              slo_short_window_s: float = 60.0,
              slo_burn_threshold: float = 2.0,
              slo_min_samples: int = 10,
              brownout_dwell_s: float = 0.25,
              brownout_clear_s: float = 1.0,
              brownout_clamp_tokens: int = 8) -> "Tenancy":
        """Freeze the tenant set into the runtime `Tenancy`: the
        stacked adapter bank (validated against the model's `vocab`
        when given), one SLOEngine holding every tenant's
        ``ttft:<name>`` objective, and one brownout controller per
        tenant that declared an SLO or a queue quota (tenants with
        neither never shed — nothing could ever signal)."""
        from idc_models_tpu.observe.slo import SLO, SLOEngine
        from idc_models_tpu.serve.brownout import BrownoutController

        if not self._tenants:
            raise ValueError("TenantRegistry.build() with no tenants "
                             "registered — register at least one")
        if self._default is not None and self._default not in self._tenants:
            raise ValueError(
                f"default tenant {self._default!r} is not registered "
                f"(registered: {self.names()})")
        self._built = True
        bank = None
        with_adapter = [t for t in self._tenants.values()
                        if t.adapter is not None]
        if with_adapter:
            V, r = with_adapter[0].adapter[0].shape
            if vocab is not None and V != vocab:
                raise ValueError(
                    f"adapter vocab dim {V} != model vocab {vocab} — "
                    f"the logit-space adapter maps [V] -> [V] for "
                    f"THIS model's head")
            u = np.zeros((len(self._tenants), V, r), np.float32)
            v = np.zeros((len(self._tenants), r, V), np.float32)
            for t in self._tenants.values():
                if t.adapter is not None:
                    u[t.tid], v[t.tid] = t.adapter
            # adapter-less tenants keep zero rows: their delta is
            # exactly zero, so they decode the base model through the
            # same gathered program
            bank = AdapterBank(u=u, v=v, rank=r, vocab=V)
        slo = None
        objectives = [SLO.latency(t.slo_name,
                                  threshold_s=t.slo_ttft_p95_ms / 1e3)
                      for t in self._tenants.values()
                      if t.slo_ttft_p95_ms is not None]
        if objectives:
            slo = SLOEngine(
                objectives, short_window_s=slo_short_window_s,
                long_window_s=5.0 * slo_short_window_s,
                burn_threshold=slo_burn_threshold,
                min_samples=slo_min_samples, logger=logger,
                registry=registry, clock=clock)
        brownouts = {}
        for t in self._tenants.values():
            if t.slo_name is None and t.quota.max_queued is None:
                continue
            # the brownout watermark sits BELOW the hard max_queued
            # quota: at the quota itself submits are already refused,
            # so the queue can never reach it after an admission and
            # a watermark there would never fire (found by drill)
            qh = (None if t.quota.max_queued is None
                  else max((3 * t.quota.max_queued) // 4, 1))
            brownouts[t.name] = BrownoutController(
                slo=slo if t.slo_name is not None else None,
                slo_name=t.slo_name,
                queue_high=qh,
                clamp_tokens=brownout_clamp_tokens,
                escalate_dwell_s=brownout_dwell_s,
                clear_after_s=brownout_clear_s, logger=logger,
                registry=registry, clock=clock, tenant=t.name)
        default = self._default or next(iter(self._tenants))
        return Tenancy(dict(self._tenants), default=default, bank=bank,
                       slo=slo, brownouts=brownouts)


@dataclasses.dataclass(frozen=True)
class AdapterBank:
    """The stacked per-tenant adapter factors the engine gathers from:
    ``u [T, V, r]`` / ``v [T, r, V]`` host float32 (the engine places
    them replicated on its mesh once). Tenants without an adapter hold
    zero rows — their gathered delta is exactly zero."""

    u: np.ndarray
    v: np.ndarray
    rank: int
    vocab: int


class Tenancy:
    """The built runtime the server wires through engine, scheduler,
    and metrics. Frozen tenant set; all lookups are by ONE tenant
    name (the cross-tenant scan discipline — see the module
    docstring)."""

    def __init__(self, tenants: dict[str, Tenant], *, default: str,
                 bank: AdapterBank | None, slo, brownouts: dict):
        self._tenants = tenants
        self.default = default
        self.bank = bank
        self.slo = slo
        self.brownouts = brownouts

    def resolve(self, name: str | None) -> Tenant:
        """The tenant a request tag names (None = the default). An
        unknown tag is a caller error, taught loudly — silently
        lumping it into the default would charge one tenant's quota
        for another's traffic."""
        if name is None:
            name = self.default
        t = self._tenants.get(name)
        if t is None:
            raise ValueError(
                f"unknown tenant {name!r} (registered: "
                f"{self.names()}) — requests carry tenant= tags that "
                f"must name a registered tenant")
        return t

    def names(self) -> list[str]:
        return list(self._tenants)

    def n_tenants(self) -> int:
        return len(self._tenants)

    def quota(self, name: str) -> TenantQuota:
        return self.resolve(name).quota

    def brownout(self, name: str):
        """The tenant's own brownout controller (None when the tenant
        declared neither an SLO nor a queue quota)."""
        return self.brownouts.get(self.resolve(name).name)

    def breached(self, name: str) -> bool:
        """The per-tenant admission signal — `SLOEngine.breached` on
        the tenant's ``ttft:<name>`` objective (False when the tenant
        declared no SLO): True while the tenant's TTFT burn-rate
        alert is active."""
        t = self.resolve(name)
        if self.slo is None or t.slo_name is None:
            return False
        return self.slo.breached(t.slo_name)

    def observe_ttft(self, name: str, ttft_s: float) -> None:
        """Feed one TTFT sample into the tenant's objective (no-op for
        tenants without one) — called by the serving metrics hooks."""
        t = self.resolve(name)
        if self.slo is not None and t.slo_name is not None:
            self.slo.observe(t.slo_name, ttft_s)

    def evaluate(self) -> None:
        """One burn-rate evaluation over every tenant objective —
        the scheduler calls this once per cycle (the SLOEngine
        evaluates all its objectives in one pass; per-tenant iteration
        lives inside it, not here)."""
        if self.slo is not None:
            self.slo.evaluate()
