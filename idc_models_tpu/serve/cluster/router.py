"""The cluster router: one public submit/poll/drain surface over N
`LMServer` replicas — SLO-aware placement, prefill/decode
disaggregation over the prefix-registry handoff, straggler hedging,
graceful drain, and journal-backed failover.

This is the layer ROADMAP item 1 names above the single-engine serve
stack, realized on the repo's own control plane:

- **Placement** reads each replica's health document (the in-process
  twin of `/healthz`): a replica is a candidate only while live, not
  draining, not brownout-shedding, under its queue bound, and — paged
  engines — holding page headroom for THIS request; candidates order
  by (SLO burning, load, fewest free slots), ties broken by fleet
  order, so placement is a pure function of observable state and
  drills replay deterministically.
- **Disaggregation**: with dedicated `role="prefill"` replicas armed,
  a prompt reaching the first chunk boundary is first driven through
  `Replica.prefill_only` — chunked prefill to the last boundary, each
  boundary snapshot published into the cluster `PrefixRegistry` — and
  the decode replica's normal admission then ADOPTS the published
  prefix: the decode replica never runs those chunks, and the tokens
  are bit-identical to a single-replica run because the snapshot IS
  the chunk program's output (gated by test). A prompt the registry
  already covers skips the prefill replica entirely — the hot system
  prompt is prefilled once, cluster-wide.
- **Hedging** (`hedge_after_s`): a request still unfinished that long
  after placement is duplicated onto the least-loaded OTHER replica;
  the first finisher answers under the original id and the loser is
  discarded — the classic tail-latency trade (bounded duplicated
  work), bounded per request by the `RetryPolicy`'s max_retries.
- **Drain**: `drain_replica` flips the replica to draining (placement
  stops; its brownout — when armed — jumps to the shed stage) while
  its in-flight work steps to completion — or, `migrate=True`, leaves
  WITH it: queued work re-places onto the fleet and RUNNING slots
  move live (mid-decode KV + sampling state export/import, output
  bit-identical), the source journal staying open across the
  export→import gap so a crash inside it replays the request.
- **Elasticity** (`autoscaler=`): an `Autoscaler` reads the health
  documents every step and the router applies its decisions — scale-
  up builds a replica through `replica_factory` (warm spin-up when
  the factory carries the fleet's `CompileCache`), scale-down drains
  the least-loaded live replica with slot migration. When EVERY
  decode-capable replica is draining or dead, `submit` answers with a
  terminal shed instead of a retry-forever False.
- **Failover**: a replica whose step raises (or is killed by the
  drill) is marked dead; terminal results its final tick salvaged are
  adopted, and everything its journal WAL shows accepted-but-
  unfinished is resubmitted through the NORMAL placement path onto
  survivors — original id, seed, relative deadline, and trace_id
  preserved (the journal contract), so recovered greedy/seeded output
  is bit-identical (the engine's serial-parity contract; gated by
  test).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from idc_models_tpu.observe import metrics_registry as mreg
from idc_models_tpu.observe import trace
from idc_models_tpu.serve.api import Request, Result
from idc_models_tpu.serve.journal import pending_requests
from idc_models_tpu.serve.metrics import aggregate_summaries
from idc_models_tpu.serve.scheduler import _next_trace_id


def _entry_request(entry) -> Request:
    """Rebuild a `Request` from a scheduler entry — the drain path's
    fallback for work this router never placed itself (a direct
    replica submit) or can no longer seat live. Mirrors the journal's
    submit record: id, prompt, budget, eos, integer seed, trace and
    tenant identity (an explicit jax key is not re-placeable — same
    documented limit as the WAL's)."""
    seed = (int(entry.rng)
            if isinstance(entry.rng, (int, np.integer)) else None)
    return Request(
        id=str(entry.rid),
        prompt=tuple(int(t) for t in np.asarray(entry.prompt)
                     .reshape(-1)),
        max_new_tokens=int(entry.budget), eos_id=entry.eos_id,
        seed=seed, trace_id=entry.trace_id,
        tenant=getattr(entry, "tenant", None))


class Router:
    """Front end over a fleet of `Replica`s (serve/cluster/replica.py).

    The router owns the public surface: `submit`/`poll`/`step`/
    `drain`/`run(trace)` mirror `LMServer`'s so a caller scales from
    one replica to N without changing shape. `retry` (a scheduler
    `RetryPolicy`) bounds per-request re-placements (migrations +
    hedges); `prefix_registry` arms cross-replica prefix reuse and the
    prefill/decode handoff; `slo` (an `observe.slo.SLOEngine`) is fed
    cluster-level TTFT/error samples — the router's own burn-rate
    alerting over the whole fleet."""

    def __init__(self, replicas, *, retry=None, hedge_after_s=None,
                 prefix_registry=None, slo=None, logger=None,
                 registry=None, clock=time.monotonic,
                 tenant_affinity_slack: int | None = 4,
                 autoscaler=None, replica_factory=None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("need at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"replica ids must be unique, got {ids}")
        if hedge_after_s is not None and hedge_after_s <= 0:
            raise ValueError(f"need hedge_after_s > 0, got "
                             f"{hedge_after_s}")
        if autoscaler is not None and replica_factory is None:
            raise ValueError(
                "an autoscaler needs a replica_factory: a scale-up "
                "decision has to BUILD the replica it adds (a callable "
                "replica_id -> Replica; serve/cluster/replica.py's "
                "build_replica partial is the usual one)")
        # misconfigured disaggregation fails at FLEET BUILD, not on the
        # first caller's submit: a prefill replica is useless without
        # chunked prefill (boundary snapshots are the artifact) and
        # without a registry to publish through, and its chunk grid
        # must match the registry's
        for r in replicas:
            if r.role != "prefill":
                continue
            chunk = r.server.engine.prefill_chunk
            if chunk is None:
                raise ValueError(
                    f"prefill replica {r.replica_id!r} was built "
                    f"without prefill_chunk — boundary snapshots are "
                    f"the handoff artifact")
            if prefix_registry is None:
                raise ValueError(
                    f"prefill replica {r.replica_id!r} needs a "
                    f"prefix_registry: the handoff artifact travels "
                    f"through it")
            if chunk != prefix_registry.chunk:
                raise ValueError(
                    f"prefill replica {r.replica_id!r} chunk {chunk} "
                    f"!= registry chunk {prefix_registry.chunk} — "
                    f"snapshots live on one grid")
        self.replicas = replicas
        self._by_id = {r.replica_id: r for r in replicas}
        self.retry = retry
        self.hedge_after_s = hedge_after_s
        self.prefix_registry = prefix_registry
        self.slo = slo
        self.logger = logger
        self.clock = clock
        reg = registry if registry is not None else mreg.REGISTRY
        # kept public: ClusterTelemetry folds the router's own
        # cluster_* series into the fleet exposition from here
        self.registry = reg
        self._m_placements = reg.counter(
            "cluster_placements_total",
            "requests placed on a replica by the router",
            labels=("replica",))
        self._m_migrations = reg.counter(
            "cluster_migrations_total",
            "journaled requests migrated off a dead replica onto "
            "survivors")
        self._m_handoffs = reg.counter(
            "cluster_handoffs_total",
            "prefill->decode handoffs (a dedicated prefill replica "
            "published the prompt's boundary snapshot for the decode "
            "replica to adopt)")
        self._m_hedges = reg.counter(
            "cluster_hedges_total",
            "straggler requests duplicated onto a second replica")
        self._m_deaths = reg.counter(
            "cluster_replica_deaths_total",
            "replicas marked dead (step failure or kill drill)")
        self._m_slot_migrations = reg.counter(
            "cluster_slot_migrations_total",
            "mid-decode slots exported off a draining replica and "
            "imported live onto a peer (KV + sampling state move; "
            "decode continues bit-identically)")
        self._m_scale = reg.counter(
            "cluster_scale_events_total",
            "autoscaler decisions applied to the fleet",
            labels=("action",))
        # tenant affinity (serve/tenancy.py, ISSUE 14): a tenant's
        # requests stick to the replica that last served them — its
        # prefix cache holds the tenant's system-prompt snapshots and
        # its engine the tenant's warm state — unless that replica is
        # more than `tenant_affinity_slack` requests more loaded than
        # the best candidate (None disables affinity). Affinity never
        # overrides admissibility: a draining/shedding/full home just
        # loses the tenant to the normal least-loaded placement.
        self.tenant_affinity_slack = tenant_affinity_slack
        self._tenant_home: dict[str, object] = {}
        self._m_affinity = reg.counter(
            "cluster_tenant_affinity_placements_total",
            "placements routed to the tenant's home replica by "
            "affinity (prefix-cache / adapter warmth)",
            labels=("tenant",))
        self._g_live = reg.gauge(
            "cluster_replicas_live",
            "replicas currently live (placeable fleet size)")
        self._g_live.set(len(replicas))
        # results finalized OUTSIDE a replica's step return (failover
        # adoption, retry-exhausted/journal-less losses) — drained into
        # the next step()'s return so drain()/run() keep their
        # "returns everything that finished" contract
        self._out_of_band: list[Result] = []
        # rid -> current owning replica / original Request / submit
        # stamp / total placement attempts; hedge copy id -> original
        self._owner: dict = {}
        self._requests: dict = {}
        self._submit_t: dict = {}
        self._attempts: dict = {}
        self._hedges: dict = {}
        self._hedged: set = set()
        # hedge copy id -> the replica it runs on (failover cleanup)
        self._hedge_target: dict = {}
        # rids already routed through the handoff decision — submit()
        # re-offers under backpressure, and each re-offer must not
        # re-prefill or duplicate the handoff record
        self._handed_off: set = set()
        self._results: dict[str, Result] = {}
        # migrated requests waiting for a survivor with room, in the
        # dead replica's original submit order
        self._pending_migration: list[Request] = []
        # rid -> DRAINING source replica whose journal still holds the
        # open submit: once the re-placement lands, the source writes
        # the terminal "migrated" finish (a dead source — failover —
        # never appears here; its journal is closed and the WAL itself
        # is the recovery record)
        self._migrating_from: dict = {}
        self.placements: dict[str, int] = {i: 0 for i in ids}
        self.migrations: list[dict] = []
        self.handoffs: list[dict] = []
        # live mid-decode slot moves ({rid, from, to}), distinct from
        # `migrations` (re-placements that re-run from the prompt)
        self.slot_migrations: list[dict] = []
        self.hedges_sent = 0
        # elasticity (serve/cluster/autoscaler.py): the autoscaler
        # reads the health documents each step and the router applies
        # its decisions — scale-up through replica_factory/add_replica,
        # scale-down through drain_replica(migrate=True)
        self.autoscaler = autoscaler
        self.replica_factory = replica_factory
        self._next_replica_ordinal = len(replicas)
        # cluster-wide sheds happen at the ROUTER (no replica ever
        # sees the request), so they must be counted here — replica
        # metrics cannot
        self.cluster_sheds = 0
        # the open weight rollout, if any (start_rollout/finish_rollout)
        self._rollout: dict | None = None
        # an armed ClusterWatchdog (serve/cluster/telemetry.py) runs
        # its detector pass once per step — assigned after
        # construction (the watchdog needs the router to exist first)
        self.watchdog = None
        # fleet trace context (ISSUE 20): the router assigns each
        # request its trace_id AT THE DOOR (so every hop event carries
        # it even before any replica accepts the work), numbers the
        # hops per request, and holds one detached cluster.request root
        # span per in-flight request — each replica's serve.request
        # span opens as its child, so the merged cross-process span
        # export is one tree under one trace_id
        self._trace_ids: dict[str, str] = {}
        self._hop_seq: dict[str, int] = {}
        self._root_span: dict[str, object] = {}
        # rid -> source replica_id of a pending from-the-prompt
        # re-placement (drain or failover) so the cluster_migrate hop
        # can name where the work came FROM, not just where it landed
        self._migration_src: dict[str, str] = {}

    # -- placement --------------------------------------------------------

    def _score(self, replica, health) -> tuple:
        """Lower is better. SLO-burning replicas sort last among the
        admissible; then least loaded; then fewest free slots as the
        tiebreak (prefer topping up an already-warm replica over waking
        an idle one is the WRONG call for latency — most free slots
        first); fleet order makes the whole thing deterministic."""
        return (1 if health["slo_breached"] else 0,
                health["load"],
                -health["free_slots"],
                self.replicas.index(replica))

    def _place(self, request: Request):
        """The best replica that can take `request` right now, or
        None. Pure function of the replicas' observable health — no
        randomness, so placement (and every drill built on it)
        replays."""
        p_len = len(request.prompt)
        cands = [r for r in self.replicas
                 if r.can_take(p_len, int(request.max_new_tokens))]
        if not cands:
            return None
        best = min(cands, key=lambda r: self._score(r, r.health()))
        tenant = getattr(request, "tenant", None)
        if tenant is not None and self.tenant_affinity_slack is not None:
            home = self._tenant_home.get(tenant)
            if (home is not None and home is not best and home in cands
                    and not home.health()["slo_breached"]
                    and home.load()
                    <= best.load() + self.tenant_affinity_slack):
                self._m_affinity.inc(tenant=tenant)
                return home
        return best

    # -- fleet trace context (ISSUE 20) -----------------------------------

    def _hop(self, rid) -> int:
        """The next hop sequence number for `rid` — every placement/
        handoff/hedge/migration/canary event a request crosses gets one,
        so the merged timeline orders hops even when two land inside
        one wall-clock tick."""
        n = self._hop_seq.get(rid, 0) + 1
        self._hop_seq[rid] = n
        return n

    def _trace_context(self, request: Request) -> Request:
        """Stamp the router-assigned trace_id onto `request` — assigned
        once per rid at the fleet door and sticky across re-offers,
        re-placements, and hedges, so every hop event and every
        replica-side span carries ONE identity. A caller-provided (or
        journal-recovered) trace_id is adopted, never replaced."""
        tid = self._trace_ids.get(request.id)
        if tid is None:
            tid = request.trace_id or _next_trace_id()
            self._trace_ids[request.id] = tid
        if request.trace_id != tid:
            request = dataclasses.replace(request, trace_id=tid)
        return request

    def _finalize_trace(self, rid, status) -> None:
        """Close the request's cluster.request root span (hop count as
        the closing attribute) and drop its trace bookkeeping — every
        terminal path (normal finish, shed, failover loss) funnels
        through here so nothing leaks."""
        root = self._root_span.pop(rid, None)
        if root is not None:
            root.close(status=status, hops=self._hop_seq.get(rid, 0))
        self._trace_ids.pop(rid, None)
        self._hop_seq.pop(rid, None)

    def _submit_to(self, replica, request: Request) -> bool:
        rid = request.id
        root = self._root_span.get(rid)
        if root is None:
            root = trace.start_span("cluster.request", rid=rid,
                                    trace_id=request.trace_id)
            self._root_span[rid] = root
        ok = replica.submit(request, parent_span=root.span_id)
        if not ok:
            return False
        self._owner[rid] = replica
        self._requests[rid] = request
        self._submit_t[rid] = self.clock()
        tenant = getattr(request, "tenant", None)
        if tenant is not None:
            # the tenant's home for affinity: last successful placement
            # wins, so a tenant displaced by load rehomes where it
            # actually landed
            self._tenant_home[tenant] = replica
        self._attempts[rid] = self._attempts.get(rid, 0) + 1
        self._results.pop(rid, None)
        self.placements[replica.replica_id] += 1
        self._m_placements.inc(replica=replica.replica_id)
        hop = self._hop(rid)
        trace.point("cluster.place", parent=root.span_id, rid=rid,
                    replica=replica.replica_id,
                    attempt=self._attempts[rid],
                    trace_id=request.trace_id, hop=hop)
        self._log(event="cluster_place", id=rid,
                  replica=replica.replica_id,
                  attempt=self._attempts[rid],
                  trace_id=request.trace_id, hop=hop)
        if (self._rollout is not None
                and replica is self._rollout["canary"]):
            # canary assignment is a hop of its own: the divergence
            # watchdog and the merged timeline both need to know WHICH
            # requests rode the candidate weights
            chop = self._hop(rid)
            trace.point("cluster.canary", parent=root.span_id, rid=rid,
                        replica=replica.replica_id,
                        trace_id=request.trace_id, hop=chop)
            self._log(event="cluster_canary", id=rid,
                      replica=replica.replica_id,
                      trace_id=request.trace_id, hop=chop)
        return True

    def submit(self, request: Request) -> bool:
        """Place `request` on the best replica. False = cluster-wide
        backpressure (every admissible queue full — retry later) or a
        cluster-wide shed (every live replica shedding — a terminal
        ``shed`` Result is recorded, mirroring `LMServer.submit`)."""
        prior = self._results.get(request.id)
        if ((prior is not None and prior.status != "shed")
                or request.id in self._owner
                or request.id in self._hedges):
            # the _hedges check closes the id-namespace door: a caller
            # id colliding with an in-flight hedge copy's would be
            # silently renamed by the first-result-wins mapping
            raise ValueError(f"request id {request.id!r} already used")
        request = self._trace_context(request)
        self._maybe_handoff(request)
        target = self._place(request)
        if target is None:
            live = [r for r in self.replicas
                    if r.state == "live" and r.role != "prefill"]
            if not live:
                # every decode-capable replica is draining or dead:
                # there is NOTHING for a re-offer loop to wait out, so
                # spinning would hang the caller forever. The honest
                # terminal answer is a shed — and because submit()
                # admits ids whose prior result was a shed, the same
                # id may resubmit once add_replica revives the fleet.
                self._results[request.id] = Result(
                    id=request.id, tokens=[], status="shed",
                    finish_reason="shed",
                    error="no live decode-capable replica "
                          "(all draining or dead)",
                    trace_id=request.trace_id)
                self.cluster_sheds += 1
                trace.point("cluster.shed", rid=request.id,
                            trace_id=request.trace_id,
                            reason="no_live_replica")
                self._log(event="cluster_shed", id=request.id,
                          trace_id=request.trace_id,
                          reason="no_live_replica")
                self._finalize_trace(request.id, "shed")
                if self.slo is not None and self.slo.has("error_rate"):
                    self.slo.record("error_rate", ok=False)
                return False
            if live and all(r.server.brownout is not None
                            and r.server.brownout.shedding
                            for r in live):
                # every live replica is shedding: the honest terminal
                # answer, not a queue race to wait out
                self._results[request.id] = Result(
                    id=request.id, tokens=[], status="shed",
                    finish_reason="shed",
                    trace_id=request.trace_id)
                self.cluster_sheds += 1
                trace.point("cluster.shed", rid=request.id,
                            trace_id=request.trace_id,
                            reason="all_shedding")
                self._log(event="cluster_shed", id=request.id,
                          trace_id=request.trace_id,
                          reason="all_shedding")
                self._finalize_trace(request.id, "shed")
                if self.slo is not None and self.slo.has("error_rate"):
                    # a cluster-wide shed IS the fleet failing its
                    # users, even though each replica sheds by design
                    self.slo.record("error_rate", ok=False)
            return False
        return self._submit_to(target, request)

    # -- disaggregated prefill --------------------------------------------

    def _maybe_handoff(self, request: Request) -> None:
        """Route the prompt's chunk-grid prefix through a dedicated
        prefill replica (publishing its boundary snapshot for the
        decode replica to adopt) — unless the registry already covers
        it, in which case the prompt is hot cluster-wide and nobody
        prefills it again."""
        if self.prefix_registry is None:
            return
        if request.id in self._handed_off:
            return                      # a re-offered blocked submit
        pre = [r for r in self.replicas
               if r.role == "prefill" and r.state == "live"]
        if not pre:
            return
        chunk = pre[0].server.engine.prefill_chunk
        p_len = len(request.prompt)
        boundary = (p_len // chunk) * chunk
        if boundary < chunk:
            return                      # nothing on the snapshot grid
        if p_len + 1 > pre[0].server.engine.t_max:
            # a caller error (prompt too long to ever admit) — let the
            # normal submission path raise the honest ValueError; it
            # must not read as a prefill-replica fault below
            return
        cached = self.prefix_registry.covered(request.prompt)
        if cached >= boundary:
            rec = {"rid": request.id, "replica": None,
                   "prefix_tokens": cached, "cached": True}
        else:
            rep = min(pre, key=lambda r: (r.load(),
                                          self.replicas.index(r)))
            try:
                done = rep.prefill_only(request.prompt)
            except Exception as exc:
                # a prefill replica that cannot prefill is dead to the
                # fleet; the request itself just loses the handoff and
                # prefills on its decode replica
                self._fail_replica(rep, exc)
                return
            rec = {"rid": request.id, "replica": rep.replica_id,
                   "prefix_tokens": done, "cached": False}
        self._handed_off.add(request.id)
        self.handoffs.append(rec)
        self._m_handoffs.inc()
        hop = self._hop(request.id)
        trace.point("cluster.handoff", trace_id=request.trace_id,
                    hop=hop, **rec)
        self._log(event="cluster_handoff", id=rec["rid"],
                  replica=rec["replica"],
                  prefix_tokens=rec["prefix_tokens"],
                  cached=rec["cached"],
                  trace_id=request.trace_id, hop=hop)

    # -- the step loop ----------------------------------------------------

    def step(self) -> list[Result]:
        """One cluster tick: place any migration backlog, tick every
        live/draining replica (a step that raises marks the replica
        dead and migrates its journal), collect finished Results, and
        evaluate hedging. Returns the requests that finished."""
        self._place_migrations()
        out: list[Result] = []
        for rep in self.replicas:
            if rep.state == "dead":
                continue
            try:
                finished = rep.step()
            except Exception as exc:
                self._fail_replica(rep, exc)
                continue
            for r in finished:
                out.extend(self._record(rep, r))
        if self._out_of_band:
            # failover-finalized results (adopted terminal answers,
            # journal-less/retry-exhausted losses) join this step's
            # return — drain()'s contract covers every finish
            out.extend(self._out_of_band)
            self._out_of_band = []
        if self.hedge_after_s is not None:
            self._maybe_hedge()
        if self.slo is not None:
            self.slo.evaluate()
        if self.autoscaler is not None:
            self._autoscale()
        if self.watchdog is not None:
            self.watchdog.check()
        return out

    def _record(self, replica, result: Result) -> list[Result]:
        rid = result.id
        orig = self._hedges.get(rid)
        if orig is not None:
            # a hedge copy finished: first result answers under the
            # original id, the second is discarded (its work was the
            # hedge's price)
            del self._hedges[rid]
            self._hedge_target.pop(rid, None)
            if orig in self._results:
                return []
            result = dataclasses.replace(result, id=orig)
            rid = orig
        elif rid in self._results:
            return []                   # hedged original lost the race
        self._results[rid] = result
        self._owner.pop(rid, None)
        self._requests.pop(rid, None)
        self._submit_t.pop(rid, None)
        self._finalize_trace(rid, result.status)
        if self.slo is not None:
            if result.ttft_ms is not None and self.slo.has("ttft"):
                self.slo.observe("ttft", result.ttft_ms / 1e3)
            if self.slo.has("error_rate"):
                self.slo.record("error_rate", ok=result.status == "ok")
        return [result]

    def poll(self, rid: str) -> Result | None:
        return self._results.get(rid)

    def results(self) -> list[Result]:
        return list(self._results.values())

    def idle(self) -> bool:
        return (not self._pending_migration
                and not self._owner
                and all(r.idle() for r in self.replicas
                        if r.state != "dead"))

    def _check_liveness(self, *, submitting: bool = False) -> None:
        """Raise instead of spinning: with no live decode-capable
        replica, a migration backlog (or unsubmitted trace work) can
        never place and stepping makes no progress. Draining replicas
        still FINISH what they hold, so only the work that needs a
        fresh placement trips this."""
        if any(r.state == "live" and r.role != "prefill"
               for r in self.replicas):
            return
        if self._pending_migration or submitting:
            raise RuntimeError(
                "no live decode-capable replica left — the journals "
                "hold the unfinished requests; rebuild the fleet and "
                "migrate them")

    def drain(self) -> list[Result]:
        """Step until every placed request (and migration backlog) has
        finished; returns everything that finished."""
        out = list(self._out_of_band)
        self._out_of_band = []
        while not self.idle():
            self._check_liveness()
            out.extend(self.step())
        return out

    def run(self, trace_reqs, *, realtime: bool = False,
            on_full: str = "block") -> list[Result]:
        """Replay `[(arrival_s, Request), ...]` across the fleet and
        drain — `LMServer.run`'s contract at cluster scope."""
        if on_full not in ("block", "reject"):
            raise ValueError(f"on_full must be 'block' or 'reject', "
                             f"got {on_full!r}")
        trace_reqs = sorted(trace_reqs, key=lambda tr: tr[0])
        t0 = self.clock()
        out, i = [], 0
        while i < len(trace_reqs) or not self.idle():
            self._check_liveness(submitting=i < len(trace_reqs))
            now = self.clock() - t0
            while i < len(trace_reqs) and (not realtime
                                           or trace_reqs[i][0] <= now):
                req = trace_reqs[i][1]
                if self.submit(req):
                    i += 1
                    continue
                shed = self._results.get(req.id)
                if shed is not None and shed.status == "shed":
                    out.append(shed)
                    i += 1
                elif on_full == "reject":
                    r = Result(id=req.id, tokens=[], status="rejected")
                    self._results[r.id] = r
                    out.append(r)
                    i += 1
                else:
                    break               # blocked: re-offer next tick
            if realtime and self.idle() and i < len(trace_reqs):
                time.sleep(min(max(trace_reqs[i][0]
                                   - (self.clock() - t0), 0.0), 0.005))
                continue
            out.extend(self.step())
        return out

    # -- hedging ----------------------------------------------------------

    def _maybe_hedge(self) -> None:
        now = self.clock()
        for rid, rep in list(self._owner.items()):
            if rid in self._hedged or rid in self._hedges:
                continue                # one hedge per request (and
                #                         never hedge a hedge)
            if now - self._submit_t.get(rid, now) < self.hedge_after_s:
                continue
            if (self.retry is not None
                    and self._attempts.get(rid, 0)
                    > self.retry.max_retries):
                continue
            request = self._requests.get(rid)
            if request is None:
                continue
            p_len = len(request.prompt)
            others = [r for r in self.replicas
                      if r is not rep
                      and r.can_take(p_len,
                                     int(request.max_new_tokens))]
            if not others:
                continue
            hid = f"{rid}#h"
            if (hid in self._owner or hid in self._results
                    or hid in self._requests):
                # a REAL request already owns the hedge id's name —
                # don't hedge rather than collide namespaces
                continue
            target = min(others,
                         key=lambda r: self._score(r, r.health()))
            copy = dataclasses.replace(request, id=hid)
            # the copy decodes under the ORIGINAL's hop context: its
            # serve.request span parents under the same cluster.request
            # root, so the merged tree shows both carriers of one rid
            root = self._root_span.get(rid)
            pspan = root.span_id if root is not None else None
            if not target.submit(copy, parent_span=pspan):
                continue
            self._hedges[copy.id] = rid
            self._hedge_target[copy.id] = target
            self._hedged.add(rid)
            self._attempts[rid] = self._attempts.get(rid, 0) + 1
            self.hedges_sent += 1
            self._m_hedges.inc()
            hop = self._hop(rid)
            trace.point("cluster.hedge", parent=pspan, rid=rid,
                        replica=target.replica_id,
                        trace_id=request.trace_id, hop=hop)
            self._log(event="cluster_hedge", id=rid,
                      replica=target.replica_id,
                      trace_id=request.trace_id, hop=hop)

    # -- elasticity (serve/cluster/autoscaler.py) -------------------------

    def add_replica(self, replica) -> None:
        """Grow the fleet live — the autoscaler's scale-up path, and
        the operator's drain-then-revive move. The replica joins
        placement immediately: the very next submit/step can land on
        it, and a fleet the honest-shed branch declared dead becomes
        placeable again (shed ids may resubmit)."""
        if replica.replica_id in self._by_id:
            raise ValueError(
                f"replica id {replica.replica_id!r} is already in "
                f"the fleet")
        self.replicas.append(replica)
        self._by_id[replica.replica_id] = replica
        self.placements.setdefault(replica.replica_id, 0)
        self._g_live.set(sum(1 for r in self.replicas
                             if r.state == "live"))
        trace.point("cluster.scale_up", replica=replica.replica_id)
        self._log(event="cluster_scale_up",
                  replica=replica.replica_id,
                  live=sum(1 for r in self.replicas
                           if r.state == "live"))

    def _next_auto_id(self) -> str:
        while True:
            rid = f"auto{self._next_replica_ordinal}"
            self._next_replica_ordinal += 1
            if rid not in self._by_id:
                return rid

    def _autoscale(self) -> None:
        """Apply the autoscaler's decision for this tick: ``up`` spins
        a replica through `replica_factory` (warm when the factory
        hands the fleet's CompileCache to the server — spin-up is a
        deserialize, not a compile) and adds it; ``down`` drains the
        least-loaded live decode replica with live slot migration, so
        shrinking never drops or re-runs in-flight work."""
        decision = self.autoscaler.evaluate(self.healths(),
                                            now=self.clock())
        if decision is None:
            return
        action = decision["action"]
        if action == "up":
            rep = self.replica_factory(self._next_auto_id())
            self.add_replica(rep)
            self._m_scale.inc(action="up")
        elif action == "down":
            live = [r for r in self.replicas
                    if r.state == "live" and r.role != "prefill"]
            if len(live) <= 1:
                return                  # never drain the last one
            victim = min(live, key=lambda r: (r.load(),
                                              self.replicas.index(r)))
            self._m_scale.inc(action="down")
            self.drain_replica(victim.replica_id, migrate=True)

    # -- drain / failover -------------------------------------------------

    def drain_replica(self, replica_id: str, *, wait: bool = False,
                      migrate: bool = False) -> list[str]:
        """Graceful drain: placement stops immediately (the scheduler
        enters its sticky drain mode and sheds stragglers; the
        brownout, when armed, jumps to shed). With ``migrate=True``
        the replica's unfinished work leaves with it — queued entries
        re-enter the NORMAL placement path and RUNNING slots move
        LIVE: mid-decode KV, position, rng chain, and budget exported
        and imported into a peer's free slot, decode continuing there
        bit-identically (the elastic scale-down path). With
        `wait=True` the fleet steps until the replica is idle.
        Returns the ids whose work moved."""
        rep = self._by_id[replica_id]
        rep.drain()
        trace.point("cluster.drain", replica=replica_id)
        self._log(event="cluster_drain", replica=replica_id)
        moved = self._migrate_out(rep) if migrate else []
        while wait and not rep.idle():
            self.step()
        return moved

    def _migrate_out(self, rep) -> list[str]:
        """Empty a draining replica onto the fleet. Queued (and still-
        prefilling / retry-parked) entries are re-placed through
        `_place_migrations` — original id, seed, relative deadline
        preserved, the request re-runs from the prompt. Running slots
        migrate live instead: `Scheduler.export_running` lifts the
        slot's KV + sampling state, a compatible peer's
        `import_running` seats it, and decode resumes mid-request with
        bit-identical output (the engine's serial-parity contract).

        Journal protocol across the export→import gap: the SOURCE
        journal's submit stays open until the peer's import (which
        journals a normal submit on the TARGET) has landed; only then
        does the source write ``journal_migrate`` + the terminal
        ``"migrated"`` finish. A crash anywhere inside the gap
        therefore leaves the request pending in exactly one WAL — the
        source's — and the normal failover replay re-runs it from the
        prompt, bit-identically."""
        sch = rep.server.scheduler
        moved: list[str] = []
        # 1. work that never reached a slot re-enters normal placement
        for entry in sch.drain_pending():
            rid = entry.rid
            orig = self._hedges.pop(rid, None)
            if orig is not None:
                # a queued hedge copy: the original still runs on its
                # own replica — drop the copy (and close its WAL entry
                # so a later kill of THIS replica cannot resurrect it)
                self._hedge_target.pop(rid, None)
                self._hedged.discard(orig)
                if sch.journal is not None:
                    sch.journal.record_finish(rid, "shed",
                                              reason="drain")
                continue
            req = self._requests.get(rid)
            if req is None:
                # never placed by this router (a direct replica
                # submit): rebuild the Request from the entry so the
                # drain still honors it
                req = _entry_request(entry)
            self._owner.pop(rid, None)
            self._results.pop(rid, None)
            self._pending_migration.append(req)
            self._migrating_from[rid] = rep
            self._migration_src[rid] = rep.replica_id
            moved.append(rid)
        # 2. running slots move live. quiesce() first: it collects the
        # in-flight decode window without dispatching another, which is
        # the dispatch-idle point export_slot requires — and any
        # request that window finished is adopted, not migrated.
        running = list(sch.running_ids())
        if running and rep.server.engine.supports_slot_migration:
            for r in rep.server.quiesce():
                self._out_of_band.extend(self._record(rep, r))
            for rid in list(sch.running_ids()):
                target = self._slot_target(rep, rid)
                if target is not None:
                    # the peer may hold its own in-flight dispatched
                    # window — collect it (import needs the engine
                    # dispatch-idle, same as export does)
                    for r in target.server.quiesce():
                        self._out_of_band.extend(
                            self._record(target, r))
                entry, snap = sch.export_running(rid)
                seated = (target is not None
                          and target.server.scheduler.import_running(
                              entry, snap))
                if not seated:
                    # no compatible peer with a free slot right now:
                    # fall back to a from-the-prompt re-placement (the
                    # source submit is still open, so the journal
                    # contract already covers this path)
                    req = self._requests.get(rid)
                    if req is None:
                        req = _entry_request(entry)
                    self._owner.pop(rid, None)
                    self._results.pop(rid, None)
                    self._pending_migration.append(req)
                    self._migrating_from[rid] = rep
                    self._migration_src[rid] = rep.replica_id
                    moved.append(rid)
                    continue
                self._owner[rid] = target
                # the import landed: close the gap on the source WAL
                if sch.journal is not None:
                    sch.journal.record_migrate(
                        rid, "out", peer=target.replica_id)
                    sch.journal.record_finish(rid, "migrated")
                tj = target.server.scheduler.journal
                if tj is not None:
                    tj.record_migrate(rid, "in", peer=rep.replica_id)
                self.slot_migrations.append(
                    {"rid": rid, "from": rep.replica_id,
                     "to": target.replica_id})
                self._m_slot_migrations.inc()
                tid = self._trace_ids.get(rid)
                hop = self._hop(rid)
                root = self._root_span.get(rid)
                trace.point("cluster.slot_migrate",
                            parent=(root.span_id if root is not None
                                    else None),
                            rid=rid, src=rep.replica_id,
                            dst=target.replica_id,
                            trace_id=tid, hop=hop)
                self._log(event="cluster_slot_migrate", id=rid,
                          src=rep.replica_id,
                          dst=target.replica_id,
                          trace_id=tid, hop=hop)
                moved.append(rid)
        self._place_migrations()
        return moved

    def _slot_target(self, rep, rid) -> object | None:
        """The peer a running slot can move into: live, decode-
        capable, migration-capable, geometry-identical (head/block
        layout and cache dtype — import_slot re-validates), not
        draining, t_max at least the source's, and holding a free
        slot. Least-loaded first, fleet order breaking ties — the same
        determinism contract as placement."""
        e1 = rep.server.engine
        cands = []
        for r in self.replicas:
            if r is rep or r.state != "live" or r.role == "prefill":
                continue
            e2 = r.server.engine
            if (not e2.supports_slot_migration
                    or r.server.scheduler.draining
                    or not e2.free_slots()
                    or e2.t_max < e1.t_max
                    or e2._cfg.embed_dim != e1._cfg.embed_dim
                    or e2._cfg.num_heads != e1._cfg.num_heads
                    or e2._cfg.num_blocks != e1._cfg.num_blocks
                    or e2._cfg.cache_dtype != e1._cfg.cache_dtype):
                continue
            cands.append(r)
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load(),
                                         self.replicas.index(r)))

    def kill_replica(self, replica_id: str) -> list[str]:
        """The failover drill: hard-kill a replica (its journal WAL is
        all that survives) and migrate its accepted-but-unfinished
        requests onto the survivors. Returns the migrated ids."""
        rep = self._by_id[replica_id]
        return self._fail_replica(
            rep, RuntimeError("killed by operator drill"))

    def _fail_replica(self, replica, exc) -> list[str]:
        """THE cluster recovery entry point (the serve/ exception-
        discipline scan recognizes it next to the scheduler's
        `_quarantine`/`_abort_running`): mark the replica dead, adopt
        any terminal Results its final tick salvaged, and queue its
        journal's pending requests for migration onto survivors."""
        already_dead = replica.state == "dead"
        replica.kill()
        # a dead home cannot serve affinity: drop its tenants so their
        # next placement rehomes on a survivor
        self._tenant_home = {t: r for t, r in self._tenant_home.items()
                             if r is not replica}
        if not already_dead:
            self._m_deaths.inc()
            self._g_live.set(sum(1 for r in self.replicas
                                 if r.state == "live"))
            trace.point("cluster.replica_dead",
                        replica=replica.replica_id,
                        error=f"{type(exc).__name__}: {exc}")
            self._log(event="cluster_replica_dead",
                      replica=replica.replica_id,
                      error=f"{type(exc).__name__}: {exc}")
        # hedge copies RUNNING ON the dying replica die with it: drop
        # their mappings so (a) the original — when still live on its
        # own replica — is no longer considered hedged and may
        # re-hedge, and (b) the journal replay below cannot resurrect
        # the copy. An original BOTH of whose carriers are now gone
        # (its own replica died journal-less earlier) is an honest
        # loss, recorded here.
        dead_copies = set()
        for hid, tgt in list(self._hedge_target.items()):
            if tgt is not replica:
                continue
            dead_copies.add(hid)
            del self._hedge_target[hid]
            orig = self._hedges.pop(hid, None)
            if orig is None:
                continue
            self._hedged.discard(orig)
            if orig not in self._owner and orig not in self._results:
                lost = Result(
                    id=orig, tokens=[], status="error",
                    finish_reason="error",
                    error=f"replica {replica.replica_id} died holding "
                          f"the hedge copy of an already-lost request",
                    trace_id=self._trace_ids.get(orig))
                self._results[orig] = lost
                self._out_of_band.append(lost)
                self._finalize_trace(orig, "error")
        # terminal results the dying tick already finalized (an
        # engine-failure tick salvages completed entries with their
        # true statuses — api.step's pop_failed path) are real answers;
        # adopt them instead of re-running finished work
        for rid, owner in list(self._owner.items()):
            if owner is not replica:
                continue
            r = replica.poll(rid)
            if r is not None and r.status != "error":
                self._out_of_band.extend(self._record(replica, r))
        migrated: list[str] = []
        if replica.journal_path is not None:
            for req in pending_requests(replica.journal_path):
                if req.id in dead_copies:
                    continue            # a hedge copy handled above
                if self._owner.get(req.id) not in (None, replica):
                    # a live mid-decode migration moved this slot onto
                    # a survivor before the death closed the source
                    # WAL: the open submit is stale — the survivor is
                    # decoding it right now, and replaying here would
                    # answer the id twice
                    continue
                if any(p.id == req.id
                       for p in self._pending_migration):
                    continue            # a drain already queued it
                orig = self._hedges.get(req.id, req.id)
                if orig in self._results:
                    continue            # already answered (hedge won,
                    #                     or adopted above)
                if req.id in self._hedges:
                    # a dead hedge copy: the original is still running
                    # on its own replica — don't resurrect the copy
                    del self._hedges[req.id]
                    self._hedge_target.pop(req.id, None)
                    self._hedged.discard(orig)
                    continue
                if req.id in self._hedges.values():
                    # the original died but its hedge copy is still
                    # running elsewhere: the copy IS the in-flight
                    # recovery — let it answer instead of migrating a
                    # duplicate
                    self._owner.pop(req.id, None)
                    continue
                if (self.retry is not None
                        and self._attempts.get(req.id, 0)
                        > self.retry.max_retries):
                    lost = Result(
                        id=req.id, tokens=[], status="error",
                        finish_reason="error",
                        error=f"replica {replica.replica_id} died and "
                              f"the retry budget is exhausted",
                        trace_id=req.trace_id)
                    self._results[req.id] = lost
                    self._out_of_band.append(lost)
                    self._owner.pop(req.id, None)
                    self._finalize_trace(req.id, "error")
                    continue
                self._owner.pop(req.id, None)
                self._results.pop(req.id, None)
                self._pending_migration.append(req)
                self._migration_src[req.id] = replica.replica_id
                migrated.append(req.id)
        else:
            # no WAL: the in-flight requests are honestly lost —
            # except ones whose hedge copy still runs elsewhere (the
            # copy answers under the original id when it finishes)
            for rid, owner in list(self._owner.items()):
                if owner is not replica:
                    continue
                self._owner.pop(rid, None)
                if rid in self._hedges.values():
                    continue
                lost = Result(
                    id=rid, tokens=[], status="error",
                    finish_reason="error",
                    error=f"replica {replica.replica_id} died "
                          f"without a journal",
                    trace_id=self._trace_ids.get(rid))
                self._results[rid] = lost
                self._out_of_band.append(lost)
                self._finalize_trace(rid, "error")
        self._place_migrations()
        return migrated

    def _place_migrations(self) -> None:
        """Offer the migration backlog to survivors, original submit
        order preserved; a backlog head the fleet cannot take yet
        blocks the rest (FIFO — recovered requests must not reorder
        behind each other)."""
        while self._pending_migration:
            req = self._pending_migration[0]
            # a journal-recovered (or direct-submitted) request may not
            # have crossed submit(): adopt its WAL trace_id into the
            # router's context — failover must keep the original
            # identity, never mint a new one
            req = self._trace_context(req)
            target = self._place(req)
            if target is None or not self._submit_to(target, req):
                return
            self._pending_migration.pop(0)
            src = self._migrating_from.pop(req.id, None)
            if src is not None:
                # the re-placement landed and the TARGET journaled its
                # own submit — only now does the still-open source WAL
                # close with the terminal migrated finish (a crash any
                # earlier replays the request from the source)
                sj = src.server.scheduler.journal
                if sj is not None and src.state != "dead":
                    sj.record_migrate(req.id, "out",
                                      peer=target.replica_id)
                    sj.record_finish(req.id, "migrated")
            self.migrations.append({"rid": req.id,
                                    "replica": target.replica_id,
                                    "trace_id": req.trace_id})
            self._m_migrations.inc()
            src_id = self._migration_src.pop(req.id, None)
            hop = self._hop(req.id)
            root = self._root_span.get(req.id)
            trace.point("cluster.migrate",
                        parent=(root.span_id if root is not None
                                else None),
                        rid=req.id, replica=target.replica_id,
                        src=src_id, trace_id=req.trace_id, hop=hop)
            self._log(event="cluster_migrate", id=req.id,
                      replica=target.replica_id, src=src_id,
                      trace_id=req.trace_id, hop=hop)

    # -- weight rollout (checkpoint/rollout.py at fleet scope) ------------

    def start_rollout(self, candidate, *, replica_id=None) -> str:
        """Open a fleet rollout: ONE replica becomes the canary. The
        candidate (a params tree, or a sharded-checkpoint path —
        checkpoint/sharded.py — restored against the canary engine's
        mesh + rules) is spot-checked on the canary's already-compiled
        programs first; a NaN/garbage candidate raises here and the
        fleet is untouched. On success the canary's weights are
        swapped in-place (its in-flight slots keep decoding) while the
        rest of the fleet keeps the old weights — normal placement
        keeps routing live traffic onto the canary, which is the
        controlled-exposure mechanism at cluster scope. Returns the
        canary's replica_id; `finish_rollout` reads the health
        documents and promotes the rest or swaps the canary back."""
        if self._rollout is not None:
            raise RuntimeError(
                f"a rollout is already open (canary "
                f"{self._rollout['canary'].replica_id!r}) — "
                f"finish_rollout() it before starting another")
        cands = [r for r in self.replicas
                 if r.state == "live" and r.role != "prefill"]
        if replica_id is not None:
            rep = self._by_id[replica_id]
            if rep.state != "live" or rep.role == "prefill":
                raise ValueError(
                    f"replica {replica_id!r} is "
                    f"{rep.state}/{rep.role} — the canary must be a "
                    f"live decode-capable replica")
        elif not cands:
            raise RuntimeError("no live decode-capable replica to "
                               "canary on")
        else:
            # least-loaded live replica: the cheapest place to expose
            # candidate weights, deterministic via the placement score
            rep = min(cands, key=lambda r: self._score(r, r.health()))
        if isinstance(candidate, (str, os.PathLike)):
            from idc_models_tpu.checkpoint.sharded import restore_sharded

            eng = rep.server.engine
            rules = eng._partition_rules
            candidate = restore_sharded(
                candidate,
                mesh=eng._cfg.mesh if rules is not None else None,
                rules=rules, logger=self.logger)
        rep.server.metrics.on_rollout(stage="staging")
        check = rep.server.engine.spot_check_params(candidate)
        if not check["ok"]:
            detail = {1: "non-finite logits",
                      2: f"magnitude-blown logits (max |x| = "
                         f"{check['max_abs']:.3g})"}
            rep.server.metrics.on_rollout(
                stage="rolled_back", outcome="rolled_back",
                reason=f"spot-check: {detail[check['code']]}")
            raise ValueError(
                f"candidate failed the spot-check on canary "
                f"{rep.replica_id!r}: {detail[check['code']]} — the "
                f"fleet was not touched")
        old = rep.server.engine._params
        rep.server.swap_params(candidate)
        self._rollout = {"canary": rep, "candidate": candidate,
                         "old": old,
                         "baseline": {r.replica_id: r.health()
                                      for r in self.replicas
                                      if r is not rep
                                      and r.state == "live"}}
        rep.server.metrics.on_rollout(stage="canary")
        trace.point("cluster.rollout_canary", replica=rep.replica_id)
        self._log(event="cluster_rollout", stage="canary",
                  replica=rep.replica_id)
        return rep.replica_id

    def finish_rollout(self) -> str:
        """Decide the open rollout from the HEALTH DOCUMENTS: the
        canary must not be SLO-breached, brownout-shedding, or dead
        while the rest of the fleet is clean. Healthy -> promote: every
        other live replica's weights are swapped in place (in-flight
        work keeps decoding; zero recompiles — all replicas share the
        process jit cache). Unhealthy -> the canary swaps BACK to the
        old weights; nothing else ever saw the candidate. Returns
        "promoted" or "rolled_back"."""
        ro = self._rollout
        if ro is None:
            raise RuntimeError("no rollout open — start_rollout() "
                               "first")
        rep = ro["canary"]
        h = rep.health() if rep.state != "dead" else {"status": "dead"}
        fleet_breached = any(b["slo_breached"]
                             for b in ro["baseline"].values())
        reasons = []
        if rep.state != "live":
            reasons.append(f"canary is {rep.state}")
        else:
            if h["slo_breached"] and not fleet_breached:
                reasons.append("canary SLO breached while the fleet "
                               "is clean")
            if h["shedding"]:
                reasons.append(f"canary shedding (brownout stage "
                               f"{h['brownout_stage']})")
        if reasons:
            if rep.state == "live":
                rep.server.swap_params(ro["old"])
            reason = "; ".join(reasons)
            rep.server.metrics.on_rollout(
                stage="rolled_back", outcome="rolled_back",
                reason=reason)
            verdict = "rolled_back"
        else:
            for other in self.replicas:
                if other is rep or other.state != "live":
                    continue
                other.server.swap_params(ro["candidate"])
            rep.server.metrics.on_rollout(stage="promoted",
                                          outcome="promoted")
            reason = None
            verdict = "promoted"
        trace.point("cluster.rollout_done", replica=rep.replica_id,
                    outcome=verdict)
        self._log(event="cluster_rollout", stage=verdict,
                  replica=rep.replica_id, reason=reason)
        self._rollout = None
        return verdict

    # -- lifecycle / observability ----------------------------------------

    @property
    def rollout_canary(self):
        """The open rollout's canary replica, or None — the read the
        canary-divergence watchdog (and an operator poll) uses without
        reaching into the rollout dict."""
        return (None if self._rollout is None
                else self._rollout["canary"])

    def close(self) -> None:
        """Shut every replica down (journals flushed); the router's
        surface then refuses new work through the replicas' own closed
        schedulers."""
        for rep in self.replicas:
            if rep.state != "dead":
                rep.server.close()

    def healths(self) -> list[dict]:
        """Every replica's placement-signal document — the fleet view
        an operator (or test) reads in one call."""
        return [r.health() for r in self.replicas]

    def summary(self) -> dict:
        """The cluster rollup: pooled per-request aggregates over
        every replica (serve/metrics.aggregate_summaries), the
        router's own counters, and the prefix registry's — the record
        `bench_serving_cluster` and the CLI epilogue report."""
        out = aggregate_summaries([r.server.metrics
                                   for r in self.replicas])
        # replica-level sheds (a straggling direct submit refused by a
        # draining replica's brownout) plus the router-level
        # cluster-wide ones — either way the caller got status="shed"
        out["cluster_shed"] += self.cluster_sheds
        out.update({
            "cluster_replicas_live": sum(1 for r in self.replicas
                                         if r.state == "live"),
            "cluster_replicas_draining": sum(
                1 for r in self.replicas if r.state == "draining"),
            "cluster_replicas_dead": sum(1 for r in self.replicas
                                         if r.state == "dead"),
            "cluster_placements": dict(self.placements),
            "cluster_migrations": len(self.migrations),
            "cluster_slot_migrations": len(self.slot_migrations),
            "cluster_handoffs": len(self.handoffs),
            "cluster_hedges": self.hedges_sent,
        })
        if self.prefix_registry is not None:
            out.update(self.prefix_registry.summary())
        return out

    def _log(self, **record) -> None:
        if self.logger is not None:
            self.logger.log(**record)
