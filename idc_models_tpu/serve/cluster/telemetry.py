"""Fleet-level observability: one merged /metrics, one fleet /healthz,
and the anomaly watchdogs — ISSUE 20's cluster telemetry plane.

Every replica already owns an honest per-replica `MetricsRegistry`
(build_replica) and health document (`Replica.health`), and the router
keeps its own registry of cluster_* series. What was missing is the
operator's single pane:

- `ClusterTelemetry.merged_registry()` folds every replica registry
  into ONE fresh registry per scrape — each per-replica series gains a
  ``replica`` label (histograms merge state-wise, no re-observation) —
  then derives the fleet rollups FROM the just-merged series:
  ``cluster_fleet_queue_depth``, ``cluster_fleet_kv_pages_used`` /
  ``_total``, and per-tenant fleet totals. Because the rollups are
  sums over the very series the same exposition carries, "fleet rollup
  == sum of per-replica series" holds by construction at every
  instant, which is exactly what the bench gate asserts.
- `ClusterTelemetry.health()` is the fleet /healthz: every replica's
  health document embedded verbatim, plus fleet aggregates, the
  cluster SLO engine's state, the autoscaler's live hysteresis clocks
  (`Autoscaler.state_doc`), and the shared compile cache's hit/miss
  counters. The NON-cluster /healthz document is untouched —
  `observe.MetricsExporter` only serves this shape when armed with a
  ClusterTelemetry.
- `ClusterWatchdog` runs four windowed detectors over the live fleet
  objects and emits a frozen-schema ``cluster_anomaly`` jsonl record
  (plus a ``cluster_anomalies_total{kind}`` counter) on each
  TRANSITION into the anomalous state — hysteresis like `SLOEngine`,
  so a persistent fault fires once, not once per tick, and a clean
  run stays silent.

Watchdog detectors (all windowed over `WatchdogConfig.window_s`):

``accept_collapse``     fleet speculative accept rate over the window
                        fell below ``accept_rate_floor`` (only judged
                        once ``accept_min_drafted`` tokens were
                        drafted in the window — a cold drafter is not
                        a collapsed one).
``compile_churn``       one replica observed more than
                        ``compile_churn_limit`` fresh XLA compiles in
                        the window — shape-bucket thrash or a cache
                        that stopped hitting.
``migration_spike``     more than ``migration_spike_limit`` journal +
                        live-slot migrations fleet-wide in the window
                        — replicas are dying or draining faster than
                        steady state.
``canary_divergence``   the rollout canary's own SLO engine is
                        breached while NO baseline decode replica's
                        is — the new weights themselves are the
                        regression, so the operator should roll back
                        rather than scale out.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from idc_models_tpu.observe.metrics_registry import MetricsRegistry


class ClusterTelemetry:
    """The fleet aggregation surface over one `Router`: merged
    replica-labeled metrics with derived rollups, and the fleet
    health document. Stateless per scrape — every call reads the live
    fleet, so a replica added or killed between scrapes just appears
    or disappears."""

    def __init__(self, router, *, compile_cache=None):
        self.router = router
        # the fleet's shared persistent compile cache, when spin-up
        # uses one — its hit/miss counters belong on the fleet health
        # document (satellite: warm spin-up visibility)
        self.compile_cache = compile_cache

    # -- merged metrics ---------------------------------------------------

    def merged_registry(self) -> MetricsRegistry:
        """One fresh registry holding: the router's own cluster_*
        series verbatim, every replica registry's series re-labeled
        with ``replica=<id>``, and the fleet rollup series derived
        from the merged copies."""
        out = MetricsRegistry()
        router_reg = getattr(self.router, "registry", None)
        if router_reg is not None:
            for inst in router_reg.instruments():
                self._copy(out, inst, None)
        for rep in self.router.replicas:
            reg = getattr(rep, "registry", None)
            if reg is None or reg is router_reg:
                # a replica sharing the router's (or the process)
                # registry has no per-replica series to re-label —
                # the verbatim copy above already carries it
                continue
            for inst in reg.instruments():
                self._copy(out, inst, rep.replica_id)
        self._rollups(out)
        return out

    @staticmethod
    def _copy(out: MetricsRegistry, inst, replica_id) -> None:
        extra = {} if replica_id is None else {"replica": replica_id}
        if "replica" in inst.label_names and extra:
            # already replica-labeled at the source — re-labeling
            # would double-report; copy verbatim instead
            extra = {}
        names = inst.label_names + tuple(extra)
        existing = out.get(inst.name)
        if existing is not None and (
                existing.kind != inst.kind
                or existing.label_names != names):
            # same metric name registered with an incompatible shape
            # (e.g. the router's shared-registry copy of a serve_*
            # gauge vs. a replica's) — the first writer wins; merging
            # two label schemas into one series would lie
            return
        if inst.kind == "counter":
            m = out.counter(inst.name, inst.help, labels=names)
            for labels, val in inst._series():
                if val:
                    m.inc(val, **labels, **extra)
                else:
                    m.inc(0.0, **labels, **extra)
        elif inst.kind == "gauge":
            m = out.gauge(inst.name, inst.help, labels=names)
            for labels, val in inst._series():
                m.set(val, **labels, **extra)
        elif inst.kind == "histogram":
            m = out.histogram(inst.name, inst.help, labels=names,
                              buckets=inst.buckets)
            for labels, val in inst._series():
                m.merge_state(val, **labels, **extra)

    @staticmethod
    def _rollups(out: MetricsRegistry) -> None:
        """Derive the fleet series from the merged replica-labeled
        copies — summing the exposition's own series, not the live
        objects, is what makes "rollup == sum of scrapes" exact."""

        def fleet_sum(name):
            inst = out.get(name)
            if inst is None:
                return None
            vals = [v for labels, v in inst._series()
                    if labels.get("replica")]
            return sum(vals) if vals else None

        q = fleet_sum("serve_queue_depth")
        if q is not None:
            out.gauge(
                "cluster_fleet_queue_depth",
                "sum of every replica's admission queue depth "
                "(rollup of serve_queue_depth{replica=...})").set(q)
        for src, dst in (("serve_kv_pages_used",
                          "cluster_fleet_kv_pages_used"),
                         ("serve_kv_pages_total",
                          "cluster_fleet_kv_pages_total")):
            v = fleet_sum(src)
            if v is not None:
                out.gauge(dst, f"fleet rollup of {src} across "
                               f"replicas").set(v)
        for src, dst in (("serve_tenant_requests_total",
                          "cluster_fleet_tenant_requests_total"),
                         ("serve_tenant_tokens_emitted_total",
                          "cluster_fleet_tenant_tokens_total")):
            inst = out.get(src)
            if inst is None:
                continue
            sums: dict[str, float] = {}
            for labels, v in inst._series():
                t = labels.get("tenant")
                if t is not None:
                    sums[t] = sums.get(t, 0.0) + v
            if sums:
                c = out.counter(
                    dst, f"per-tenant fleet total (rollup of {src} "
                         f"across replicas and statuses)",
                    labels=("tenant",))
                for t, v in sums.items():
                    c.inc(v, tenant=t)

    def prometheus_text(self) -> str:
        return self.merged_registry().prometheus_text()

    # -- fleet health -----------------------------------------------------

    def health(self) -> dict:
        """The fleet /healthz document: per-replica health docs
        embedded verbatim under ``replicas``, fleet aggregates under
        ``fleet``, plus the cluster SLO engine state, the autoscaler's
        live hysteresis clocks, and the shared compile cache's
        hit/miss counters when each is armed."""
        r = self.router
        reps = {rep.replica_id: rep.health() for rep in r.replicas}
        live = [h for h in reps.values() if h["state"] == "live"]
        fleet = {
            "replicas_live": len(live),
            "replicas_draining": sum(
                1 for h in reps.values() if h["state"] == "draining"),
            "replicas_dead": sum(
                1 for h in reps.values() if h["state"] == "dead"),
            "queue_depth": sum(h["queue_depth"] for h in live),
            "load": sum(h["load"] for h in live),
            "kv_pages_used": sum(
                h["kv_pages_used"] or 0 for h in live),
            "kv_pages_total": sum(
                h["kv_pages_total"] or 0 for h in live),
        }
        slo_breached = bool(r.slo is not None and r.slo.breached())
        status = ("ok" if live and not fleet["replicas_dead"]
                  and not slo_breached else "degraded")
        doc = {"status": status, "replicas": reps, "fleet": fleet}
        if r.slo is not None:
            doc["slo"] = r.slo.state_doc()
        if r.autoscaler is not None:
            doc["autoscaler"] = r.autoscaler.state_doc()
        if self.compile_cache is not None:
            cs = self.compile_cache.summary()
            doc["compile_cache"] = {
                "hits": cs["hits"], "misses": cs["misses"],
                "stores": cs["stores"]}
        return doc


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """The anomaly detectors' knobs, validated at construction."""

    window_s: float = 5.0
    accept_rate_floor: float = 0.2
    accept_min_drafted: int = 64
    compile_churn_limit: int = 3
    migration_spike_limit: int = 4

    def __post_init__(self):
        if self.window_s <= 0:
            raise ValueError(f"need window_s > 0, got {self.window_s}")
        if not 0 <= self.accept_rate_floor <= 1:
            raise ValueError(f"need 0 <= accept_rate_floor <= 1, got "
                             f"{self.accept_rate_floor}")
        if self.accept_min_drafted < 1:
            raise ValueError(f"need accept_min_drafted >= 1, got "
                             f"{self.accept_min_drafted}")
        if self.compile_churn_limit < 0 or self.migration_spike_limit < 0:
            raise ValueError(
                f"limits must be >= 0, got compile_churn_limit="
                f"{self.compile_churn_limit} migration_spike_limit="
                f"{self.migration_spike_limit}")


class ClusterWatchdog:
    """Windowed anomaly detectors over the live fleet. Drive `check()`
    once per router step (or health poll); each detector samples a
    CUMULATIVE reading into its window and judges the windowed delta,
    then fires only on the transition into the anomalous state.

    A firing appends one frozen-schema record — ``{ts, event:
    "cluster_anomaly", kind, replica, value, threshold, window_s}``
    (``replica`` null for fleet-wide kinds) — to the logger, bumps
    ``cluster_anomalies_total{kind}``, and records it in
    `self.anomalies`. `check()` returns the records fired by THAT
    call, so a bench gate can assert fire-on-fault / silent-on-clean
    directly."""

    KINDS = ("accept_collapse", "compile_churn", "migration_spike",
             "canary_divergence")

    def __init__(self, router, cfg: WatchdogConfig | None = None, *,
                 logger=None, registry=None, clock=time.monotonic):
        self.router = router
        self.cfg = cfg if cfg is not None else WatchdogConfig()
        self.logger = logger
        self.clock = clock
        reg = (registry if registry is not None
               else getattr(router, "registry", None))
        self._m_anomalies = (
            None if reg is None else reg.counter(
                "cluster_anomalies_total",
                "anomaly watchdog firings by kind",
                labels=("kind",)))
        self.anomalies: list[dict] = []
        # (kind-scope key) -> deque of (t, cumulative value)
        self._samples: dict[tuple, deque] = {}
        self._alerting: dict[tuple, bool] = {}

    def _windowed(self, key: tuple, now: float, value: float) -> float:
        """Append one cumulative reading and return the delta over the
        trailing window (value minus the oldest retained reading)."""
        q = self._samples.setdefault(key, deque())
        q.append((now, value))
        cutoff = now - self.cfg.window_s
        while len(q) > 1 and q[0][0] < cutoff:
            q.popleft()
        return value - q[0][1]

    def _judge(self, fired: list, *, kind: str, replica, anomalous: bool,
               value: float, threshold: float) -> None:
        key = (kind, replica)
        if not anomalous:
            self._alerting[key] = False
            return
        if self._alerting.get(key):
            return
        self._alerting[key] = True
        rec = {"kind": kind, "replica": replica,
               "value": round(float(value), 4),
               "threshold": float(threshold),
               "window_s": self.cfg.window_s}
        self.anomalies.append(rec)
        fired.append(rec)
        if self._m_anomalies is not None:
            self._m_anomalies.inc(kind=kind)
        if self.logger is not None:
            self.logger.log(event="cluster_anomaly", **rec)

    def check(self, now: float | None = None) -> list[dict]:
        """One detector pass; returns the anomaly records fired by
        this call (empty on a healthy fleet)."""
        now = self.clock() if now is None else now
        cfg = self.cfg
        r = self.router
        fired: list[dict] = []
        live = [rep for rep in r.replicas if rep.state != "dead"]

        # 1. fleet speculative accept-rate collapse
        drafted = sum(rep.server.metrics.spec_drafted for rep in live)
        accepted = sum(rep.server.metrics.spec_accepted for rep in live)
        d_drafted = self._windowed(("drafted", None), now, drafted)
        d_accepted = self._windowed(("accepted", None), now, accepted)
        if d_drafted >= cfg.accept_min_drafted:
            rate = d_accepted / d_drafted
            self._judge(fired, kind="accept_collapse", replica=None,
                        anomalous=rate < cfg.accept_rate_floor,
                        value=rate, threshold=cfg.accept_rate_floor)
        # too little drafting in the window to judge: hold state — a
        # quiet drafter neither fires nor clears a standing alert

        # 2. per-replica compile churn
        for rep in live:
            d = self._windowed(("compiles", rep.replica_id), now,
                               rep.server.metrics.compiles_observed)
            self._judge(fired, kind="compile_churn",
                        replica=rep.replica_id,
                        anomalous=d > cfg.compile_churn_limit,
                        value=d, threshold=cfg.compile_churn_limit)

        # 3. fleet migration-rate spike (journal failover + live slot)
        migs = len(r.migrations) + len(r.slot_migrations)
        d = self._windowed(("migrations", None), now, migs)
        self._judge(fired, kind="migration_spike", replica=None,
                    anomalous=d > cfg.migration_spike_limit,
                    value=d, threshold=cfg.migration_spike_limit)

        # 4. canary-vs-baseline SLO divergence
        canary = getattr(r, "rollout_canary", None)
        if canary is not None and canary.state == "live":
            ch = canary.health()
            baseline_breached = any(
                rep.health()["slo_breached"] for rep in r.replicas
                if rep is not canary and rep.state == "live"
                and rep.role != "prefill")
            self._judge(
                fired, kind="canary_divergence",
                replica=canary.replica_id,
                anomalous=bool(ch["slo_breached"]
                               and not baseline_breached),
                value=1.0 if ch["slo_breached"] else 0.0,
                threshold=1.0)
        else:
            # rollout closed (or no canary): clear any standing canary
            # alert so the NEXT rollout's divergence fires fresh
            for key in list(self._alerting):
                if key[0] == "canary_divergence":
                    self._alerting[key] = False
        return fired
