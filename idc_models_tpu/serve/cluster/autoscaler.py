"""Autoscaling policy over the fleet's health documents — the elastic
half of ROADMAP item 1's control plane.

The router already publishes, per replica, everything a scaling
decision legitimately reads: queue depth, load, slot/page headroom,
brownout stage, SLO burn (`Replica.health`, the in-process twin of
`/healthz`). This module turns those documents into ``"up"`` /
``"down"`` / ``"hold"`` with the two properties a production policy
needs and ad-hoc threshold code never has:

- **Purity**: `decide()` is a function of (healths, now, state,
  config) and nothing else — no wall clock, no I/O, no hidden
  counters — so every decision replays deterministically from a
  recorded health stream, and the hysteresis unit tests drive it with
  a fake clock.
- **Hysteresis + cooldown**: a scale signal must HOLD for `dwell_s`
  before it fires (one bursty tick never buys a replica), and after
  any action the policy is quiet for `cooldown_s` (a freshly added
  replica gets time to absorb load before the signal is re-read —
  without this, the up signal persists through spin-up and the fleet
  staircases to max).

Signals (live decode-capable replicas only — draining/dead/prefill
replicas neither count toward capacity nor vote):

===========================  =========================================
scale **up** when            mean queued-per-replica > ``queue_high``,
                             OR any live replica is brownout-shedding,
                             OR (paged) fleet page headroom fraction
                             < ``page_headroom``
scale **down** when          mean queued-per-replica < ``queue_low``
                             AND nobody is shedding or SLO-burning
bounded by                   ``min_replicas`` <= fleet <= ``max_replicas``
===========================  =========================================

`Autoscaler` wraps the pure function with the state threading and a
frozen-schema ``autoscale_decision`` jsonl event per ACTION (holds are
silent — drills replay the decision stream, not a heartbeat), which is
what `bench_serving_elastic` and the drain drills assert against.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The policy knobs, validated at construction so a bad config
    fails at fleet build, not on the first overload tick.

    `queue_low` must sit strictly below `queue_high`: the gap IS the
    hysteresis band — equal thresholds would oscillate a borderline
    fleet up and down every cooldown."""

    min_replicas: int = 1
    max_replicas: int = 4
    queue_high: float = 4.0
    queue_low: float = 1.0
    page_headroom: float = 0.1
    dwell_s: float = 0.5
    cooldown_s: float = 2.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if not 0 <= self.queue_low < self.queue_high:
            raise ValueError(
                f"need 0 <= queue_low < queue_high (the gap is the "
                f"hysteresis band), got {self.queue_low} vs "
                f"{self.queue_high}")
        if not 0 <= self.page_headroom < 1:
            raise ValueError(f"need 0 <= page_headroom < 1, got "
                             f"{self.page_headroom}")
        if self.dwell_s < 0 or self.cooldown_s < 0:
            raise ValueError(
                f"need dwell_s >= 0 and cooldown_s >= 0, got "
                f"{self.dwell_s} / {self.cooldown_s}")


def _fresh_state() -> dict:
    return {"up_since": None, "down_since": None, "last_action_t": None}


def decide(healths, *, now: float, state: dict | None = None,
           cfg: AutoscaleConfig | None = None) -> tuple:
    """One pure decision: ``(action, reason, new_state)`` where action
    is ``"up"`` / ``"down"`` / ``"hold"``. `state` is the opaque dict a
    previous call returned (None = fresh); `healths` is the router's
    `healths()` list. The caller applies the action; this function
    only ever reads its arguments."""
    cfg = cfg if cfg is not None else AutoscaleConfig()
    st = dict(state) if state else _fresh_state()
    live = [h for h in healths
            if h["state"] == "live" and h["role"] != "prefill"]
    n = len(live)
    if n == 0:
        # nothing live to read a signal from — scaling up on zero
        # evidence is the router/operator's call (add_replica), not a
        # policy the hysteresis clock should own
        return "hold", "no live decode replica", _fresh_state()
    queued = sum(h["queue_depth"] + h["load"] for h in live)
    mean_q = queued / n
    shedding = any(h["shedding"] for h in live)
    burning = any(h["slo_breached"] for h in live)
    pages_total = sum(h["kv_pages_total"] or 0 for h in live)
    pages_used = sum(h["kv_pages_used"] or 0 for h in live)
    headroom = (1.0 - pages_used / pages_total if pages_total else None)
    up_reason = None
    if mean_q > cfg.queue_high:
        up_reason = (f"mean queued/replica {mean_q:.2f} > "
                     f"queue_high {cfg.queue_high}")
    elif shedding:
        up_reason = "a live replica is brownout-shedding"
    elif headroom is not None and headroom < cfg.page_headroom:
        up_reason = (f"fleet page headroom {headroom:.2f} < "
                     f"{cfg.page_headroom}")
    down_ok = (mean_q < cfg.queue_low and not shedding
               and not burning)
    # hysteresis dwell: a signal starts its clock on the tick it first
    # appears and fires only once it has held dwell_s; the opposite
    # signal (or quiet) resets it
    st["up_since"] = (st["up_since"] if up_reason is not None
                      and st["up_since"] is not None
                      else (now if up_reason is not None else None))
    st["down_since"] = (st["down_since"] if down_ok
                        and st["down_since"] is not None
                        else (now if down_ok else None))
    last = st["last_action_t"]
    if last is not None and now - last < cfg.cooldown_s:
        return "hold", "cooldown", st
    if (up_reason is not None and n < cfg.max_replicas
            and now - st["up_since"] >= cfg.dwell_s):
        st["last_action_t"] = now
        st["up_since"] = None
        return "up", up_reason, st
    if (down_ok and n > cfg.min_replicas
            and now - st["down_since"] >= cfg.dwell_s):
        st["last_action_t"] = now
        st["down_since"] = None
        return "down", (f"mean queued/replica {mean_q:.2f} < "
                        f"queue_low {cfg.queue_low}"), st
    if up_reason is not None and n >= cfg.max_replicas:
        return "hold", f"at max_replicas ({cfg.max_replicas})", st
    return "hold", "no signal held long enough", st


class Autoscaler:
    """The stateful wrapper the router drives once per step: threads
    `decide`'s state, and writes one frozen-schema
    ``autoscale_decision`` jsonl record per ACTION — {event, action,
    reason, live, queued, t} — so a drill replays the exact decision
    stream (holds stay silent by design)."""

    def __init__(self, cfg: AutoscaleConfig | None = None, *,
                 logger=None):
        self.cfg = cfg if cfg is not None else AutoscaleConfig()
        self.logger = logger
        self.state = _fresh_state()
        self.decisions: list[dict] = []

    def evaluate(self, healths, *, now: float) -> dict | None:
        """One tick: returns ``{"action", "reason", "live", "queued",
        "t"}`` for an up/down decision, None on hold."""
        action, reason, self.state = decide(
            healths, now=now, state=self.state, cfg=self.cfg)
        if action == "hold":
            return None
        live = [h for h in healths
                if h["state"] == "live" and h["role"] != "prefill"]
        rec = {"action": action, "reason": reason,
               "live": len(live),
               "queued": sum(h["queue_depth"] + h["load"]
                             for h in live),
               "t": round(now, 4)}
        self.decisions.append(rec)
        if self.logger is not None:
            self.logger.log(event="autoscale_decision", **rec)
        return rec

    def state_doc(self) -> dict:
        """The autoscaler block the fleet /healthz embeds (ISSUE 20):
        the policy bounds and dwell/cooldown knobs plus the LIVE
        hysteresis clocks — an operator reading the document can tell
        "quiet" from "a scale signal is dwelling right now" from
        "cooling down after an action"."""
        return {
            "min_replicas": self.cfg.min_replicas,
            "max_replicas": self.cfg.max_replicas,
            "queue_high": self.cfg.queue_high,
            "queue_low": self.cfg.queue_low,
            "dwell_s": self.cfg.dwell_s,
            "cooldown_s": self.cfg.cooldown_s,
            "up_since": self.state["up_since"],
            "down_since": self.state["down_since"],
            "last_action_t": self.state["last_action_t"],
            "decisions": len(self.decisions),
        }
