"""Cross-replica prefix registry: chunk-boundary KV snapshots shared
by every replica in a cluster.

Each replica's radix `PrefixCache` (serve/prefix_cache.py) is local to
one engine; under a router fanning one workload across N replicas, a
hot system prompt would be prefilled once PER REPLICA. The registry is
the cluster-wide tier above those caches: a host-memory store of
chunk-boundary snapshots keyed by the token prefix, shared by every
replica in the process.

What is stored is the PACKED host-numpy form of the array snapshot —
the caches truncated to the prefix length, exactly what
`PrefixCache.set_packer` stores locally — because that form is
device-agnostic: any replica's `_unpack` pads it back to `t_max` and
re-places it under its OWN mesh sharding, so one published snapshot
serves engines on different devices. This is also the prefill→decode
HANDOFF artifact: a dedicated prefill replica drives chunks to the
last boundary, each completed boundary publishes here, and the decode
replica's admission adopts the prefix without re-running a single
chunk (serve/cluster/router.py; gated bit-identical by test).

The PAGED flavor deliberately does not publish: a `PagedPrefixCache`
snapshot is a list of physical page ids in ONE engine's pool —
meaningless to any other replica. Paged replicas keep their local
zero-copy sharing; cross-replica reuse is the array flavor's job.

Thread-safety: replicas in this process are stepped by one router
loop, so access is single-threaded by construction (like every other
serve-side host structure); the registry holds no locks.
"""

from __future__ import annotations

import numpy as np

from idc_models_tpu.observe import metrics_registry as mreg


def _host_copy(tree):
    import jax

    return jax.tree.map(lambda a: np.array(a, copy=True), tree)


def _tree_bytes(tree) -> int:
    import jax

    return sum(int(a.nbytes) for a in jax.tree.leaves(tree))


class _Node:
    __slots__ = ("children", "snapshot", "nbytes", "stamp", "parent",
                 "edge", "hit_count")

    def __init__(self, parent=None, edge=None):
        self.children: dict[tuple, _Node] = {}
        self.snapshot = None
        self.nbytes = 0
        self.stamp = 0
        self.parent = parent
        self.edge = edge
        self.hit_count = 0


class PrefixRegistry:
    """Radix store of published chunk-boundary snapshots under a byte
    budget, LRU-evicted (never-hit snapshots first, like the local
    caches — a burst of unique tails churns its own entries, not the
    shared system prompts the registry exists for).

    `chunk` must equal every attached cache's chunk — snapshots live
    on one grid. `max_bytes` bounds the summed host bytes of stored
    snapshots."""

    def __init__(self, chunk: int, max_bytes: int, *, logger=None,
                 registry=None):
        if chunk < 1:
            raise ValueError(f"need chunk >= 1, got {chunk}")
        if max_bytes < 0:
            raise ValueError(f"need max_bytes >= 0, got {max_bytes}")
        self.chunk = int(chunk)
        self.max_bytes = int(max_bytes)
        self.logger = logger
        reg = registry if registry is not None else mreg.REGISTRY
        self._m_lookups = reg.counter(
            "cluster_prefix_lookups_total",
            "cross-replica prefix-registry lookups by outcome",
            labels=("result",))
        self._m_published = reg.counter(
            "cluster_prefix_published_total",
            "chunk-boundary snapshots published into the cross-replica "
            "prefix registry")
        self._m_bytes = reg.gauge(
            "cluster_prefix_registry_bytes",
            "host bytes of snapshots held by the cross-replica prefix "
            "registry")
        self._root = _Node()
        self._clock = 0
        self.nbytes = 0
        self.n_snapshots = 0
        self.publishes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- the chunk grid ---------------------------------------------------

    def _chunks(self, tokens) -> list[tuple]:
        toks = np.asarray(tokens).reshape(-1).tolist()
        n_full = len(toks) // self.chunk
        return [tuple(toks[i * self.chunk:(i + 1) * self.chunk])
                for i in range(n_full)]

    # -- publish / lookup -------------------------------------------------

    def publish(self, tokens, caches, logits) -> bool:
        """Store the snapshot for `tokens` (length on the chunk grid)
        as host-numpy deep copies. Returns False (nothing stored) when
        the key already exists (dedupe — the first publisher's copy
        keeps answering; boundary snapshots for the same tokens are
        identical by the chunk program's determinism) or the snapshot
        alone exceeds the whole budget."""
        toks = np.asarray(tokens).reshape(-1)
        if toks.size == 0 or toks.size % self.chunk:
            raise ValueError(
                f"prefix length {toks.size} is not a multiple of the "
                f"chunk {self.chunk} — snapshots live on chunk "
                f"boundaries only")
        node = self._root
        for edge in self._chunks(toks):
            node = node.children.setdefault(edge, _Node(node, edge))
        self._clock += 1
        node.stamp = self._clock
        if node.snapshot is not None:
            return False
        snap = (_host_copy(caches), np.array(logits, copy=True))
        size = _tree_bytes(snap[0]) + int(snap[1].nbytes)
        if size > self.max_bytes:
            self._prune(node)
            return False
        node.snapshot = snap
        node.nbytes = size
        self.nbytes += size
        self.n_snapshots += 1
        self.publishes += 1
        self._m_published.inc()
        while self.nbytes > self.max_bytes and self.n_snapshots > 1:
            self._evict_lru(protect=node)
        self._m_bytes.set(self.nbytes)
        self._log(event="cluster_prefix_publish",
                  prefix_tokens=int(toks.size), nbytes=size)
        return True

    def lookup(self, tokens):
        """Longest published prefix of `tokens` on the chunk grid:
        ``(start, packed_caches, logits)`` — fresh numpy copies, or
        (0, None, None) on a miss."""
        node = self._root
        best, best_depth, depth = None, 0, 0
        for edge in self._chunks(tokens):
            node = node.children.get(edge)
            if node is None:
                break
            depth += 1
            if node.snapshot is not None:
                best, best_depth = node, depth
        if best is None:
            self.misses += 1
            self._m_lookups.inc(result="miss")
            return 0, None, None
        self._clock += 1
        best.stamp = self._clock
        best.hit_count += 1
        self.hits += 1
        self._m_lookups.inc(result="hit")
        caches, logits = best.snapshot
        return (best_depth * self.chunk, _host_copy(caches),
                np.array(logits, copy=True))

    def covered(self, tokens) -> int:
        """Chunk-grid tokens of `tokens` the registry already holds —
        the router's handoff short-circuit (a hot prompt need not be
        prefilled again anywhere). Pure read: no hit/LRU bookkeeping."""
        node, depth, best = self._root, 0, 0
        for edge in self._chunks(tokens):
            node = node.children.get(edge)
            if node is None:
                break
            depth += 1
            if node.snapshot is not None:
                best = depth
        return best * self.chunk

    # -- eviction ---------------------------------------------------------

    def _walk(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.snapshot is not None:
                yield n

    def _evict_lru(self, protect=None) -> None:
        victims = [n for n in self._walk() if n is not protect]
        if not victims:
            return
        v = min(victims, key=lambda n: (min(n.hit_count, 1), n.stamp))
        self.nbytes -= v.nbytes
        v.snapshot, v.nbytes = None, 0
        self.n_snapshots -= 1
        self.evictions += 1
        self._m_bytes.set(self.nbytes)
        self._prune(v)

    def _prune(self, node) -> None:
        while (node is not self._root and node.snapshot is None
               and not node.children and node.parent is not None):
            del node.parent.children[node.edge]
            node = node.parent

    # -- observability ----------------------------------------------------

    def summary(self) -> dict:
        return {
            "cluster_prefix_published": self.publishes,
            "cluster_prefix_hits": self.hits,
            "cluster_prefix_misses": self.misses,
            "cluster_prefix_evictions": self.evictions,
            "cluster_prefix_snapshots": self.n_snapshots,
            "cluster_prefix_bytes": self.nbytes,
        }

    def _log(self, **record) -> None:
        if self.logger is not None:
            self.logger.log(**record)
