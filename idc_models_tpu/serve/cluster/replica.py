"""One serving replica under the cluster router: an `LMServer` plus
the identity, role, lifecycle state, and health surface the router
places on.

A replica owns its OWN metrics registry (so N replicas' serve_* gauges
never stomp each other — each can serve an honest per-replica
`/healthz` through `observe.MetricsExporter`), its own journal WAL
(the failover artifact: a killed replica's unfinished requests are
migrated from its journal onto survivors), optionally its own brownout
controller (the DRAIN mechanism: draining pushes it to the shed
stage), and — `role="prefill"` — the `prefill_only` entry point that
drives chunked prefill to the last chunk boundary and publishes the
boundary snapshots into the cluster prefix registry WITHOUT ever
decoding (the disaggregation handoff; serve/cluster/registry.py).

Lifecycle: ``live`` (placeable) -> ``draining`` (unplaceable, finishes
its in-flight work) -> gone, or ``live`` -> ``dead`` (killed/failed —
the router migrates its journaled work). State only ever moves
forward; a drained replica that should serve again is rebuilt.
"""

from __future__ import annotations

import time

import numpy as np

ROLES = ("mixed", "prefill", "decode")


class Replica:
    """Identity + lifecycle around one `LMServer`. The router is the
    only submitter; `state` gates placement, the server's own
    brownout/backpressure gate admission below that."""

    def __init__(self, replica_id: str, server, *, role: str = "mixed",
                 journal_path=None, registry=None,
                 clock=time.monotonic):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got "
                             f"{role!r}")
        self.replica_id = str(replica_id)
        self.server = server
        self.role = role
        self.journal_path = journal_path
        # the replica's own MetricsRegistry (None = the process one):
        # kept so a caller can arm a per-replica MetricsExporter over it
        self.registry = registry
        self.clock = clock
        self.state = "live"
        self._last_step: float | None = None

    # -- the serving surface the router drives ---------------------------

    def submit(self, request, *, parent_span=None) -> bool:
        if self.state != "live":
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state} — the "
                f"router must not place on it")
        return self.server.submit(request, parent_span=parent_span)

    def step(self):
        """One scheduler tick; stamps the host-side freshness the
        health document reports. Engine failures propagate — the
        router's step loop converts them into a replica death +
        journal migration. While DRAINING, the brownout stays pinned
        at shed (the per-cycle evaluation would otherwise restore it
        once the queue looks clear — a drain is an operator decision,
        not a burn signal to hysteresis away)."""
        self._last_step = self.clock()
        b = self.server.brownout
        if self.state == "draining" and b is not None and b.stage < 3:
            b.force_stage(3, reason="drain")
        return self.server.step()

    def poll(self, rid):
        return self.server.poll(rid)

    def idle(self) -> bool:
        return self.server.scheduler.idle()

    def load(self) -> int:
        return self.server.scheduler.load()

    # -- placement signals ------------------------------------------------

    def placeable(self) -> bool:
        """True when the router may place NEW work here: live, decode-
        capable role, not shedding, queue below its backpressure bound.
        (Page headroom is per-request — `can_take`.)"""
        if self.state != "live" or self.role == "prefill":
            return False
        b = self.server.brownout
        if b is not None and b.shedding:
            return False
        sch = self.server.scheduler
        return len(sch.queue) < sch.queue.max_depth

    def can_take(self, p_len: int, budget: int) -> bool:
        """`placeable` plus the paged engine's page-headroom gate for
        this specific request (reclaims LRU prefix snapshots exactly
        like local admission would — a True here means admission will
        succeed)."""
        return (self.placeable()
                and self.server.engine.can_admit_pages(p_len, budget))

    def health(self) -> dict:
        """The placement-signal document — the in-process twin of the
        /healthz endpoint (observe/exporter.py), read straight off the
        live objects: queue depth, load, slot/page headroom, brownout
        stage, SLO burn, and host-loop freshness."""
        s = self.server
        eng = s.engine
        sch = s.scheduler
        slo = s.metrics.slo
        pages = eng.page_stats() if eng.paged else None
        b = s.brownout
        return {
            "replica": self.replica_id,
            "role": self.role,
            "state": self.state,
            "status": "ok" if self.state == "live" else self.state,
            "queue_depth": len(sch.queue),
            "load": sch.load(),
            "free_slots": len(eng.free_slots()),
            "slot_occupancy": eng.occupancy(),
            "kv_pages_total": (None if pages is None
                               else pages["pages_total"]),
            "kv_pages_used": (None if pages is None
                              else pages["pages_used"]),
            "brownout_stage": 0 if b is None else b.stage,
            "shedding": bool(b is not None and b.shedding),
            "slo_breached": (bool(slo.breached())
                             if slo is not None else False),
            "last_tick_age_s": (
                None if self._last_step is None
                else round(self.clock() - self._last_step, 4)),
        }

    # -- disaggregated prefill --------------------------------------------

    def prefill_only(self, prompt) -> int:
        """Drive chunked prefill for `prompt` to completion WITHOUT
        decoding: every completed chunk boundary snapshots into this
        replica's prefix cache — and, when the cache is wired to the
        cluster `PrefixRegistry`, publishes there — then the slot is
        released untouched by any window. Returns the boundary length
        now covered. This is the prefill half of the disaggregation
        handoff: the decode replica's admission adopts the published
        prefix and never runs these chunks itself.

        Consults the local cache/registry first (via the engine's
        normal `start_prefill` lookup), so a prompt already published
        costs only its uncached suffix."""
        eng = self.server.engine
        if self.state != "live":
            raise RuntimeError(
                f"replica {self.replica_id} is {self.state}")
        if eng.prefill_chunk is None:
            raise RuntimeError(
                "prefill_only needs an engine built with prefill_chunk "
                "— boundary snapshots are the handoff artifact")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size + 1 > eng.t_max:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room inside "
                f"t_max {eng.t_max}")
        free = eng.free_slots()
        if not free:
            raise RuntimeError(
                f"prefill replica {self.replica_id} has no free slot")
        slot = free[0]
        # budget 1 is a placeholder: the final prefill_step inserts the
        # request into the batch row, and the release right after
        # vacates it before any window could decode from it
        eng.start_prefill(slot, prompt, 1)
        try:
            while not eng.prefill_step(slot):
                pass
        except Exception:
            # drop the partial reservation so the slot (and, paged, its
            # page grant) is not leaked, then let the router's handoff
            # failure path decide the replica's fate
            if slot in eng.prefilling():
                eng.cancel_prefill(slot)
            raise
        eng.release(slot)
        return (prompt.size // eng.prefill_chunk) * eng.prefill_chunk

    # -- lifecycle --------------------------------------------------------

    def drain(self) -> None:
        """Begin a graceful drain: the router stops placing here
        (state gates `placeable`), and the brownout controller — when
        armed — jumps to its shed stage so any straggling direct
        submit is refused with the honest ``shed`` status. In-flight
        and queued work keeps stepping to completion; once `idle()`
        the replica can be dropped from the fleet."""
        if self.state == "dead":
            raise RuntimeError(
                f"replica {self.replica_id} is dead — drain is for "
                f"live replicas (failover migrates dead ones)")
        self.state = "draining"
        # the scheduler's own drain mode sheds stragglers even on
        # replicas built WITHOUT a brownout controller, and stays
        # sticky (brownout hysteresis cannot un-drain it)
        self.server.scheduler.begin_drain()
        if self.server.brownout is not None:
            self.server.brownout.force_stage(3, reason="drain")

    def kill(self) -> None:
        """Simulate (or acknowledge) a hard replica death: the state
        flips to ``dead``, the admission surface closes, and the
        journal is flushed shut — the WAL on disk is all that survives,
        which is exactly what the router's failover replays onto the
        survivors. Idempotent."""
        if self.state == "dead":
            return
        self.state = "dead"
        self.server.close()


def build_replica(params, *, replica_id: str, embed_dim: int,
                  num_heads: int, num_blocks: int, t_max: int,
                  device=None, role: str = "mixed", n_slots: int = 4,
                  window: int = 8, prefill_chunk: int | None = None,
                  prefix_cache_mb: float = 0.0, shared_prefix=None,
                  journal_path=None, retry=None,
                  brownout_queue_high: int | None = None,
                  brownout_dwell_s: float = 0.25,
                  brownout_clear_s: float = 1.0,
                  brownout_clamp_tokens: int = 8, slo=None,
                  logger=None, clock=time.monotonic,
                  **server_kw) -> Replica:
    """Construct one cluster replica: its own single-device mesh slice
    (`device`, carved off the fleet's device list — None uses the
    default device), its OWN `MetricsRegistry`, its local prefix cache
    (wired to the cluster `shared_prefix` registry when given), its
    journal WAL, and — when `brownout_queue_high` is set — its own
    brownout controller (the drain mechanism doubles as organic
    overload protection). Everything else passes through to
    `LMServer`."""
    from idc_models_tpu import mesh as meshlib
    from idc_models_tpu.observe.metrics_registry import MetricsRegistry
    from idc_models_tpu.serve.api import LMServer
    from idc_models_tpu.serve.brownout import BrownoutController
    from idc_models_tpu.serve.prefix_cache import PrefixCache

    mesh = (None if device is None
            else meshlib.make_mesh({meshlib.SEQ_AXIS: 1},
                                   devices=[device]))
    reg = MetricsRegistry()
    prefix_cache = None
    paged = server_kw.get("kv_page_size") is not None
    if paged and shared_prefix is not None:
        raise ValueError(
            "paged replicas cannot join the cluster prefix registry: "
            "their snapshots are physical page ids of one engine's "
            "pool (they keep local zero-copy sharing instead)")
    if prefix_cache_mb and prefix_cache_mb > 0:
        if prefill_chunk is None:
            raise ValueError("prefix_cache_mb needs prefill_chunk")
        if paged:
            # let LMServer build the matching PagedPrefixCache (it
            # binds the engine's allocator at construction)
            server_kw["prefix_cache_mb"] = prefix_cache_mb
        else:
            prefix_cache = PrefixCache(
                prefill_chunk, int(prefix_cache_mb * 1024 * 1024),
                logger=logger, registry=reg, shared=shared_prefix)
    elif shared_prefix is not None:
        raise ValueError(
            "a shared prefix registry needs a local prefix cache "
            "(prefix_cache_mb > 0) to adopt into and publish from")
    brownout = None
    if brownout_queue_high is not None:
        brownout = BrownoutController(
            slo=slo, queue_high=brownout_queue_high,
            clamp_tokens=brownout_clamp_tokens,
            escalate_dwell_s=brownout_dwell_s,
            clear_after_s=brownout_clear_s, logger=logger,
            registry=reg, clock=clock)
    server = LMServer(
        params, embed_dim=embed_dim, num_heads=num_heads,
        num_blocks=num_blocks, t_max=t_max, n_slots=n_slots,
        window=window, mesh=mesh, prefill_chunk=prefill_chunk,
        prefix_cache=prefix_cache, journal=journal_path, retry=retry,
        brownout=brownout, slo=slo, logger=logger, clock=clock,
        registry=reg, **server_kw)
    return Replica(replica_id, server, role=role,
                   journal_path=journal_path, registry=reg,
                   clock=clock)
