from idc_models_tpu.serve.cluster.autoscaler import (  # noqa: F401
    AutoscaleConfig, Autoscaler,
)
from idc_models_tpu.serve.cluster.registry import (  # noqa: F401
    PrefixRegistry,
)
from idc_models_tpu.serve.cluster.replica import (  # noqa: F401
    Replica, build_replica,
)
from idc_models_tpu.serve.cluster.router import Router  # noqa: F401
from idc_models_tpu.serve.cluster.telemetry import (  # noqa: F401
    ClusterTelemetry, ClusterWatchdog, WatchdogConfig,
)
