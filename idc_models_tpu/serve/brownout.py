"""SLO-driven brownout: staged load shedding with hysteresis.

PR 7 built the signal (`SLOEngine.breached()` — burn-rate alerting over
sliding windows); this module closes the loop from OBSERVING SLO burn
to ACTING on it. When the configured signal fires — a declared SLO in
breach, or the admission queue above a depth watermark — the controller
escalates through explicit ordered degradation stages, one per
evaluation once `escalate_dwell_s` has passed since the last change,
and steps back down only after the signal has been CLEAR for
`clear_after_s` (hysteresis — a flapping burn cannot oscillate the
server between stages every cycle):

    stage 0  normal              everything on
    stage 1  pause_cache_writes  prefix-cache inserts stop (lookups
                                 still serve hits): snapshot copies +
                                 eviction churn are the first work a
                                 degrading server sheds
    stage 2  clamp_tokens        admissions clamp max_new_tokens to
                                 `clamp_tokens` — shorter answers for
                                 everyone beats no answers for some
    stage 3  shed                new submits are refused with a ``shed``
                                 status (an explicit, honest rejection
                                 the client can retry elsewhere — the
                                 SRE alternative to unbounded queueing)

Every transition is a `serve.brownout` trace point, a jsonl
``serve_brownout`` record, and the ``serve_brownout_stage`` gauge — an
operator can reconstruct exactly when and why the server degraded and
recovered. The scheduler consults `shedding` / `token_clamp` per
submit/admission and calls `evaluate()` once per cycle.
"""

from __future__ import annotations

import time

from idc_models_tpu.observe import metrics_registry as mreg
from idc_models_tpu.observe import trace

STAGES = ("normal", "pause_cache_writes", "clamp_tokens", "shed")


class BrownoutController:
    """Staged degradation driven by SLO breach and/or queue depth.

    `slo` is an `observe.slo.SLOEngine`; `slo_name` picks one declared
    objective (None = any breached objective triggers). `queue_high`
    escalates when the admission queue reaches it; `queue_low` (default
    `queue_high // 4`) is the depth the queue must fall back to before
    the clear timer starts. At least one signal must be configured.
    `clock` is injectable so tests drive the dwell/hysteresis
    arithmetic deterministically."""

    def __init__(self, *, slo=None, slo_name: str | None = None,
                 queue_high: int | None = None,
                 queue_low: int | None = None, clamp_tokens: int = 8,
                 escalate_dwell_s: float = 0.25,
                 clear_after_s: float = 1.0, prefix_cache=None,
                 logger=None, registry=None, clock=time.monotonic,
                 tenant: str | None = None):
        if slo is None and queue_high is None:
            raise ValueError(
                "brownout needs at least one signal: an SLOEngine "
                "(slo=) or a queue-depth watermark (queue_high=)")
        if queue_high is not None and queue_high < 1:
            raise ValueError(f"need queue_high >= 1, got {queue_high}")
        if clamp_tokens < 1:
            raise ValueError(f"need clamp_tokens >= 1, got "
                             f"{clamp_tokens}")
        if escalate_dwell_s < 0 or clear_after_s < 0:
            raise ValueError("dwell/clear times must be >= 0")
        self.slo = slo
        self.slo_name = slo_name
        self.queue_high = queue_high
        self.queue_low = (queue_low if queue_low is not None
                          else (max(queue_high // 4, 0)
                                if queue_high is not None else None))
        if (self.queue_high is not None
                and self.queue_low >= self.queue_high):
            raise ValueError(
                f"need queue_low < queue_high, got {self.queue_low} / "
                f"{self.queue_high}")
        self.clamp_tokens = int(clamp_tokens)
        self.escalate_dwell_s = float(escalate_dwell_s)
        self.clear_after_s = float(clear_after_s)
        self.prefix_cache = prefix_cache
        self.logger = logger
        self.clock = clock
        # tenant: this controller degrades ONE tenant's admissions
        # (serve/tenancy.py), not the whole server — its gauge is the
        # tenant-labeled twin and its jsonl event a NEW type, so the
        # historical unlabeled serve_brownout surfaces stay
        # byte-identical. A per-tenant controller must not hold the
        # (shared, cross-tenant) prefix cache: stage 1's cache-write
        # pause is a global-resource action that stays with the
        # server-wide controller.
        self.tenant = tenant
        if tenant is not None and prefix_cache is not None:
            raise ValueError(
                "a per-tenant brownout cannot pause the SHARED prefix "
                "cache (that would degrade every tenant for one "
                "tenant's burn) — leave prefix_cache on the server-"
                "wide controller")
        reg = registry if registry is not None else mreg.REGISTRY
        if tenant is None:
            self._g_stage = reg.gauge(
                "serve_brownout_stage",
                "current brownout degradation stage (0 normal, 1 "
                "prefix-cache writes paused, 2 max_new_tokens clamped,"
                " 3 shedding new submits)")
            self._g_stage.set(0)
        else:
            self._g_stage = reg.gauge(
                "serve_tenant_brownout_stage",
                "current per-tenant brownout degradation stage (0 "
                "normal .. 3 shedding that tenant's submits) — one "
                "tenant's flood degrades only its own admissions",
                labels=("tenant",))
            self._g_stage.set(0, tenant=tenant)
        self.stage = 0
        self.max_stage_seen = 0
        self.transitions: list[dict] = []
        self._last_change = float("-inf")
        self._clear_since: float | None = None

    # -- the per-cycle evaluation -----------------------------------------

    def _burning(self) -> list[str]:
        """The reasons the degradation signal is firing right now
        (empty = not firing). Queue depth is read from the caller —
        the controller holds no reference to the queue."""
        reasons = []
        if self.slo is not None and self.slo.breached(self.slo_name):
            reasons.append(f"slo:{self.slo_name or 'any'}")
        return reasons

    def evaluate(self, *, queue_depth: int = 0,
                 pressure: bool = False) -> int:
        """One evaluation (the scheduler calls this once per cycle):
        escalate while the signal fires, start/extend the clear timer
        while it is fully clear, and step one stage back down per
        sustained `clear_after_s`. Returns the current stage.
        `pressure` is an extra caller-owned escalation signal — the
        paged engine's page-exhaustion backpressure (ISSUE 11): a pool
        running dry pauses cache writes (frees snapshot pages), then
        clamps budgets (smaller reservations), then sheds — each stage
        directly reduces page demand."""
        now = self.clock()
        reasons = self._burning()
        if pressure:
            reasons.append("pages")
        if (self.queue_high is not None
                and queue_depth >= self.queue_high):
            reasons.append(f"queue:{queue_depth}")
        if reasons:
            self._clear_since = None
            if (self.stage < len(STAGES) - 1
                    and now - self._last_change >= self.escalate_dwell_s):
                self._transition(self.stage + 1, now,
                                 "+".join(reasons))
            return self.stage
        # the CLEAR condition is stricter than "not firing": the queue
        # must fall below the low watermark too, so the controller does
        # not restore straight into the load that tripped it
        clear = (self.queue_low is None
                 or queue_depth <= self.queue_low)
        if not clear or self.stage == 0:
            self._clear_since = None
            return self.stage
        if self._clear_since is None:
            self._clear_since = now
        if now - self._clear_since >= self.clear_after_s:
            self._transition(self.stage - 1, now, "recovered")
            # one stage per sustained clear period — restoring
            # everything at once would slam the restored load back on
            self._clear_since = now
        return self.stage

    def _transition(self, stage: int, now: float, reason: str) -> None:
        direction = "escalate" if stage > self.stage else "restore"
        self.stage = stage
        self.max_stage_seen = max(self.max_stage_seen, stage)
        self._last_change = now
        rec = {"stage": stage, "stage_name": STAGES[stage],
               "direction": direction, "reason": reason}
        if self.tenant is None:
            self._g_stage.set(stage)
            trace.point("serve.brownout", **rec)
            event = "serve_brownout"
        else:
            # the tenant-labeled twin surfaces: a NEW jsonl event type
            # (frozen from day one in test_observability) so the
            # historical serve_brownout record stays byte-identical
            self._g_stage.set(stage, tenant=self.tenant)
            rec["tenant"] = self.tenant
            trace.point("serve.tenant_brownout", **rec)
            event = "serve_tenant_brownout"
        if self.prefix_cache is not None:
            self.prefix_cache.pause_writes(stage >= 1)
        self.transitions.append(rec)
        if self.logger is not None:
            self.logger.log(event=event, **rec)

    def force_stage(self, stage: int, *, reason: str = "drain") -> int:
        """Jump straight to `stage`, bypassing the dwell timer — the
        DRAIN entry point (serve/cluster): a replica being drained is
        pushed to the shed stage so new submits are refused with the
        honest ``shed`` status while its in-flight work completes, and
        a drain that is cancelled steps back down through the normal
        hysteresis. The jump is recorded like any other transition
        (trace point, jsonl record, gauge), so the drain is visible in
        the same timeline as organic brownouts."""
        if not 0 <= stage < len(STAGES):
            raise ValueError(f"stage must be in [0, {len(STAGES) - 1}], "
                             f"got {stage}")
        if stage != self.stage:
            self._transition(stage, self.clock(), reason)
            self._clear_since = None
        return self.stage

    # -- the knobs the scheduler consults ---------------------------------

    @property
    def shedding(self) -> bool:
        """True while new submits should be refused with status
        ``shed``."""
        return self.stage >= 3

    @property
    def token_clamp(self) -> int | None:
        """The max_new_tokens bound admissions should apply right now
        (None = no clamp)."""
        return self.clamp_tokens if self.stage >= 2 else None
