"""Serving observability: TTFT, per-token latency, throughput, queue
depth, slot occupancy.

Counters accumulate in memory and stream — when a logger is given —
through the same `observe.JsonlLogger` jsonl record shape every other
loop in the framework writes, so a serving run's timeline sits next to
its training runs' in one machine-comparable format. `summary()` is the
record `bench.py` embeds in the official JSON line (`serve_*` fields).
"""

from __future__ import annotations

import time

import numpy as np

from idc_models_tpu.observe import metrics_registry as mreg


def _pct(values, q) -> float | None:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingMetrics:
    """Per-request and per-cycle serving counters.

    Hooks are called by the scheduler: `on_submit`/`on_reject` at the
    queue, `on_first_token` when a request's first decode window lands
    (TTFT), `on_finish` with the whole request's timing, and `on_cycle`
    once per engine cycle with queue depth / slot occupancy / tokens
    emitted. All times are seconds on the caller's clock.

    Every hook ALSO updates the process-wide metrics registry
    (observe/metrics_registry.py: serve_* counters/gauges/histograms)
    — additive instrumentation only; the jsonl records this class has
    always written keep their exact keys (gated by test).

    `slo` is an optional `observe.slo.SLOEngine`: the hooks feed it the
    declared subset of ``ttft`` / ``queue_wait`` (latency samples,
    seconds) and ``error_rate`` (bad = rejected, or a finish reason of
    error/timeout/deadline), and `on_cycle` runs one burn-rate
    evaluation per scheduler cycle.
    """

    def __init__(self, logger=None, prefix_cache=None, registry=None,
                 slo=None, tenancy=None):
        self.logger = logger
        self.slo = slo
        # tenancy (serve/tenancy.py, ISSUE 14): when armed, the hooks
        # also maintain tenant-labeled series (every registration
        # carries the tenant label — enforced by the static scan), a
        # per-tenant rollup under summary()["serve_tenants"], and the
        # NEW serve_tenant_* jsonl events (frozen from day one; every
        # historical event schema stays byte-identical). TTFT samples
        # feed the tenant's own ttft:<name> SLO objective.
        self.tenancy = tenancy
        # when a PrefixCache is attached its serve_prefix_* counters
        # roll into summary() next to the serving fields
        self.prefix_cache = prefix_cache
        reg = registry if registry is not None else mreg.REGISTRY
        # submissions and terminal outcomes are SEPARATE counters: a
        # single status-labeled counter would count every completed
        # request twice (once as "submitted", once at finish), doubling
        # any sum(rate(...)) a Prometheus consumer runs over the labels
        self._m_submitted = reg.counter(
            "serve_requests_submitted_total", "requests submitted")
        self._m_requests = reg.counter(
            "serve_requests_total",
            "requests by terminal outcome", labels=("status",))
        self._m_tokens = reg.counter(
            "serve_tokens_emitted_total", "decode tokens emitted")
        self._m_ttft = reg.histogram(
            "serve_ttft_seconds", "submit -> first token")
        # inter-token latency (ISSUE 20): the request's mean seconds
        # per decoded token after the first — TTFT covers the prefill
        # side of the latency SLO, this histogram covers the decode
        # side (its p95 is what the fleet view alerts on)
        self._m_itl = reg.histogram(
            "serve_itl_seconds",
            "per-request mean inter-token latency (decode seconds "
            "per token after the first)")
        self._m_queue = reg.gauge(
            "serve_queue_depth", "admission queue depth (last cycle)")
        self._m_occ = reg.gauge(
            "serve_slot_occupancy",
            "fraction of decode slots running (last cycle)")
        self._m_compiles = reg.counter(
            "serve_compiles_total",
            "XLA compiles observed as jit cache-size growth after the "
            "first cycle")
        # the /healthz freshness anchor (observe/exporter.py): stamped
        # with time.monotonic() once per scheduler cycle so a scrape
        # can tell a healthy-but-idle server from a wedged one
        self._m_last_tick = reg.gauge(
            "serve_last_tick_monotonic_seconds",
            "time.monotonic() stamp of the last scheduler cycle — "
            "/healthz reports now minus this as last_tick_age_s")
        # resilience instruments (ISSUE 8): quarantines, retries, shed
        # submits, brownout clamps, and injected drill faults
        self._m_slot_faults = reg.counter(
            "serve_slot_faults_total",
            "slots quarantined by the per-cycle health checks, by "
            "fault kind", labels=("kind",))
        self._m_retries = reg.counter(
            "serve_retries_total",
            "quarantined requests re-admitted after backoff")
        self._m_shed = reg.counter(
            "serve_shed_total",
            "submits refused by the brownout controller's shed stage")
        self._m_clamped = reg.counter(
            "serve_clamped_total",
            "admissions whose max_new_tokens the brownout clamp "
            "shortened")
        self._m_faults_injected = reg.counter(
            "serve_faults_injected_total",
            "declarative serve faults fired by an armed ServeFaultPlan,"
            " by kind", labels=("kind",))
        # speculative decoding (ISSUE 10): decode dispatches by kind
        # (window vs verify) and the drafted/accepted token ledger —
        # acceptance rate and tokens-per-dispatch derive from these
        self._m_dispatches = reg.counter(
            "serve_decode_dispatches_total",
            "decode dispatches by kind: 'window' (fused one-token-per-"
            "step scan) or 'verify' (speculative draft-and-verify)",
            labels=("kind",))
        self._m_spec_drafted = reg.counter(
            "serve_spec_drafted_tokens_total",
            "draft tokens submitted to speculative verify dispatches")
        self._m_spec_accepted = reg.counter(
            "serve_spec_accepted_tokens_total",
            "draft tokens the verify accepted (emitted as-is)")
        # paged KV (ISSUE 11): pool occupancy gauges — the live
        # tokens-resident-per-HBM-byte capacity signals — plus the
        # page-exhaustion backpressure counter
        self._m_pages_used = reg.gauge(
            "serve_kv_pages_used",
            "KV pool pages currently allocated (slots + prefix-cache "
            "snapshots), last cycle")
        self._m_pages_total = reg.gauge(
            "serve_kv_pages_total",
            "total KV pool pages the paged engine was built with")
        self._m_pages_cached = reg.gauge(
            "serve_kv_pages_cached",
            "distinct KV pool pages pinned by prefix-cache snapshots "
            "(a subset of serve_kv_pages_used; shared zero-copy with "
            "the slots that wrote them), last cycle")
        self._m_page_exhausted = reg.counter(
            "serve_page_exhaustions_total",
            "cycles the paged engine refused work for lack of free "
            "pages (admission gate or mid-decode growth)")
        # hot weight rollout (ROADMAP 4): terminal outcomes plus the
        # live stage gauge an operator watches during a canary
        self._m_rollouts = reg.counter(
            "serve_rollouts_total",
            "weight rollouts by terminal outcome: 'promoted' (canary "
            "healthy, live weights swapped) or 'rolled_back' (staging "
            "spot-check or canary SLO comparison failed)",
            labels=("outcome",))
        self._m_rollout_stage = reg.gauge(
            "serve_rollout_stage_code",
            "current rollout stage: 0 idle, 1 staging, 2 canary, "
            "3 promoted, 4 rolled_back")
        # tenant-labeled instruments, registered only when tenancy is
        # armed so tenant-less servers' registries stay byte-identical
        # (the /metrics exposition equality gates)
        if tenancy is not None:
            self._m_t_requests = reg.counter(
                "serve_tenant_requests_total",
                "requests by tenant and terminal outcome",
                labels=("tenant", "status"))
            self._m_t_tokens = reg.counter(
                "serve_tenant_tokens_emitted_total",
                "decode tokens emitted per tenant", labels=("tenant",))
            self._m_t_ttft = reg.histogram(
                "serve_tenant_ttft_seconds",
                "submit -> first token per tenant", labels=("tenant",))
            self._m_t_queue = reg.gauge(
                "serve_tenant_queue_depth",
                "admission-queue entries each tenant holds (last "
                "cycle)", labels=("tenant",))
            self._m_t_slots = reg.gauge(
                "serve_tenant_slots_used",
                "decode slots (running + prefilling) each tenant "
                "holds (last cycle)", labels=("tenant",))
            self._m_t_pages = reg.gauge(
                "serve_tenant_kv_pages_used",
                "KV pool pages each tenant's admissions have reserved "
                "(last cycle; paged engines)", labels=("tenant",))
            self._m_t_shed = reg.counter(
                "serve_tenant_shed_total",
                "submits refused by the tenant's own brownout shed "
                "stage", labels=("tenant",))
            self._m_t_quota = reg.counter(
                "serve_tenant_quota_rejections_total",
                "submits refused by a per-tenant quota, by quota kind",
                labels=("tenant", "kind"))
        # per-tenant rollup (all keyed by tenant name; empty dicts
        # when tenancy is off)
        self.tenant_ttft_s: dict[str, list] = {}
        self.tenant_finished: dict[str, int] = {}
        self.tenant_tokens: dict[str, int] = {}
        self.tenant_shed: dict[str, int] = {}
        self.tenant_quota_rejections: dict[str, int] = {}
        self._jit_cache_seen: int | None = None
        self.compiles_observed = 0
        # compile-cache rollup (serve/compile_cache.py): gauges are
        # registered lazily by on_compile_cache, so a cache-less
        # server's /metrics exposition stays byte-identical (the
        # equality gates)
        self._reg = reg
        self.compile_cache_summary: dict | None = None
        self._g_cc: dict | None = None
        # rollout rollup: stage trail + terminal outcomes
        self.rollout_stage: str | None = None
        self.rollout_outcomes: list[str] = []
        # paged-KV rollup (all zero/None on contiguous engines)
        self.kv_pages_total: int | None = None
        self.kv_pages_used_peak = 0
        self.kv_resident_tokens_peak = 0
        self.kv_resident_bytes_peak = 0
        self.kv_tokens_per_byte_peak: float | None = None
        self.page_exhaustions = 0
        # speculative rollup: dispatch counts by kind plus the draft
        # ledger (slot_verifies = per-slot participations, the
        # denominator of the per-slot tokens-per-dispatch figure)
        self.window_dispatches = 0
        self.verify_dispatches = 0
        self.spec_drafted = 0
        self.spec_accepted = 0
        self.spec_emitted = 0
        self.spec_slot_verifies = 0
        # drafting-pass wall time (host scans + the learned drafter's
        # batched dispatch) — the draft-overhead numerator
        self.propose_s = 0.0
        self.propose_calls = 0
        self.submitted = 0
        self.rejected = 0
        self.timed_out = 0
        self.slot_faults = 0
        self.retries = 0
        self.shed = 0
        self.clamped = 0
        self.faults_injected = 0
        self.finished = 0
        self.tokens_out = 0
        self.cycles = 0
        self.ttft_s: list[float] = []
        self.queue_wait_s: list[float] = []  # submit -> slot claimed
        self.prefill_s: list[float] = []     # slot claimed -> first token
        self.token_s: list[float] = []      # per-token decode latency
        self.queue_depths: list[int] = []
        self.occupancies: list[float] = []
        self.cycle_tokens: list[int] = []
        self.cycle_prefill_s: list[float] = []  # per-cycle decode stall
        self._wait_by_rid: dict = {}
        self._t_first: float | None = None
        self._t_last: float | None = None

    # -- request lifecycle ----------------------------------------------

    def on_submit(self, rid, t: float, *, tenant=None) -> None:
        self.submitted += 1
        if self._t_first is None:
            self._t_first = t
        self._m_submitted.inc()
        self._log(event="serve_submit", id=rid)

    def on_reject(self, rid, t: float) -> None:
        self.rejected += 1
        self._m_requests.inc(status="rejected")
        if self.slo is not None and self.slo.has("error_rate"):
            self.slo.record("error_rate", ok=False)
        self._log(event="serve_reject", id=rid)

    def on_admit(self, rid, wait_s: float) -> None:
        """A request claimed a slot `wait_s` seconds after submit — the
        QUEUE-WAIT half of its eventual TTFT (the other half, from slot
        claim to first token, is prefill compute + window wait). New
        event type, new keys only: existing serve.jsonl consumers see
        an unchanged record schema for the events they already parse."""
        self.queue_wait_s.append(wait_s)
        self._wait_by_rid[rid] = wait_s
        if self.slo is not None and self.slo.has("queue_wait"):
            self.slo.observe("queue_wait", wait_s)
        self._log(event="serve_admit", id=rid, queue_wait_ms=wait_s * 1e3)

    def on_first_token(self, rid, ttft_s: float, *,
                       tenant=None) -> None:
        self._m_ttft.observe(ttft_s)
        if self.slo is not None and self.slo.has("ttft"):
            self.slo.observe("ttft", ttft_s)
        if tenant is not None and self.tenancy is not None:
            # the tenant's own ttft:<name> objective — THE per-tenant
            # admission/brownout signal (SLOEngine.breached)
            self.tenancy.observe_ttft(tenant, ttft_s)
            self._m_t_ttft.observe(ttft_s, tenant=tenant)
            self.tenant_ttft_s.setdefault(tenant, []).append(ttft_s)
        self.ttft_s.append(ttft_s)
        wait = self._wait_by_rid.pop(rid, None)
        prefill = None if wait is None else max(ttft_s - wait, 0.0)
        if prefill is not None:
            self.prefill_s.append(prefill)
        self._log(event="serve_first_token", id=rid,
                  ttft_ms=ttft_s * 1e3,
                  prefill_ms=None if prefill is None else prefill * 1e3)

    def on_finish(self, rid, *, n_tokens: int, ttft_s: float | None,
                  decode_s: float, reason: str, t: float,
                  tenant=None) -> None:
        # a request cancelled before its first token never reaches
        # on_first_token — drop its queue-wait entry here too or the
        # dict grows for the server's lifetime under deadline pressure
        self._wait_by_rid.pop(rid, None)
        self.finished += 1
        if reason in ("timeout", "deadline"):
            self.timed_out += 1
        self._m_requests.inc(status=str(reason))
        if self.slo is not None and self.slo.has("error_rate"):
            self.slo.record("error_rate", ok=reason not in (
                "error", "timeout", "deadline"))
        if n_tokens:
            self._m_tokens.inc(n_tokens)
        self.tokens_out += n_tokens
        self._t_last = t
        if n_tokens > 1 and decode_s > 0:
            itl = decode_s / (n_tokens - 1)
            self.token_s.append(itl)
            self._m_itl.observe(itl)
        self._log(event="serve_finish", id=rid, tokens=n_tokens,
                  reason=reason,
                  ttft_ms=None if ttft_s is None else ttft_s * 1e3)
        if tenant is not None and self.tenancy is not None:
            # the tenant-attributed finish is a NEW event type (frozen
            # from day one), never a reshaped serve_finish — the
            # historical schema stays byte-identical
            self.tenant_finished[tenant] = (
                self.tenant_finished.get(tenant, 0) + 1)
            self.tenant_tokens[tenant] = (
                self.tenant_tokens.get(tenant, 0) + n_tokens)
            self._m_t_requests.inc(tenant=tenant, status=str(reason))
            if n_tokens:
                self._m_t_tokens.inc(n_tokens, tenant=tenant)
            self._log(event="serve_tenant_finish", id=rid,
                      tenant=tenant, tokens=n_tokens, reason=reason,
                      ttft_ms=None if ttft_s is None else ttft_s * 1e3)

    # -- resilience ------------------------------------------------------

    def on_slot_fault(self, rid, *, kind: str, slot=None) -> None:
        """A running/prefilling slot was quarantined: `kind` is the
        detector that fired (nonfinite_logits / logit_magnitude /
        invariant / prefill_error). New event type only — the frozen
        serve.jsonl schema is untouched."""
        self.slot_faults += 1
        self._m_slot_faults.inc(kind=kind)
        self._log(event="serve_slot_fault", id=rid, kind=kind,
                  slot=slot)

    def on_retry(self, rid, *, attempt: int, delay_s: float) -> None:
        """A quarantined request was scheduled for re-admission
        `delay_s` seconds out; `attempt` is the total attempt count it
        re-enters with."""
        self.retries += 1
        self._m_retries.inc()
        self._log(event="serve_retry", id=rid, attempt=attempt,
                  delay_ms=delay_s * 1e3)

    def on_shed(self, rid, *, tenant=None) -> None:
        """A submit was refused by the brownout shed stage (the
        server-wide controller OR — `tenant` set with tenancy armed —
        that tenant's own). Counted as its own terminal outcome —
        deliberately NOT fed to the error-rate SLO: shedding is the
        controller's intended action, and scoring it as an error
        would make shedding beget more shedding."""
        self.shed += 1
        self._m_shed.inc()
        self._m_requests.inc(status="shed")
        self._log(event="serve_shed", id=rid)
        if tenant is not None and self.tenancy is not None:
            self.tenant_shed[tenant] = (
                self.tenant_shed.get(tenant, 0) + 1)
            self._m_t_shed.inc(tenant=tenant)
            self._m_t_requests.inc(tenant=tenant, status="shed")
            self._log(event="serve_tenant_shed", id=rid, tenant=tenant)

    def on_tenant_quota(self, rid, *, tenant: str, kind: str) -> None:
        """A submit was refused by a per-tenant quota (`kind` =
        "queued" today; page/slot quotas block IN the queue instead of
        refusing). Counted as a rejection for the aggregate figures
        but — like shed — never fed to the error-rate SLO: the
        refusal IS the isolation mechanism protecting the other
        tenants, not the service failing."""
        self.rejected += 1
        self._m_requests.inc(status="rejected")
        self.tenant_quota_rejections[tenant] = (
            self.tenant_quota_rejections.get(tenant, 0) + 1)
        self._m_t_quota.inc(tenant=tenant, kind=kind)
        self._m_t_requests.inc(tenant=tenant, status="rejected")
        self._log(event="serve_tenant_quota_reject", id=rid,
                  tenant=tenant, kind=kind)

    def on_tenant_cycle(self, names, *, depths: dict, slots: dict,
                        pages: dict) -> None:
        """Per-cycle tenant occupancy gauges — every registered tenant
        gets an explicit point (zero included) so a tenant that just
        drained reads 0, not its stale last value."""
        for name in names:
            self._m_t_queue.set(depths.get(name, 0), tenant=name)
            self._m_t_slots.set(slots.get(name, 0), tenant=name)
            self._m_t_pages.set(pages.get(name, 0), tenant=name)

    def on_clamp(self, rid, *, asked: int, clamp: int) -> None:
        """The brownout clamp shortened an admission's budget."""
        self.clamped += 1
        self._m_clamped.inc()
        self._log(event="serve_clamp", id=rid, max_new_tokens=clamp,
                  asked=asked)

    def on_fault_injected(self, kind: str, *, tick: int = 0) -> None:
        """A declarative drill fault fired (ServeFaultPlan)."""
        self.faults_injected += 1
        self._m_faults_injected.inc(kind=kind)
        self._log(event="serve_fault_injected", kind=kind, tick=tick)

    # -- hot weight rollout ----------------------------------------------

    def on_rollout(self, *, stage: str, outcome: str | None = None,
                   canary_requests: int = 0,
                   reason: str | None = None) -> None:
        """One rollout state-machine transition (checkpoint/rollout.py
        drives these: staging -> canary -> promoted | rolled_back).
        `outcome` is set only on the terminal transitions; `reason`
        explains a rollback (spot-check code, SLO comparison). New
        event type only — every historical schema stays
        byte-identical."""
        codes = {"idle": 0, "staging": 1, "canary": 2, "promoted": 3,
                 "rolled_back": 4}
        if stage not in codes:
            raise ValueError(f"unknown rollout stage {stage!r} "
                             f"(one of {sorted(codes)})")
        self.rollout_stage = stage
        self._m_rollout_stage.set(codes[stage])
        if outcome is not None:
            self.rollout_outcomes.append(outcome)
            self._m_rollouts.inc(outcome=outcome)
        self._log(event="serve_rollout", stage=stage, outcome=outcome,
                  canary_requests=canary_requests, reason=reason)

    # -- speculative decoding --------------------------------------------

    def on_dispatch(self, kind: str) -> None:
        """One decode dispatch was COLLECTED: kind is 'window' (the
        fused one-token-per-step scan) or 'verify' (speculative
        draft-and-verify). Counted at collect, not at dispatch, so an
        aborted in-flight dispatch (engine failure mid-drill) whose
        tokens never land does not skew the denominator. The shared
        tokens-per-dispatch definition (summary) divides emitted
        tokens by this count, so spec-on and spec-off runs compare on
        one denominator."""
        if kind == "verify":
            self.verify_dispatches += 1
        else:
            self.window_dispatches += 1
        self._m_dispatches.inc(kind=kind)

    def on_spec(self, *, drafted: int, accepted: int, emitted: int,
                slots: int) -> None:
        """A verify dispatch was collected: `drafted` tokens proposed
        across `slots` genuinely PROPOSING rows (ride-along slots the
        drafter declined are excluded — they would dilute the rates
        operators tune by), `accepted` of them emitted as-is,
        `emitted` those rows' total tokens out (accepted + one bonus
        pick per row that had budget for it). New event type only —
        the frozen serve.jsonl schemas are untouched."""
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        self.spec_emitted += emitted
        self.spec_slot_verifies += slots
        if drafted:
            self._m_spec_drafted.inc(drafted)
        if accepted:
            self._m_spec_accepted.inc(accepted)
        self._log(event="serve_spec_verify", drafted=drafted,
                  accepted=accepted, emitted=emitted, slots=slots)

    def on_propose(self, seconds: float) -> None:
        """One drafting pass completed (scheduler._propose_drafts):
        `seconds` of wall time spent producing proposals — the n-gram
        scans and/or the learned drafter's batched device dispatch.
        Rollup only (one call per cycle; no per-cycle event spam, no
        new exposition lines — the /metrics byte-equality gates
        stay)."""
        self.propose_s += float(seconds)
        self.propose_calls += 1

    # -- paged KV ---------------------------------------------------------

    def on_pages(self, *, pages_total: int, pages_used: int,
                 pages_cached: int, resident_tokens: int,
                 resident_bytes: int) -> None:
        """Per-cycle page-pool occupancy from the paged engine
        (engine.page_stats): gauges for live scraping plus the peak
        rollup the summary reports — peak resident tokens over the
        bytes backing them is the tokens-per-HBM-byte capacity claim.
        Logs nothing per cycle (one gauge set per cycle, no event
        spam)."""
        if self.kv_pages_total is None:
            self._m_pages_total.set(pages_total)
        self.kv_pages_total = int(pages_total)
        self._m_pages_used.set(pages_used)
        self._m_pages_cached.set(pages_cached)
        self.kv_pages_used_peak = max(self.kv_pages_used_peak,
                                      int(pages_used))
        if resident_tokens > self.kv_resident_tokens_peak:
            self.kv_resident_tokens_peak = int(resident_tokens)
            if resident_bytes > 0:
                self.kv_tokens_per_byte_peak = (resident_tokens
                                                / resident_bytes)
        self.kv_resident_bytes_peak = max(self.kv_resident_bytes_peak,
                                          int(resident_bytes))

    def on_page_exhausted(self, *, rid=None, needed: int = 0) -> None:
        """The paged engine could not grant pages this cycle —
        admission held the queue head back, or a running slot's
        mid-decode growth failed. New event type only; the frozen
        historical schemas are untouched."""
        self.page_exhaustions += 1
        self._m_page_exhausted.inc()
        self._log(event="serve_page_exhausted", id=rid, needed=needed)

    # -- engine cycle ----------------------------------------------------

    def on_cycle(self, *, queue_depth: int, occupancy: float,
                 tokens: int = 0, prefill_s: float = 0.0) -> None:
        self.cycles += 1
        self._m_queue.set(queue_depth)
        self._m_occ.set(occupancy)
        self._m_last_tick.set(time.monotonic())
        if self.slo is not None:
            self.slo.evaluate()
        self.queue_depths.append(int(queue_depth))
        self.occupancies.append(float(occupancy))
        self.cycle_tokens.append(int(tokens))
        self.cycle_prefill_s.append(float(prefill_s))

    def on_jit_cache(self, total_entries: int) -> None:
        """Called once per cycle with the summed jit-cache entry count
        of the engine's compiled programs; any growth AFTER the first
        observation is a compile the serve loop paid for mid-traffic
        (the no-recompile contract says zero after warmup)."""
        if self._jit_cache_seen is not None:
            delta = total_entries - self._jit_cache_seen
            if delta > 0:
                self._m_compiles.inc(delta)
                self.compiles_observed += delta
        self._jit_cache_seen = total_entries

    def on_compile_cache(self, cache) -> None:
        """Snapshot a `CompileCache`'s counters after warmup: first
        call registers the serve_compile_cache_* gauges (lazily — see
        `_g_cc`), every call re-reads `cache.summary()` into them and
        the rollup, so warm-vs-cold spin-up is visible in the `stats`
        epilogue, not just in bench_serving_elastic."""
        if self._g_cc is None:
            reg = self._reg
            self._g_cc = {
                "hits": reg.gauge(
                    "serve_compile_cache_hits",
                    "persistent compile-cache hits (executables "
                    "deserialized from disk instead of compiled)"),
                "misses": reg.gauge(
                    "serve_compile_cache_misses",
                    "persistent compile-cache misses (programs XLA-"
                    "compiled and stored; includes corrupt evictions)"),
                "deserialize_s": reg.gauge(
                    "serve_compile_cache_deserialize_seconds",
                    "cumulative seconds spent deserializing cached "
                    "executables (the warm spin-up cost)"),
            }
        s = cache.summary()
        self.compile_cache_summary = s
        self._g_cc["hits"].set(s["hits"])
        self._g_cc["misses"].set(s["misses"])
        self._g_cc["deserialize_s"].set(s["deserialize_s"])
        self._log(event="serve_compile_cache", **s)

    # -- rollup -----------------------------------------------------------

    def summary(self) -> dict:
        """The serving scenario record: aggregate throughput over the
        span from first submit to last finish, TTFT percentiles, and
        mean queue/occupancy — the `serve_*` fields bench.py reports."""
        span = ((self._t_last - self._t_first)
                if self._t_first is not None and self._t_last is not None
                else None)
        out = {
            "serve_requests": self.finished,
            "serve_rejected": self.rejected,
            "serve_timed_out": self.timed_out,
            "serve_tokens": self.tokens_out,
            "serve_tokens_per_sec": (
                round(self.tokens_out / span, 2)
                if span and span > 0 else None),
            "serve_ttft_ms_p50": _r(_pct(self.ttft_s, 50), 1e3),
            "serve_ttft_ms_p95": _r(_pct(self.ttft_s, 95), 1e3),
            # TTFT decomposed: time queued (submit -> slot claimed) vs
            # time computing (slot claimed -> first token, i.e. prefill
            # + first decode window) — which half dominates tells an
            # operator whether to add slots or shrink prompts/chunks
            "serve_queue_wait_ms_p50": _r(_pct(self.queue_wait_s, 50),
                                          1e3),
            "serve_queue_wait_ms_p95": _r(_pct(self.queue_wait_s, 95),
                                          1e3),
            "serve_prefill_ms_p50": _r(_pct(self.prefill_s, 50), 1e3),
            "serve_prefill_ms_p95": _r(_pct(self.prefill_s, 95), 1e3),
            "serve_token_ms_p50": _r(_pct(self.token_s, 50), 1e3),
            # decode-side tail: p95 inter-token latency (additive key,
            # ISSUE 20) — the fleet SLO reads this side of the request,
            # TTFT the prefill side
            "serve_token_ms_p95": _r(_pct(self.token_s, 95), 1e3),
            "serve_slot_occupancy": (
                round(float(np.mean(self.occupancies)), 4)
                if self.occupancies else None),
            "serve_queue_depth_mean": (
                round(float(np.mean(self.queue_depths)), 2)
                if self.queue_depths else None),
            "serve_queue_depth_max": (
                max(self.queue_depths) if self.queue_depths else None),
            "serve_window_tokens_mean": (
                round(float(np.mean(self.cycle_tokens)), 2)
                if self.cycle_tokens else None),
            # host time per cycle spent admitting/prefilling before the
            # next window dispatch — the decode stall chunking bounds
            "serve_prefill_stall_ms_mean": (
                _r(float(np.mean(self.cycle_prefill_s)), 1e3)
                if self.cycle_prefill_s else None),
            "serve_prefill_stall_ms_max": (
                _r(float(np.max(self.cycle_prefill_s)), 1e3)
                if self.cycle_prefill_s else None),
            # NEW key (additive — existing consumers unaffected): jit
            # cache-size growth seen after the first cycle; nonzero
            # means admission traffic compiled something mid-serve
            "serve_compiles_observed": self.compiles_observed,
            # resilience rollup (additive, ISSUE 8): quarantines by
            # the health checks, bounded re-admissions, brownout sheds
            # and clamps, and drill faults fired
            "serve_slot_faults": self.slot_faults,
            "serve_retries": self.retries,
            "serve_shed": self.shed,
            "serve_clamped": self.clamped,
            "serve_faults_injected": self.faults_injected,
            # speculative rollup (additive, ISSUE 10). The SHARED
            # tokens-per-dispatch definition — emitted tokens over
            # decode dispatches of EITHER kind — so spec-on and
            # spec-off runs compare on one denominator; the spec-only
            # figures isolate the verify path: accept rate over
            # drafted tokens, and emitted tokens per participating
            # SLOT per verify (>1 means speculation beat one-token-
            # per-step decode for the slots that ran it)
            "serve_decode_dispatches": (self.window_dispatches
                                        + self.verify_dispatches),
            "serve_tokens_per_dispatch": (
                round(self.tokens_out
                      / (self.window_dispatches
                         + self.verify_dispatches), 3)
                if self.window_dispatches + self.verify_dispatches
                else None),
            "serve_spec_verify_dispatches": self.verify_dispatches,
            "serve_spec_drafted": self.spec_drafted,
            "serve_spec_accepted": self.spec_accepted,
            "serve_spec_accept_rate": (
                round(self.spec_accepted / self.spec_drafted, 4)
                if self.spec_drafted else None),
            "serve_spec_tokens_per_dispatch": (
                round(self.spec_emitted / self.spec_slot_verifies, 3)
                if self.spec_slot_verifies else None),
            # draft-model overhead: total drafting-pass wall seconds
            # (None when speculation never drafted — spec-off runs
            # keep their summary shape unchanged)
            "serve_spec_propose_s": (
                round(self.propose_s, 6) if self.propose_calls
                else None),
            # paged-KV rollup (additive, ISSUE 11): pool size and peak
            # occupancy, the peak tokens-resident-per-HBM-byte the
            # capacity claim is stated in, and how often the pool ran
            # dry — all None/0 on contiguous engines
            "serve_kv_pages_total": self.kv_pages_total,
            "serve_kv_pages_used_peak": (
                self.kv_pages_used_peak
                if self.kv_pages_total is not None else None),
            "serve_kv_resident_tokens_peak": (
                self.kv_resident_tokens_peak
                if self.kv_pages_total is not None else None),
            "serve_kv_resident_bytes_peak": (
                self.kv_resident_bytes_peak
                if self.kv_pages_total is not None else None),
            "serve_kv_tokens_per_hbm_byte": (
                None if self.kv_tokens_per_byte_peak is None
                else round(self.kv_tokens_per_byte_peak, 6)),
            "serve_page_exhaustions": self.page_exhaustions,
            # rollout rollup (additive, ROADMAP 4): terminal outcome
            # count, the last outcome, and the stage the machine ended
            # in — None/0 on servers that never rolled anything out
            "serve_rollouts": len(self.rollout_outcomes),
            "serve_rollout_outcome": (self.rollout_outcomes[-1]
                                      if self.rollout_outcomes
                                      else None),
            "serve_rollout_stage": self.rollout_stage,
        }
        if self.tenancy is not None:
            # per-tenant rollup (additive key, ISSUE 14): one record
            # per REGISTERED tenant — zeros included, so "tenant B was
            # untouched by A's flood" is readable straight off the
            # summary
            out["serve_tenants"] = {
                name: {
                    "requests": self.tenant_finished.get(name, 0),
                    "tokens": self.tenant_tokens.get(name, 0),
                    "ttft_ms_p50": _r(
                        _pct(self.tenant_ttft_s.get(name, []), 50),
                        1e3),
                    "ttft_ms_p95": _r(
                        _pct(self.tenant_ttft_s.get(name, []), 95),
                        1e3),
                    "shed": self.tenant_shed.get(name, 0),
                    "quota_rejections":
                        self.tenant_quota_rejections.get(name, 0),
                    "slo_breached": self.tenancy.breached(name),
                }
                for name in self.tenancy.names()}
        if self.compile_cache_summary is not None:
            # additive key (PR 18): the persistent compile-cache
            # rollup of THIS server's warmup — absent on servers that
            # spun up without one
            out["serve_compile_cache"] = dict(self.compile_cache_summary)
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.summary())
        return out

    def _log(self, **record) -> None:
        if self.logger is not None:
            self.logger.log(**record)


def _r(v, scale) -> float | None:
    return None if v is None else round(v * scale, 2)


def aggregate_summaries(metrics_list) -> dict:
    """The CLUSTER rollup over N replicas' `ServingMetrics` — the
    record the router's `summary()` reports and `bench_serving_cluster`
    compares across replica counts.

    Percentiles are computed over the POOLED per-request samples (every
    replica's raw ttft/queue-wait lists concatenated), never by
    averaging per-replica percentiles — a p95 of p95s is not a p95.
    Aggregate throughput spans from the earliest first-submit to the
    latest last-finish across the fleet: the wall-clock window a user
    of the whole cluster actually experienced."""
    metrics_list = list(metrics_list)
    ttft, queue_wait, itl = [], [], []
    tokens = finished = rejected = timed_out = shed = 0
    t_first, t_last = None, None
    for m in metrics_list:
        ttft.extend(m.ttft_s)
        queue_wait.extend(m.queue_wait_s)
        itl.extend(m.token_s)
        tokens += m.tokens_out
        finished += m.finished
        rejected += m.rejected
        timed_out += m.timed_out
        shed += m.shed
        if m._t_first is not None:
            t_first = (m._t_first if t_first is None
                       else min(t_first, m._t_first))
        if m._t_last is not None:
            t_last = (m._t_last if t_last is None
                      else max(t_last, m._t_last))
    span = (t_last - t_first
            if t_first is not None and t_last is not None else None)
    return {
        "cluster_replicas": len(metrics_list),
        "cluster_requests": finished,
        "cluster_rejected": rejected,
        "cluster_timed_out": timed_out,
        "cluster_shed": shed,
        "cluster_tokens": tokens,
        "cluster_tokens_per_sec": (round(tokens / span, 2)
                                   if span and span > 0 else None),
        "cluster_ttft_ms_p50": _r(_pct(ttft, 50), 1e3),
        "cluster_ttft_ms_p95": _r(_pct(ttft, 95), 1e3),
        "cluster_queue_wait_ms_p95": _r(_pct(queue_wait, 95), 1e3),
        # pooled decode-side tail (additive, ISSUE 20): p95 of the
        # per-request mean inter-token latencies across the fleet
        "cluster_itl_ms_p95": _r(_pct(itl, 95), 1e3),
    }
