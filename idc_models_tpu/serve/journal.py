"""Request journal: a jsonl write-ahead log of the serving engine's
accepted work, from which a REBUILT engine recovers in-flight requests
after a crash.

The scheduler's engine-failure cleanup (PR 3) keeps the *process*
serviceable, but a hard engine death (device loss, OOM kill of the
engine, an injected ``crash`` fault) still loses every in-flight
request: the caller holds error Results and nothing re-runs them. The
journal closes that gap with three record types through the standard
`observe.JsonlLogger` shape (new event types only — it is its own
file, never mixed into serve.jsonl):

- ``journal_submit``   at acceptance: everything needed to re-create
  the Request — id, prompt tokens, budget, eos, integer seed (explicit
  jax keys are not journalable — documented), the ORIGINAL relative
  deadline, and the trace_id, so a recovered request keeps its
  lifecycle identity across the crash boundary;
- ``journal_progress`` one batched record per written cycle: the
  cumulative emitted-token count of every emitting request, written
  every `progress_every` cycles (operator-facing progress accounting —
  recovery itself re-runs the request from scratch, which is what
  makes the recovered output bit-identical to an uncrashed run: the
  engine's serial-parity contract does the work, the journal only
  remembers WHAT to re-run — so the cadence is a cost knob, not a
  correctness one: one jsonl line per stride instead of one per slot
  per cycle keeps the armed clean path inside the <2% overhead bar);
- ``journal_finish``   at any terminal state, with the status.

A fourth, ``journal_migrate``, marks mid-decode slot migrations
(cluster drain, PR 18) — pure observability; the crash coverage of the
export→import gap rides entirely on the submit/finish pair (the source
finish, status ``"migrated"``, lands only after the peer's import).

Recovery = `pending_requests(path)`: every journaled submit without a
finish, in submit order. `LMServer.resubmit_pending` feeds them through
the normal admission path (chunked prefill + radix prefix cache
included), so a warm prefix cache carried across the rebuild serves
hits for the recovered prompts (gated by test).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from idc_models_tpu.observe import JsonlLogger
from idc_models_tpu.serve.api import Request


class RequestJournal:
    """Append-only WAL the scheduler writes through. Accepts a path
    (opened line-buffered; `close()` fsyncs) — hand the SAME path to
    the rebuilt server so the recovery records append after the
    crashed run's."""

    def __init__(self, path: str | os.PathLike, *,
                 progress_every: int = 8):
        if progress_every < 1:
            raise ValueError(f"need progress_every >= 1, got "
                             f"{progress_every}")
        self.path = Path(path)
        self.progress_every = int(progress_every)
        self._progress_skips = 0
        self._logger = JsonlLogger(self.path)

    def record_submit(self, entry, *, deadline_s: float | None) -> None:
        """One accepted request, with everything `pending_requests`
        needs to rebuild it. `deadline_s` is the ORIGINAL relative
        deadline (the scheduler rewrites `entry.deadline` to absolute
        clock time, which is meaningless to a recovering process)."""
        seed = (int(entry.rng)
                if isinstance(entry.rng, (int, np.integer)) else None)
        # the tenant tag travels the WAL so a recovered request bills
        # the SAME tenant (quota + adapter) on the rebuilt server;
        # written only when tagged, so tenant-less journals stay
        # byte-identical to every file this format ever wrote
        tkw = ({"tenant": entry.tenant}
               if getattr(entry, "tenant", None) is not None else {})
        self._logger.log(
            event="journal_submit", id=entry.rid,
            prompt=[int(t) for t in
                    np.asarray(entry.prompt).reshape(-1)],
            max_new_tokens=int(entry.budget), eos_id=entry.eos_id,
            seed=seed, deadline_s=deadline_s, trace_id=entry.trace_id,
            **tkw)

    def record_progress(self, tokens_by_rid: dict) -> None:
        """One batched progress record for every request that emitted
        this cycle ({rid: cumulative tokens}), written every
        `progress_every` calls — the stride and the batching keep the
        journal's clean-path cost to a fraction of a jsonl line per
        cycle (bench_serving_resilience prices it)."""
        if not tokens_by_rid:
            return
        self._progress_skips += 1
        if self._progress_skips < self.progress_every:
            return
        self._progress_skips = 0
        self._logger.log(event="journal_progress",
                         tokens={str(r): int(n)
                                 for r, n in tokens_by_rid.items()})

    def record_finish(self, rid, status: str,
                      reason: str | None = None) -> None:
        self._logger.log(event="journal_finish", id=rid, status=status,
                         reason=reason)

    def record_migrate(self, rid, direction: str, *, peer: str) -> None:
        """One mid-decode migration boundary (serve/cluster drain):
        ``direction`` is ``"out"`` (this replica exported the slot) or
        ``"in"`` (this replica imported it); ``peer`` names the other
        replica. Observability only — recovery semantics ride on the
        submit/finish pair: the SOURCE journal's submit stays open until
        the peer's import lands (a crash inside the export→import gap
        replays the request here, bit-identically by the serial-parity
        contract), and only then does the source write the terminal
        ``journal_finish`` with status ``"migrated"``."""
        if direction not in ("out", "in"):
            raise ValueError(f"migration direction must be 'out' or "
                             f"'in', got {direction!r}")
        self._logger.log(event="journal_migrate", id=rid,
                         direction=direction, peer=peer)

    def close(self) -> None:
        self._logger.close()

    def __enter__(self) -> "RequestJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def load_journal(path) -> dict:
    """Parse a journal file into ``{"pending": [Request, ...],
    "finished": {id: status}, "progress": {id: tokens}}``. A request
    re-submitted by a previous recovery appears once (the LAST submit
    record wins); malformed lines raise — a torn WAL is a real error,
    not something to skip silently."""
    submits: dict = {}
    finished: dict = {}
    progress: dict = {}
    order: list = []
    for i, line in enumerate(Path(path).read_text().splitlines()):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError as e:
            raise ValueError(f"journal {path}: line {i + 1} is not "
                             f"JSON: {e}") from None
        ev = rec.get("event")
        if ev == "journal_submit":
            rid = rec["id"]
            if rid not in submits:
                order.append(rid)
            submits[rid] = rec
            # a re-submit after recovery reopens the request
            finished.pop(rid, None)
        elif ev == "journal_finish":
            # an ENGINE-failure death (status=error, reason=error — the
            # crash/abort cleanup path) is a recoverable in-flight loss,
            # exactly what the journal exists to replay; every other
            # terminal state (ok, deadline, shed, an exhausted
            # slot_fault) is the request's honest final answer
            if (rec.get("status") == "error"
                    and rec.get("reason") == "error"):
                finished.pop(rec["id"], None)
            else:
                finished[rec["id"]] = rec.get("status")
        elif ev == "journal_progress":
            for rid, n in rec.get("tokens", {}).items():
                progress[rid] = int(n)
    pending = []
    for rid in order:
        if rid in finished:
            continue
        rec = submits[rid]
        pending.append(Request(
            id=str(rid), prompt=tuple(rec["prompt"]),
            max_new_tokens=int(rec["max_new_tokens"]),
            eos_id=rec.get("eos_id"), seed=rec.get("seed"),
            deadline_s=rec.get("deadline_s"),
            trace_id=rec.get("trace_id"),
            tenant=rec.get("tenant")))
    return {"pending": pending, "finished": finished,
            "progress": progress}


def pending_requests(path) -> list[Request]:
    """The requests a crashed run accepted but never finished, in
    submit order — feed them back through `LMServer.submit` (or
    `LMServer.resubmit_pending`) on the rebuilt server."""
    return load_journal(path)["pending"]
