"""Host-side free-list allocator for the paged KV pool (serve/engine.py
paged mode).

The device holds one fixed page pool per block — `[n_pages, page_size,
H, D]` K and V arrays — and an `[S, t_max/page_size]` int32 page table
mapping each slot's logical pages to physical ones. THIS class owns the
physical pages: admission asks it for pages covering the prompt plus
the decode reservation, slot release returns them, and the radix prefix
cache retains extra references so a snapshot's pages survive the slot
that wrote them.

Pages are REFERENCE COUNTED, not exclusively owned: a chunk-boundary
snapshot shares the very pages the prefilling slot wrote (they are
page-aligned and never written again — see docs/LONG_CONTEXT.md "Paged
KV"), so a prefix-cache hit costs zero copies and a shared page is
freed only when the last holder (slot or snapshot) releases it.

Allocation is DETERMINISTIC (lowest free id first, via a heap): a
replayed drill performs the identical alloc/release sequence and gets
the identical physical placement, which keeps fault-injection runs
bit-reproducible like every other serve drill.
"""

from __future__ import annotations

import heapq

import numpy as np


class PageExhausted(RuntimeError):
    """Raised when a grant cannot be satisfied — the scheduler's
    admission gate (`SlotEngine.can_admit_pages`) exists to make this
    unreachable on the admission path; mid-decode growth surfaces it
    as an honest per-request quarantine instead."""


class PageAllocator:
    """Free list + refcounts over `n_pages` fixed-size KV pages."""

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 1:
            raise ValueError(f"need n_pages >= 1, got {n_pages}")
        if page_size < 1:
            raise ValueError(f"need page_size >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.n_pages))
        heapq.heapify(self._free)
        self._refs = np.zeros(self.n_pages, np.int64)

    # -- grants -----------------------------------------------------------

    def alloc(self, n: int) -> list[int] | None:
        """Grant `n` fresh pages (refcount 1 each), lowest ids first;
        None — and NO partial grant — when fewer than `n` are free."""
        if n < 0:
            raise ValueError(f"need n >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [heapq.heappop(self._free) for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def retain(self, pages) -> None:
        """Add one reference to each page (prefix-cache snapshot, or a
        hit handing shared prefix pages to a new slot)."""
        for p in pages:
            if self._refs[p] < 1:
                raise ValueError(f"retain of free page {p}")
            self._refs[p] += 1

    def release(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to
        the free list. Returns how many were actually freed."""
        freed = 0
        for p in pages:
            if self._refs[p] < 1:
                raise ValueError(f"release of free page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                heapq.heappush(self._free, int(p))
                freed += 1
        return freed

    # -- accounting -------------------------------------------------------

    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        return self.n_pages - len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._refs[page])
