"""Iteration-level scheduling over the slot engine: admission queue,
deadlines, prefill/decode interleave, slot recycling.

The engine (serve/engine.py) is a device-state machine with no opinion
about WHICH request runs where or when; this module is the policy:

- **FIFO admission with backpressure** — `AdmissionQueue` holds at most
  `max_depth` waiting requests; a submit beyond that is REFUSED (the
  caller sees `False` and decides: retry, shed, or block). Bounded
  queues are the backpressure contract: an unbounded queue converts
  overload into unbounded tail latency instead of an explicit signal.
- **Deadlines** — a request may carry a deadline (seconds from submit).
  Queued requests past it are dropped without ever occupying a slot;
  RUNNING requests past it are cancelled mid-generation (partial tokens
  returned, the slot recycled for the next request).
- **Prefill-vs-decode interleave** — each `tick()` admits at most
  `max_prefills_per_cycle` queued requests into free slots before
  running one decode window. Prefill is the long-pole dispatch (O(P)
  work vs the window's O(W)); capping admissions per cycle bounds how
  long running requests stall behind a deep queue, while still refilling
  vacated slots within a cycle of them freeing.
- **Slot recycling** — EOS, budget exhaustion, and deadline cancels all
  route through `SlotEngine.release`; the vacated row is eligible for
  admission on the SAME tick the finish is observed, so slots never
  idle a full cycle between requests.
- **Resilience (ISSUE 8)** — per-cycle slot health checks quarantine a
  poisoned slot (non-finite/blown logits, violated invariants) and
  recover the REQUEST instead of failing the server: with a
  `RetryPolicy` armed the entry re-queues after an exponential backoff
  (keeping its original deadline and trace_id; `attempts`/`retried`
  surface on the Result), otherwise it finishes with an honest
  ``error``/``slot_fault`` status. A `ServeFaultPlan`
  (serve/faults.py) drives deterministic failure drills behind a
  default-off hook; a `RequestJournal` (serve/journal.py) WALs
  accepted work for crash recovery; a `BrownoutController`
  (serve/brownout.py) sheds load in stages when the SLO burns.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import time
from collections import deque

import numpy as np

from idc_models_tpu.observe import profile as prof
from idc_models_tpu.observe import trace
from idc_models_tpu.serve.engine import HEALTH_KINDS
from idc_models_tpu.serve.faults import (
    InjectedEngineCrash, InjectedPrefillError,
)

# process-unique request trace ids (pid + monotone counter): cheap
# enough to stamp on EVERY request whether or not a tracer is armed, so
# a rid's identity is stable across the jsonl log, the span export, and
# the user-facing Result
_TRACE_IDS = itertools.count(1)


def _next_trace_id() -> str:
    return f"{os.getpid():x}-{next(_TRACE_IDS):x}"


@dataclasses.dataclass(eq=False)     # identity eq: prompts are arrays
class Entry:
    """One request's lifetime record inside the scheduler: identity and
    limits in, timestamps/tokens/finish state out. The api layer wraps
    this into the user-facing `Result`."""
    rid: object
    prompt: object                   # int32 [P]
    budget: int
    eos_id: int | None = None
    rng: object = None               # per-request sampling key
    trace_id: str | None = None      # assigned at submit if not given
    # the cluster hop context (ISSUE 20): the router's cluster.request
    # root span id, threaded down so this request's serve.request span
    # opens as its CHILD and the cross-replica export stitches into one
    # tree. None = no router above (a direct server submit).
    parent_span: object = None
    # request-lifecycle span handles (observe/trace.py DETACHED spans —
    # they outlive any one tick, so they never sit on a thread's
    # open-span stack): the whole submit->finish interval, and the
    # queued segment inside it. The shared no-op handle when tracing
    # is disabled.
    span: object = None
    queue_span: object = None
    # RELATIVE seconds-from-submit when handed to submit(); rewritten to
    # the absolute clock time there
    deadline: float | None = None
    t_submit: float = 0.0
    t_admit: float | None = None
    t_first: float | None = None
    t_done: float | None = None
    slot: int | None = None
    tokens: list = dataclasses.field(default_factory=list)
    # pending|running|retrying|ok|timeout|rejected|shed|error
    status: str = "pending"
    # eos|budget|deadline|slot_fault|shed|error|None
    finish_reason: str | None = None
    error: str | None = None         # engine failure detail (status=error)
    # retry bookkeeping (RetryPolicy): total admission attempts (1 =
    # never faulted), whether any retry happened, and the absolute
    # clock time before which a quarantined entry must not re-queue
    attempts: int = 1
    retried: bool = False
    not_before: float = 0.0
    clamped: bool = False            # brownout shortened the budget
    # tenancy (serve/tenancy.py, ISSUE 14): the resolved tenant name
    # (None = no tenancy armed), its engine gather index, and the
    # admission-time page reservation the per-tenant KV budget charges
    tenant: str | None = None
    tid: int = 0
    pages_reserved: int = 0


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded re-admission for requests recovered from a quarantined
    slot or a failed prefill dispatch. A retried request re-enters the
    queue FRONT after `backoff_s * backoff_factor**k` (k = prior
    retries), keeps its original deadline and trace_id, and restarts
    from its prompt — the engine's serial-parity contract then makes
    the recovered greedy/seeded output bit-identical to an unfaulted
    run. A retry whose backoff would land past the deadline finishes
    immediately with the honest timeout/deadline status instead."""

    max_retries: int = 2
    backoff_s: float = 0.05
    backoff_factor: float = 2.0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"need max_retries >= 0, got "
                             f"{self.max_retries}")
        if self.backoff_s < 0:
            raise ValueError(f"need backoff_s >= 0, got "
                             f"{self.backoff_s}")
        if self.backoff_factor < 1.0:
            raise ValueError(f"need backoff_factor >= 1, got "
                             f"{self.backoff_factor}")

    def delay(self, prior_retries: int) -> float:
        return self.backoff_s * self.backoff_factor ** prior_retries


class AdmissionQueue:
    """Bounded FIFO. `push` returns False at max_depth — the
    backpressure signal — instead of growing without bound."""

    def __init__(self, max_depth: int = 64):
        if max_depth < 1:
            raise ValueError(f"need max_depth >= 1, got {max_depth}")
        self.max_depth = max_depth
        self._q: deque[Entry] = deque()

    def __len__(self) -> int:
        return len(self._q)

    def push(self, entry: Entry) -> bool:
        if len(self._q) >= self.max_depth:
            return False
        self._q.append(entry)
        return True

    def pop(self) -> Entry:
        return self._q.popleft()

    def peek(self) -> Entry:
        """The head entry without popping it — the page-aware
        admission gate inspects the head's demand before committing to
        take it."""
        return self._q[0]

    def push_front(self, entry: Entry) -> None:
        """Head-of-line insertion for RETRIED entries only: they were
        already admitted once (so they do not cheat the backpressure
        bound — the in-flight population is unchanged) and recovery
        latency beats FIFO fairness for a request that already waited
        its backoff."""
        self._q.appendleft(entry)

    def entries(self) -> tuple:
        """FIFO-order snapshot for the tenancy-aware admission scan: a
        quota-blocked HEAD must not starve other tenants (the whole
        point of per-tenant quotas), so admission may look past it —
        FIFO order is preserved WITHIN each tenant because the scan
        always takes the earliest admissible entry."""
        return tuple(self._q)

    def take(self, entry: Entry) -> None:
        """Remove a specific entry the admission scan picked (identity
        match — entries are identity-eq dataclasses)."""
        self._q.remove(entry)

    def expire(self, now: float) -> list[Entry]:
        """Drop queued entries past their deadline (they never reach a
        slot); returns them for result bookkeeping."""
        expired = [e for e in self._q
                   if e.deadline is not None and now >= e.deadline]
        if expired:
            self._q = deque(e for e in self._q if e not in expired)
        return expired


class Scheduler:
    """Continuous-batching loop: one `tick()` = expire deadlines, admit
    up to `max_prefills_per_cycle` requests into free slots, run ONE
    fused decode window of `window` tokens, recycle finished slots.
    Returns the entries that finished this tick."""

    def __init__(self, engine, *, window: int = 8, max_queue_depth: int = 64,
                 max_prefills_per_cycle: int = 1, metrics=None,
                 admit_after_collect: bool = True, clock=time.monotonic,
                 retry=None, fault_plan=None,
                 health_checks: bool | None = None, journal=None,
                 brownout=None, drafter=None, tenancy=None):
        if window < 1:
            raise ValueError(f"need window >= 1, got {window}")
        self.engine = engine
        self.window = window
        # speculative window mode (ISSUE 10): with a drafter AND an
        # engine built with draft_k, each cycle's decode dispatch may
        # be a VERIFY (k drafted tokens + the model's own correction
        # per slot, one dispatch) instead of the one-token-per-step
        # fused window — the policy lives in _propose_drafts
        self.drafter = drafter
        self._spec = (drafter is not None
                      and getattr(engine, "draft_k", None) is not None)
        if drafter is not None and not self._spec:
            raise ValueError(
                "a drafter needs an engine built with draft_k — the "
                "verify program is compiled at that fixed draft length")
        self.queue = AdmissionQueue(max_queue_depth)
        self.max_prefills_per_cycle = max(int(max_prefills_per_cycle), 1)
        self.metrics = metrics
        # resilience wiring (all default-off; see the module docstring):
        # retry = RetryPolicy, fault_plan = serve/faults.ServeFaultPlan,
        # journal = serve/journal.RequestJournal, brownout =
        # serve/brownout.BrownoutController. Health checks default to
        # armed exactly when quarantine could act on them.
        self.retry = retry
        self.fault_plan = fault_plan
        self.journal = journal
        self.brownout = brownout
        # tenancy (serve/tenancy.py): per-tenant quotas gate admission,
        # per-tenant brownouts shed one tenant's flood while its
        # neighbors stay normal, and each tenant's ttft:<name> SLO is
        # evaluated once per cycle — the built Tenancy runtime
        self.tenancy = tenancy
        if health_checks is None:
            health_checks = retry is not None or fault_plan is not None
        self.health_checks = bool(health_checks)
        self._retrying: list[Entry] = []
        # cumulative wall seconds spent in the drafting pass (host
        # scans + the learned drafter's batched device dispatch) — the
        # numerator of the bench's draft-overhead-percent key
        self.propose_seconds = 0.0
        self._cycle = 0
        self._closed = False
        # drain mode (elastic scale-down / SIGTERM): submits refuse
        # with the honest terminal shed status while accepted work
        # finishes or migrates — see begin_drain()
        self._draining = False
        self._prefill_error_pending = 0
        # paged-KV backpressure: set when admission stalls on page
        # exhaustion this cycle, consumed (and cleared) by the
        # brownout evaluation — ISSUE 11's exhaustion -> brownout wire
        self._page_pressure = False
        # refill slots the just-collected window freed before the next
        # window dispatches (recycle idles one window, not two) — at the
        # price of those prefills sitting in the device-idle gap instead
        # of overlapping the in-flight window
        self.admit_after_collect = admit_after_collect
        self.clock = clock
        self._running: dict[int, Entry] = {}
        # chunked-prefill engines: entries whose prompt is still being
        # chunked into a reserved slot (slot -> Entry); they join
        # _running when the engine's final chunk + insert land
        self._prefilling: dict[int, Entry] = {}
        # entries killed by an engine failure mid-tick: tick() re-raises
        # the engine error, so the caller collects them here (pop_failed)
        self._failed: list[Entry] = []
        self._chunked = getattr(engine, "prefill_chunk", None) is not None
        # quiesce(): one-shot suppression of the end-of-tick window
        # dispatch, so a caller can reach the engine dispatch-idle
        # (rollout spot-checks on paged engines) without losing the
        # collect/finish bookkeeping of a normal tick
        self._skip_dispatch = False

    # -- admission -------------------------------------------------------

    def close(self) -> None:
        """Shut the admission surface down: every later `submit()`
        raises RuntimeError instead of enqueueing into a loop nobody
        will ever tick again (previously undefined behavior — the
        request would sit queued forever). Already-accepted work can
        still be ticked/drained by the caller before discarding the
        scheduler."""
        self._closed = True

    def submit(self, entry: Entry) -> bool:
        """Validate + enqueue. Returns False (backpressure, or a
        brownout shed — distinguishable by `entry.status == "shed"`)
        when the request is refused; raises on requests that could
        NEVER be served (too long for t_max, missing rng for sampling)
        — those are caller errors, not load — and RuntimeError after
        `close()`."""
        if self._closed:
            raise RuntimeError(
                "Scheduler.submit() after close(): the serving loop "
                "has shut down and would never tick this request — "
                "build a new server instead of submitting to a dead "
                "queue")
        p_len = len(entry.prompt)
        if p_len < 1:
            raise ValueError("empty prompt")
        if entry.budget < 1:
            raise ValueError(f"need max_new_tokens >= 1, got "
                             f"{entry.budget}")
        if p_len + entry.budget > self.engine.t_max:
            raise ValueError(
                f"prompt {p_len} + max_new_tokens {entry.budget} exceeds "
                f"t_max {self.engine.t_max}")
        if self.engine.temperature > 0.0 and entry.rng is None:
            raise ValueError("sampling (temperature > 0) needs a "
                             "per-request rng key")
        # resolve the EFFECTIVE stop token now (request override, else
        # the engine default; -1 opts out) so the finish_reason below
        # and the engine agree on what "eos" means for this request
        if entry.eos_id is None:
            entry.eos_id = self.engine.eos_id
        if entry.eos_id is not None and entry.eos_id < 0:
            entry.eos_id = None
        tenant_bc = None
        if self.tenancy is not None:
            # an unknown tenant tag is a caller error taught loudly —
            # silently billing the default tenant would charge one
            # tenant's quota for another's traffic
            t = self.tenancy.resolve(entry.tenant)
            entry.tenant, entry.tid = t.name, t.tid
            tenant_bc = self.tenancy.brownouts.get(t.name)
        entry.t_submit = self.clock()
        # brownout shed beats backpressure: an explicit, honest
        # refusal (Result.status == "shed") the client can act on,
        # recorded BEFORE the queue is consulted so shedding actually
        # relieves the queue instead of racing it. The TENANT's own
        # controller sheds first: one tenant's flood refuses that
        # tenant's submits while every other tenant stays normal.
        # drain mode sheds exactly like a brownout: an honest terminal
        # refusal, never a silent queue into a replica that is leaving
        shedding = self._draining or (self.brownout is not None
                                      and self.brownout.shedding)
        tenant_shed = tenant_bc is not None and tenant_bc.shedding
        if shedding or tenant_shed:
            entry.status, entry.finish_reason = "shed", "shed"
            entry.t_done = entry.t_submit
            if entry.trace_id is None:
                entry.trace_id = _next_trace_id()
            kw = ({"tenant": entry.tenant}
                  if entry.tenant is not None else {})
            trace.point("serve.shed", rid=entry.rid,
                        trace_id=entry.trace_id, **kw)
            if self.metrics:
                # tenant attribution ONLY when the tenant's OWN
                # controller shed it: billing a server-wide shed to
                # the per-tenant "own brownout" counters would make a
                # victim tenant read as degraded by its own flood
                self.metrics.on_shed(
                    entry.rid,
                    tenant=entry.tenant if tenant_shed else None)
            return False
        if self.tenancy is not None and entry.tenant is not None:
            # per-tenant queue quota: refused WITHOUT touching the
            # shared queue budget, so a flooding tenant cannot fill
            # the FIFO other tenants admit from. Deliberately not fed
            # to the error-rate SLO — like shed, the refusal IS the
            # isolation mechanism working, and scoring it as an error
            # would make protection look like failure. (On a tenancy-
            # LESS server a request's tenant tag is inert bookkeeping
            # — the cluster router still uses it for affinity.)
            q = self.tenancy.quota(entry.tenant).max_queued
            if q is not None and self._tenant_queued(entry.tenant) >= q:
                entry.status = "rejected"
                if self.metrics:
                    self.metrics.on_tenant_quota(
                        entry.rid, tenant=entry.tenant, kind="queued")
                return False
        deadline_rel = entry.deadline
        if entry.deadline is not None:
            entry.deadline = entry.t_submit + entry.deadline
        if not self.queue.push(entry):
            entry.status = "rejected"
            if self.metrics:
                self.metrics.on_reject(entry.rid, entry.t_submit)
            return False
        if entry.trace_id is None:
            entry.trace_id = _next_trace_id()
        if self.journal is not None:
            self.journal.record_submit(entry, deadline_s=deadline_rel)
        # the request-lifecycle chain: a detached serve.request span
        # covering submit->finish (it spans many ticks, so it must not
        # enter any thread's open-span stack), with the queued segment
        # as a detached child closed at admission. Every span in the
        # chain carries rid, so one grep over the export reconstructs
        # the request's full timeline.
        tkw = ({"tenant": entry.tenant}
               if entry.tenant is not None else {})
        entry.span = trace.start_span("serve.request",
                                      parent=entry.parent_span,
                                      rid=entry.rid,
                                      trace_id=entry.trace_id, **tkw)
        entry.queue_span = trace.start_span(
            "serve.queued", parent=entry.span.span_id, rid=entry.rid,
            trace_id=entry.trace_id)
        if self.metrics:
            self.metrics.on_submit(entry.rid, entry.t_submit,
                                   tenant=entry.tenant)
        return True

    def _tenant_queued(self, tenant: str) -> int:
        """Queued entries a tenant holds right now — derived from the
        queue itself (never an incrementally maintained counter, so
        there is nothing to drift out of sync)."""
        return sum(1 for e in self.queue.entries()
                   if e.tenant == tenant)

    def _page_gate(self, entry: Entry, eff: int) -> bool:
        """The ONE page-aware admission gate both the FIFO-head path
        and the tenancy scan consult: True when the paged engine can
        grant pages for (prompt, effective budget) right now; on False
        records the exhaustion backpressure (the brownout 'pages'
        signal + the serve_page_exhausted event). Always True on
        contiguous engines."""
        can_admit = getattr(self.engine, "can_admit_pages", None)
        if can_admit is None or can_admit(len(entry.prompt), eff):
            return True
        self._page_pressure = True
        on_exh = getattr(self.metrics, "on_page_exhausted", None)
        if on_exh is not None:
            on_exh(rid=entry.rid,
                   needed=len(entry.prompt) + entry.budget)
        return False

    def _tenant_residency(self) -> tuple[dict, dict]:
        """(slots, pages) each tenant holds across running +
        prefilling entries — derived on demand from the live tracking
        dicts, O(n_slots), the per-tenant admission-quota ledger."""
        slots: dict[str, int] = {}
        pages: dict[str, int] = {}
        for e in list(self._running.values()) + list(
                self._prefilling.values()):
            if e.tenant is None:
                continue
            slots[e.tenant] = slots.get(e.tenant, 0) + 1
            pages[e.tenant] = pages.get(e.tenant, 0) + e.pages_reserved
        return slots, pages

    def _admit_free_slots(self) -> int:
        """Pop queued entries into free slots, at most
        max_prefills_per_cycle — the ONE admission bookkeeping path for
        both tick() passes. On a chunked engine admission only RESERVES
        the slot (start_prefill dispatches nothing); the prompt is fed
        chunk by chunk by `_step_prefills`, one chunk per cycle, so a
        long prompt never stalls the decode windows behind one
        monolithic dispatch."""
        admitted = 0
        free = self.engine.free_slots()
        clamp = (self.brownout.token_clamp if self.brownout is not None
                 else None)
        if self.tenancy is not None:
            slots_used, pages_used = self._tenant_residency()
        while (admitted < self.max_prefills_per_cycle and free
               and len(self.queue)):
            # page-aware admission (paged engines): the HEAD request
            # must fit — pages for its prompt plus the decode
            # reservation — before it leaves the queue. FIFO holds
            # (no skipping ahead of a starved head: that would starve
            # long requests forever); the exhaustion is recorded as
            # backpressure and feeds the brownout signal below.
            # With TENANCY armed the scan may look past entries whose
            # TENANT-LOCAL quota (resident slots, page budget) blocks
            # them — a flooding tenant's backlog must not starve its
            # neighbors, and FIFO holds within each tenant — but a
            # GLOBAL page exhaustion still freezes the whole scan:
            # skipping past it would starve long requests forever.
            e = t_clamp = None
            if self.tenancy is None:
                head = self.queue.peek()
                # gate on the EFFECTIVE budget: brownout stage 2 clamps
                # it at admission below, and the clamp is exactly the
                # smaller-reservations lever the pages-pressure
                # escalation exists to pull — gating on the unclamped
                # ask would wedge admission at the stage meant to
                # unwedge it
                eff = (head.budget if clamp is None
                       else min(head.budget, clamp))
                if not self._page_gate(head, eff):
                    break
                e = self.queue.pop()
            else:
                stop = False
                for cand in self.queue.entries():
                    quota = self.tenancy.quota(cand.tenant)
                    if (quota.max_resident_slots is not None
                            and slots_used.get(cand.tenant, 0)
                            >= quota.max_resident_slots):
                        continue         # tenant-local: skip, no HOL
                    bc = self.tenancy.brownouts.get(cand.tenant)
                    cand_clamp = (bc.token_clamp if bc is not None
                                  else None)
                    eff = cand.budget
                    for c in (clamp, cand_clamp):
                        if c is not None:
                            eff = min(eff, c)
                    need = self.engine.pages_for_admission(
                        len(cand.prompt), eff)
                    if (quota.kv_page_budget is not None
                            and pages_used.get(cand.tenant, 0) + need
                            > quota.kv_page_budget):
                        continue         # tenant-local page budget:
                        #                  waits for its own releases
                    if not self._page_gate(cand, eff):
                        stop = True      # GLOBAL exhaustion freezes
                        break            # the scan — no skipping
                    e, t_clamp = cand, cand_clamp
                    break
                if stop or e is None:
                    break
                self.queue.take(e)
            slot = free.pop(0)
            eff_clamp = clamp
            if t_clamp is not None:
                eff_clamp = (t_clamp if eff_clamp is None
                             else min(eff_clamp, t_clamp))
            if eff_clamp is not None and e.budget > eff_clamp:
                # brownout stage 2 (server-wide AND/OR the tenant's
                # own): shorter answers for everyone beats no answers
                # for some — recorded per request so the truncated
                # budget is visible next to the finish
                if self.metrics:
                    self.metrics.on_clamp(e.rid, asked=e.budget,
                                          clamp=eff_clamp)
                e.budget, e.clamped = eff_clamp, True
            if self.tenancy is not None:
                e.pages_reserved = self.engine.pages_for_admission(
                    len(e.prompt), e.budget)
                slots_used[e.tenant] = slots_used.get(e.tenant, 0) + 1
                pages_used[e.tenant] = (pages_used.get(e.tenant, 0)
                                        + e.pages_reserved)
            eos = e.eos_id if e.eos_id is not None else -1
            e.slot, e.status, e.t_admit = slot, "running", self.clock()
            # registered BEFORE the engine call: if the engine raises
            # mid-admission, tick's failure handler finds this entry in
            # the tracking dict and fails it with the others instead of
            # silently dropping it
            if self._chunked:
                self._prefilling[slot] = e
                self.engine.start_prefill(slot, e.prompt, e.budget,
                                          rng=e.rng, eos_id=eos,
                                          tag=e.rid, tid=e.tid)
            else:
                self._running[slot] = e
                self.engine.admit(slot, e.prompt, e.budget, rng=e.rng,
                                  eos_id=eos, tag=e.rid, tid=e.tid)
            # recorded only AFTER the engine accepted the request — an
            # admit that raises must not leave a phantom queue-wait
            # sample (and _wait_by_rid entry) behind
            if e.queue_span is not None:
                e.queue_span.close(
                    queue_wait_ms=round((e.t_admit - e.t_submit) * 1e3,
                                        3))
            if self.metrics:
                self.metrics.on_admit(e.rid, e.t_admit - e.t_submit)
            admitted += 1
        return admitted

    def _step_prefills(self, done) -> int:
        """Advance pending chunked prefills: at most
        max_prefills_per_cycle chunk DISPATCHES per cycle, oldest
        pending prefill first (FIFO completes a long prompt before
        starting to chunk the next — TTFT order follows admission
        order). Entries whose final chunk lands move to _running and
        decode from the next window. Returns chunk dispatches spent.

        A chunk dispatch that raises is REQUEST-scoped when a retry
        policy is armed (the dispatch's inputs are that request's own
        caches): the prefilling entry is quarantined — retried or
        failed honestly — and every other slot keeps serving. Without
        a retry policy the historical contract holds: the error
        propagates and the tick's failure cleanup aborts the batch."""
        steps = 0
        while steps < self.max_prefills_per_cycle and self._prefilling:
            slot = next(iter(self._prefilling))
            try:
                if self._prefill_error_pending:
                    self._prefill_error_pending -= 1
                    raise InjectedPrefillError(
                        f"injected prefill-chunk failure (slot {slot})")
                finished = self.engine.prefill_step(slot)
            except Exception as exc:
                if self.retry is None:
                    raise
                e = self._prefilling.pop(slot)
                self.engine.cancel_prefill(slot)
                self._quarantine(e, "prefill_error", self.clock(), done,
                                 detail=f"{type(exc).__name__}: {exc}")
                steps += 1
                continue
            if finished:
                self._running[slot] = self._prefilling.pop(slot)
            steps += 1
        return steps

    def _quarantine(self, e: Entry, kind: str, now: float, done,
                    *, detail: str | None = None) -> None:
        """Recover ONE faulted request: re-queue it after the retry
        backoff when the policy and its deadline allow, else finish it
        with an honest status. Emits the `serve.slot_fault` (and
        `serve.retry`) lifecycle points so one rid grep shows
        fault -> quarantine -> retry -> finish under the request's
        trace_id."""
        detail = detail or f"slot fault: {kind}"
        parent = e.span.span_id if e.span is not None else None
        trace.point("serve.slot_fault", parent=parent, rid=e.rid,
                    kind=kind, slot=e.slot, trace_id=e.trace_id)
        if self.metrics:
            self.metrics.on_slot_fault(e.rid, kind=kind, slot=e.slot)
        e.slot = None
        prior = e.attempts - 1
        can_retry = (self.retry is not None
                     and prior < self.retry.max_retries)
        delay = self.retry.delay(prior) if can_retry else 0.0
        deadline_blocks = (e.deadline is not None
                           and now + delay >= e.deadline)
        if can_retry and not deadline_blocks:
            # restart from the prompt: the tokens emitted so far came
            # from (or raced) the poisoned state, and a clean re-run
            # re-derives the exact stream (serial-parity contract), so
            # discarding is what makes recovery bit-identical
            e.attempts += 1
            e.retried = True
            e.tokens = []
            e.t_first = None
            e.status = "retrying"
            e.not_before = now + delay
            self._retrying.append(e)
            trace.point("serve.retry", parent=parent, rid=e.rid,
                        attempt=e.attempts,
                        delay_ms=round(delay * 1e3, 3),
                        trace_id=e.trace_id)
            if self.metrics:
                self.metrics.on_retry(e.rid, attempt=e.attempts,
                                      delay_s=delay)
            return
        if deadline_blocks or (e.deadline is not None
                               and now >= e.deadline):
            e.status, e.finish_reason = "timeout", "deadline"
        else:
            e.status, e.finish_reason = "error", "slot_fault"
            e.error = f"{detail} (attempt {e.attempts})"
        e.t_done = now
        self._finish(e, done)

    # -- the cycle -------------------------------------------------------

    def idle(self) -> bool:
        return (not self._running and not self._prefilling
                and not len(self.queue) and not self._retrying
                and self.engine._pending is None)

    def load(self) -> int:
        """Requests this scheduler is responsible for right now —
        queued + prefilling + running + quarantined-awaiting-retry.
        The cluster router's least-loaded placement signal
        (serve/cluster/router.py): one integer, no device traffic."""
        return (len(self.queue) + len(self._running)
                + len(self._prefilling) + len(self._retrying))

    def _apply_faults(self, cycle: int) -> None:
        """Fire the plan's non-burst faults scheduled for this cycle —
        pure function of (plan, cycle), so drills replay exactly.
        Burst arrivals are injected by the api layer (they are
        submits, not engine events)."""
        for f in self.fault_plan.at(cycle):
            if self.metrics:
                self.metrics.on_fault_injected(f.kind, tick=cycle)
            if f.kind == "stall":
                # a straggling dispatch / GC pause / noisy neighbor:
                # the tick simply takes longer — the latency fault the
                # TTFT SLO burn is supposed to catch
                time.sleep(f.seconds)
            elif f.kind == "crash":
                exc = InjectedEngineCrash(
                    f"injected engine crash at cycle {cycle}")
                self._abort_running(exc)
                raise exc
            elif f.kind in ("nan_logits", "garbage_logits"):
                self.engine.inject_slot_fault(f.slot, f.kind)
            elif f.kind == "prefill_error":
                self._prefill_error_pending += 1

    def _requeue_retries(self, now: float, done) -> None:
        """Move quarantined entries whose backoff elapsed back to the
        queue FRONT (oldest first); entries whose deadline died while
        they waited finish honestly instead of burning a slot."""
        due, waiting = [], []
        for e in self._retrying:
            if e.deadline is not None and now >= e.deadline:
                e.status, e.finish_reason = "timeout", "deadline"
                e.t_done = now
                self._finish(e, done)
            elif now >= e.not_before:
                e.status = "pending"
                due.append(e)
            else:
                waiting.append(e)
        self._retrying = waiting
        for e in reversed(due):
            self.queue.push_front(e)

    def _check_slot_health(self, now: float, got, done) -> list:
        """Per-cycle health pass over the RUNNING slots: one tiny
        jitted reduce + [S]-int fetch (engine.slot_health) plus the
        free host-shadow invariants. Runs after collect and BEFORE the
        next window dispatch, so a slot whose logits a fault poisoned
        this cycle is quarantined before a single token is sampled
        from them. Returns `got` with the quarantined entries' just-
        collected tokens dropped (they were computed from, or raced,
        the corrupted state)."""
        codes = self.engine.slot_health()
        quarantined = set()
        for slot, e in list(self._running.items()):
            kind = HEALTH_KINDS.get(int(codes[slot]))
            if kind is None and not self.engine.slot_invariants_ok(slot):
                kind = "invariant"
            if kind is None:
                continue
            self.engine.release(slot)
            del self._running[slot]
            quarantined.add(id(e))
            self._quarantine(e, kind, now, done)
        if not quarantined:
            return got
        return [(e, t) for e, t in got if id(e) not in quarantined]

    def tick(self) -> list[Entry]:
        """One pipelined cycle. Host work (admission prefills, result
        bookkeeping) runs WHILE the previously begun window executes on
        device; the tick ends by dispatching the next window. Slot
        availability seen by admissions is one window stale — a row
        freed by the in-flight window refills next tick.

        Traced (observe/trace.py, no-op unless a tracer is active):
        one `serve.tick` span per cycle with `serve.admit`,
        `serve.collect` and `serve.window` nested under it, and the
        engine's `serve.prefill`/`serve.prefill_chunk` spans nested
        under the admit. ACROSS ticks, each request's detached
        `serve.request` span (opened at submit) accumulates its
        lifecycle chain — see the Entry fields above."""
        with trace.span("serve.tick"):
            return self._tick()

    def quiesce(self) -> list[Entry]:
        """One normal cycle with the end-of-tick window dispatch
        suppressed: the in-flight window is collected and finalized
        exactly as tick() would, but nothing new launches, leaving the
        engine dispatch-idle. The safe point for operations that
        replay engine programs over the live device state — a paged
        engine's rollout spot-check (`spot_check_params`) needs it
        before candidate weights can be staged. Costs one window of
        decode idleness; the next tick() resumes dispatching."""
        self._skip_dispatch = True
        try:
            with trace.span("serve.tick", quiesce=True):
                return self._tick()
        finally:
            self._skip_dispatch = False

    def _tick(self) -> list[Entry]:
        now = self.clock()
        done: list[Entry] = []
        # 0. declarative fault drills (default-off): stall/crash/
        #    poison/prefill-error faults scheduled for this cycle fire
        #    before any real work, so the cycle index a fault names is
        #    exactly the cycle it perturbs
        cycle = self._cycle
        self._cycle += 1
        if self.fault_plan is not None:
            self._apply_faults(cycle)
        # 1. queued requests past deadline never occupy a slot
        for e in self.queue.expire(now):
            e.status, e.finish_reason, e.t_done = "timeout", "deadline", now
            self._finish(e, done)
        # 1.5 quarantined entries whose backoff elapsed re-queue at the
        #     head; ones whose deadline died waiting finish honestly
        if self._retrying:
            self._requeue_retries(now, done)
        # 2. interleave policy: refill known-free slots and (chunked
        #    engines) advance pending prefills by at most
        #    max_prefills_per_cycle chunk dispatches — all of it
        #    overlapping the in-flight window's execution. The host
        #    time this section takes is the per-cycle decode STALL a
        #    monolithic prefill inflates, so it is measured and
        #    reported (serve_chunked_prefill_decode_stall_ms).
        #    An engine failure DURING admission/chunking gets the same
        #    cleanup contract as collect()/begin_window() below: every
        #    in-flight entry is failed + released, then the error
        #    propagates — without this, a chunk dispatch that raises
        #    would leave _prefilling populated (with caches already
        #    donated to the dead dispatch) and wedge every later tick
        t_pf = self.clock()
        # naming_compiles: when the compile watchdog (observe/profile)
        # is armed, any XLA compile the admission path triggers — the
        # no-recompile contract says NONE after warmup — is recorded
        # under this name; with no watchdog it is the shared no-op
        # handle (one module-global read, same cost class as a
        # disabled trace span; charged in bench_profile_overhead)
        with trace.span("serve.admit") as _sp, \
                prof.naming_compiles("serve.admit"):
            try:
                admitted = self._admit_free_slots()
                chunk_steps = (self._step_prefills(done) if self._chunked
                               else 0)
            except Exception as e:
                self._failed.extend(done)
                self._abort_running(e)
                raise
            _sp.set(admitted=admitted, chunk_steps=chunk_steps)
        prefill_stall_s = self.clock() - t_pf
        # 3. collect the in-flight window; recycle on EOS / budget.
        #    Only the recycle decisions happen here — per-token
        #    bookkeeping is deferred past the next dispatch (step 6) so
        #    the device never idles behind host accounting.
        #    An engine failure (device OOM, poisoned program, runtime
        #    loss) must not leak the in-flight slots: every running
        #    entry is failed + released, THEN the error propagates —
        #    the queue stays serviceable for a caller that recovers
        with trace.span("serve.collect") as _sp:
            try:
                out = self.engine.collect()
            except Exception as e:
                # step-1 expiries were already finalized into `done`,
                # which this raise would otherwise discard — surface
                # them through pop_failed alongside the aborted entries
                self._failed.extend(done)
                self._abort_running(e)
                raise
            _sp.set(slots=len(out),
                    tokens=sum(len(t) for t in out.values()))
            # dispatch accounting happens HERE, at collect, not at
            # dispatch: a dispatch aborted mid-flight (engine failure,
            # crash drill) never lands tokens, so counting it would
            # permanently skew tokens-per-dispatch and break the
            # "spec events == verify dispatches" invariant. A window
            # over a non-empty running set always returns rows, so
            # `out or spec` detects exactly the collected dispatches.
            spec = getattr(self.engine, "last_spec", None)
            if (out or spec) and self.metrics:
                self.metrics.on_dispatch("verify" if spec else "window")
            # a collected VERIFY reports its accept bookkeeping
            # (drafted/accepted/emitted, fetched with the tokens)
            if spec and self.metrics:
                self.metrics.on_spec(**spec)
        t_now = self.clock()
        got: list[tuple[Entry, list]] = []
        finished: list[Entry] = []
        for slot, toks in out.items():
            e = self._running.get(slot)
            if e is None:            # cancelled while the window flew
                continue
            got.append((e, toks))
            if self.engine.finished(slot):
                self.engine.release(slot)
                del self._running[slot]
                finished.append(e)
        # 3.5 per-window slot health: quarantine poisoned slots (and
        #     drop their just-collected tokens) BEFORE the next window
        #     dispatches — the request recovers, the server keeps
        #     serving every other slot
        if self.health_checks and self._running:
            got = self._check_slot_health(now, got, done)
        # 4. running requests past deadline are cancelled mid-generation
        #    (after collect, so the partial tokens reach the result);
        #    prefilling requests past deadline drop their partial chunks
        #    and free the reserved slot immediately
        cancelled: list[Entry] = []
        for slot, e in list(self._running.items()):
            if e.deadline is not None and now >= e.deadline:
                self.engine.release(slot)
                del self._running[slot]
                cancelled.append(e)
        for slot, e in list(self._prefilling.items()):
            if e.deadline is not None and now >= e.deadline:
                self.engine.cancel_prefill(slot)
                del self._prefilling[slot]
                cancelled.append(e)
        # 5. second admission pass: slots freed by the JUST-collected
        #    window refill before the next window dispatches, so a
        #    recycle costs one window of idleness, not two. This pass's
        #    prefill dispatches sit squarely in the device-idle gap, so
        #    its host time joins the measured decode stall (on a
        #    monolithic engine THIS is where recycle-refill prefills
        #    land — omitting it would understate the baseline stall the
        #    chunked-vs-monolithic bench comparison reports)
        if self.admit_after_collect:
            t_pf2 = self.clock()
            try:
                with trace.span("serve.admit", refill=True) as _sp:
                    n2 = self._admit_free_slots()
                    _sp.set(admitted=n2)
                admitted += n2
            except Exception as e:
                # same salvage as a begin_window failure: the entries
                # the just-collected window completed are real results
                # — finalize them (and the step-1 expiries) into the
                # pop_failed channel before aborting the rest
                self._finalize_window(got, finished, cancelled, t_now,
                                      now, self._failed)
                self._failed.extend(done)
                self._abort_running(e)
                raise
            prefill_stall_s += self.clock() - t_pf2
        # 5.5 paged engines: grow page grants so every running slot
        #     can emit the next dispatch's worth of tokens; slots the
        #     pool cannot cover even after prefix-cache reclaim are
        #     quarantined NOW (retry or honest finish — never a
        #     dispatch that would decode blind past its last page).
        #     Their just-collected tokens are dropped like a health
        #     quarantine's: a retry restarts from the prompt and
        #     re-derives the exact stream.
        if self._running:
            need = self.window
            if self._spec:
                need = max(need, self.engine.draft_k + 1)
            starved = self.engine.ensure_decode_room(need)
            if starved:
                self._page_pressure = True
                on_exh = (getattr(self.metrics, "on_page_exhausted",
                                  None) if self.metrics else None)
                quarantined = set()
                for slot in starved:
                    e = self._running.pop(slot, None)
                    if e is None:
                        continue
                    if on_exh is not None:
                        on_exh(rid=e.rid, needed=need)
                    self.engine.release(slot)
                    quarantined.add(id(e))
                    self._quarantine(e, "page_exhausted", now, done)
                got = [(e, t) for e, t in got
                       if id(e) not in quarantined]
        # 6. dispatch the next window over every occupied slot — the
        #    plain fused window, or (speculative mode, when the
        #    drafter proposed and every running slot has verify room)
        #    ONE draft-and-verify dispatch emitting up to draft_k + 1
        #    tokens per slot
        occupancy = len(self._running) / self.engine.n_slots
        if self._running and not self._skip_dispatch:
            try:
                proposal = (self._propose_drafts(got) if self._spec
                            else None)
                # the spans cover the (async) DISPATCH — device
                # execution overlaps the deferred bookkeeping below and
                # is paid for inside the NEXT tick's serve.collect
                if proposal is not None:
                    drafts, vlive, proposed = proposal
                    with trace.span("serve.verify",
                                    k=self.engine.draft_k,
                                    slots=int(vlive.sum()),
                                    hits=int(proposed.sum())) as _wsp:
                        if trace.get_tracer() is not None:
                            _wsp.set(rids=[e.rid for e
                                           in self._running.values()])
                        self.engine.begin_verify(drafts, vlive,
                                                 proposed)
                else:
                    with trace.span("serve.window", window=self.window,
                                    slots=len(self._running)) as _wsp:
                        if trace.get_tracer() is not None:
                            # the decode-window leg of each rid's
                            # lifecycle chain — the list is built only
                            # when a tracer is armed (disabled-path
                            # cost stays one global read, gated by
                            # bench_tracer_overhead)
                            _wsp.set(rids=[e.rid for e
                                           in self._running.values()])
                        self.engine.begin_window(self.window)
            except Exception as e:
                # entries the just-collected window COMPLETED (EOS/
                # budget/deadline) are real results, not casualties:
                # finalize them with their true statuses — plus the
                # step-1 expiries — into the pop_failed channel this
                # raise would otherwise discard, then abort the rest
                self._finalize_window(got, finished, cancelled, t_now,
                                      now, self._failed)
                self._failed.extend(done)
                self._abort_running(e)
                raise
        # 7. deferred bookkeeping — runs WHILE the new window computes.
        #    Cycles that only admitted/prefilled (nothing decoding yet —
        #    e.g. a long prompt's chunk-by-chunk admission) STILL record:
        #    those are exactly the cycles whose stall the
        #    serve_prefill_stall_* metric exists to expose; only truly
        #    empty drain ticks are skipped.
        emitted = self._finalize_window(got, finished, cancelled, t_now,
                                        now, done)
        # brownout runs EVERY cycle (drain ticks included — recovery
        # hysteresis needs to see the queue empty out); page
        # exhaustion joins the SLO/queue signals so a pool running dry
        # degrades the server instead of wedging admissions silently
        page_pressure, self._page_pressure = self._page_pressure, False
        if self.brownout is not None:
            self.brownout.evaluate(queue_depth=len(self.queue),
                                   pressure=page_pressure)
        # per-tenant brownouts run every cycle like the global one
        # (drain ticks included — recovery hysteresis needs to watch
        # each tenant's queue empty out), each fed only ITS tenant's
        # queue depth and ttft:<name> SLO; one tenant escalating
        # leaves its neighbors' controllers at normal (gated by test)
        if self.tenancy is not None:
            depths: dict[str, int] = {}
            for e in self.queue.entries():
                if e.tenant is not None:
                    depths[e.tenant] = depths.get(e.tenant, 0) + 1
            for name, bc in self.tenancy.brownouts.items():
                bc.evaluate(queue_depth=depths.get(name, 0))
            self.tenancy.evaluate()
            if self.metrics:
                slots_used, pages_used = self._tenant_residency()
                on_tc = getattr(self.metrics, "on_tenant_cycle", None)
                if on_tc is not None:
                    on_tc(self.tenancy.names(), depths=depths,
                          slots=slots_used, pages=pages_used)
        if (self._running or admitted or chunk_steps) and self.metrics:
            self.metrics.on_cycle(queue_depth=len(self.queue),
                                  occupancy=occupancy, tokens=emitted,
                                  prefill_s=prefill_stall_s)
            on_pages = getattr(self.metrics, "on_pages", None)
            stats_fn = getattr(self.engine, "page_stats", None)
            if on_pages is not None and stats_fn is not None:
                stats = stats_fn()
                if stats is not None:
                    on_pages(**stats)
            # compiles observed via jit cache-size deltas: after warmup
            # this total must never move (the no-recompile contract);
            # when it does, the registry counter says exactly when
            on_jit = getattr(self.metrics, "on_jit_cache", None)
            sizes = getattr(self.engine, "cache_sizes", None)
            if on_jit is not None and sizes is not None:
                on_jit(sum(sizes().values()))
        return done

    def _propose_drafts(self, got):
        """The speculative policy pass — pure host work in the
        device-idle gap before the next dispatch. Builds each running
        slot's FULL stream (prompt + bookkept tokens + this cycle's
        just-collected window, exactly the device state the next
        dispatch continues from), asks the drafter for k-token
        proposals, and returns (drafts [S, k], vlive [S],
        proposed [S]) for a verify dispatch — `proposed` marking the
        rows with a REAL proposal, so the engine's accept ledger
        scores speculation undiluted by ride-alongs — or None to fall
        back to the plain fused window, bit-identically, when:

        - no slot proposed (verifying nothing but bonus picks emits
          one token per slot — strictly worse than a W-token window
          on adversarially unpredictable traffic), or
        - ANY running slot lacks verify room (`engine.spec_room`): it
          would emit nothing while its neighbors speculate. Such a
          slot is within draft_k + 1 tokens of its cache edge — and
          admission bounds prompt + budget by t_max, so it is about
          to finish; the fallback is brief by construction.

        Slots that have room but no proposal still participate
        (vlive) with zeroed drafts: a verify row whose drafts all
        miss emits exactly the one token a window step would.

        Drafters advertising `propose_batched` (the learned
        models/draft_lm.DraftLM, ChainedDrafter wrapping one) get ONE
        call covering every running slot — the engine-resident path
        dispatches a single jitted propose program for the whole
        batch. Host drafters keep the per-slot scan. Either way, every
        proposal flows through the `_check_proposal` choke point, and
        the wall time of the whole drafting pass accrues to
        `propose_seconds` (the bench's draft-overhead key)."""
        eng = self.engine
        k = eng.draft_k
        # room check FIRST, across every slot: one slot without room
        # vetoes the whole verify, so drafting before knowing that
        # would throw completed history scans away
        for slot in self._running:
            if not eng.spec_room(slot):
                return None
        just = {id(e): t for e, t in got}
        drafts = np.zeros((eng.n_slots, k), np.int32)
        vlive = np.zeros(eng.n_slots, bool)
        proposed = np.zeros(eng.n_slots, bool)
        slots, hists = [], []
        for slot, e in self._running.items():
            vlive[slot] = True
            slots.append(slot)
            hists.append(np.concatenate([
                np.asarray(e.prompt, np.int64).ravel(),
                np.asarray(e.tokens + just.get(id(e), []), np.int64)]))
        t0 = self.clock()
        batched = getattr(self.drafter, "propose_batched", None)
        if batched is not None:
            props = batched(eng, slots, hists)
        else:
            props = {s: self.drafter.propose(h)
                     for s, h in zip(slots, hists)}
        dt = self.clock() - t0
        self.propose_seconds += dt
        if self.metrics:
            on_prop = getattr(self.metrics, "on_propose", None)
            if on_prop is not None:
                on_prop(dt)
        for slot in slots:
            prop = self._check_proposal(props.get(slot), k)
            if prop is None:
                continue
            drafts[slot] = prop
            proposed[slot] = True
        if not proposed.any():
            return None
        return drafts, vlive, proposed

    def _check_proposal(self, prop, k: int):
        """The ONE validation choke point between any `propose()`
        return and `begin_verify`: a malformed proposal raises a
        teaching error here, naming the drafter class and the
        contract, instead of flowing raw into the verify dispatch
        (where a float dtype jit-misses a new program, a 2-D shape
        trips an opaque reshape, and an out-of-vocab id is silently
        CLAMPED by the embedding gather — verified as a different
        token than proposed). None passes through: it is the
        contract's honest nothing-to-verify answer."""
        if prop is None:
            return None
        name = type(self.drafter).__name__
        contract = (f"the models/draft.py contract: propose(history) "
                    f"-> np.ndarray [k={k}] integer token ids in "
                    f"[0, {self.engine.vocab}), or None")
        arr = np.asarray(prop)
        if not np.issubdtype(arr.dtype, np.integer):
            raise ValueError(
                f"{name}.propose returned dtype {arr.dtype}: draft "
                f"tokens are ids the verify program compares against "
                f"the target's own integer picks — {contract}")
        if arr.ndim != 1:
            raise ValueError(
                f"{name}.propose returned shape {tuple(arr.shape)}: "
                f"the verify program takes ONE flat row of drafts per "
                f"slot — {contract}")
        if arr.shape[0] != k:
            raise ValueError(
                f"{name}.propose returned {arr.shape[0]} tokens; the "
                f"verify program is compiled at exactly k={k} — "
                f"{contract}")
        vocab = self.engine.vocab
        if (arr < 0).any() or (arr >= vocab).any():
            bad = arr[(arr < 0) | (arr >= vocab)][0]
            raise ValueError(
                f"{name}.propose returned out-of-vocab id {int(bad)} "
                f"(vocab is {vocab}): the verify embedding gather "
                f"would silently clamp it and accept-check a "
                f"DIFFERENT token than proposed — {contract}")
        return arr.astype(np.int32)

    def drain(self) -> list[Entry]:
        """Tick until every queued and running request has finished."""
        done = []
        while not self.idle():
            done.extend(self.tick())
        return done

    # -- drain-and-migrate (elastic scale-down / SIGTERM) ----------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Enter drain mode: every later submit refuses with the honest
        terminal ``shed`` status (stop admitting NEW work) while
        everything already accepted keeps ticking to completion —
        unless the caller moves it off first (`drain_pending` for
        queued work, `export_running` for mid-decode slots). Sticky for
        the scheduler's life: a draining replica never re-opens (the
        cluster's live→draining→dead state machine is forward-only)."""
        self._draining = True

    def running_ids(self) -> list[str]:
        """Request ids currently DECODING in a slot (not queued, not
        prefilling) — the candidates for mid-decode migration."""
        return [e.rid for e in self._running.values()]

    def export_running(self, rid: str):
        """Detach one RUNNING request for mid-decode migration:
        returns ``(entry, snapshot)`` — the live Entry itself (its
        emitted tokens, timestamps, spans and identity travel with it)
        plus the engine slot's packed device snapshot
        (`SlotEngine.export_slot`). The slot is released WITHOUT
        finishing the entry: no Result is produced and the journal
        deliberately records NOTHING here — the source journal's
        still-open submit covers the export→import gap, so a crash
        inside it replays the request from this WAL, bit-identically by
        the serial-parity contract. The caller (the cluster router)
        writes the terminal ``migrated`` finish only after the peer's
        import lands. Needs the engine dispatch-idle — `quiesce()`
        first."""
        for slot, e in self._running.items():
            if e.rid == rid:
                break
        else:
            raise ValueError(f"request {rid!r} is not running here — "
                             f"only decoding slots export "
                             f"(running_ids() lists them)")
        snap = self.engine.export_slot(slot)
        del self._running[slot]
        self.engine.release(slot)
        e.slot = None
        return e, snap

    def import_running(self, entry: "Entry", snap: dict) -> bool:
        """The peer half of a mid-decode migration: claim a free slot,
        re-insert the exported snapshot (`SlotEngine.import_slot`), and
        adopt the Entry as running — its decode resumes on this
        replica's next window, bit-identical to never having moved.
        Returns False (and consumes nothing) when this scheduler cannot
        take it right now (closed, itself draining, or no free slot) —
        the router keeps the snapshot and the source request intact.
        On success the adopted request is journaled as a NORMAL submit
        here, so a crash after this point recovers it from THIS
        replica's WAL."""
        if self._closed or self._draining:
            return False
        free = self.engine.free_slots()
        if not free:
            return False
        slot = free[0]
        self.engine.import_slot(slot, snap, tid=entry.tid)
        entry.slot = slot
        entry.status = "running"
        self._running[slot] = entry
        if self.journal is not None:
            deadline_rel = (None if entry.deadline is None else
                            max(entry.deadline - self.clock(), 0.0))
            self.journal.record_submit(entry, deadline_s=deadline_rel)
        return True

    def drain_pending(self) -> list[Entry]:
        """Pop everything accepted but NOT yet decoding — queued
        entries, retry-backoff waiters, and chunked prefills in
        progress (their partial chunks are discarded: re-prefilling on
        a peer re-derives the exact same stream, so restarting from the
        prompt is the bit-identical move) — for the router to re-place
        on surviving replicas. Each entry resets to pending with no
        slot and its lifecycle spans closed here (re-placement opens a
        fresh chain under the peer's scheduler). Running slots are
        `export_running`'s job."""
        out: list[Entry] = []
        while len(self.queue):
            out.append(self.queue.pop())
        out.extend(self._retrying)
        self._retrying = []
        for slot, e in list(self._prefilling.items()):
            self.engine.cancel_prefill(slot)
            del self._prefilling[slot]
            out.append(e)
        for e in out:
            e.status, e.slot = "pending", None
            e.tokens = []
            e.t_first = None
            if e.queue_span is not None:
                e.queue_span.close(migrated=True)
                e.queue_span = None
            if e.span is not None:
                e.span.close(status="migrated", reason="drain")
                e.span = None
        return out

    def pop_failed(self) -> list[Entry]:
        """Entries finalized by a tick that raised, since the last call
        — the caller's hook to turn them into Results after tick()
        re-raised. Holds both the engine-failure casualties
        (status="error") and entries the failed tick had already
        completed normally (EOS/budget/deadline), whose true statuses
        are preserved."""
        out, self._failed = self._failed, []
        return out

    def _finalize_window(self, got, finished, cancelled, t_now, now,
                         sink) -> int:
        """The per-window result bookkeeping (token extension, first-
        token stamps, finish statuses) — one implementation for the
        normal deferred pass AND the engine-failure salvage path, so
        the two cannot drift. Returns the emitted-token count."""
        emitted = 0
        progress = {} if self.journal is not None else None
        for e, toks in got:
            if toks and e.t_first is None:
                e.t_first = t_now
                trace.point(
                    "serve.first_token",
                    parent=(e.span.span_id if e.span is not None
                            else None),
                    rid=e.rid,
                    ttft_ms=round((t_now - e.t_submit) * 1e3, 3))
                if self.metrics:
                    self.metrics.on_first_token(e.rid,
                                                t_now - e.t_submit,
                                                tenant=e.tenant)
            e.tokens.extend(toks)
            emitted += len(toks)
            if progress is not None and toks:
                progress[e.rid] = len(e.tokens)
        if progress:
            # one batched (and journal-strided) record per cycle — the
            # per-slot-per-cycle write pattern was the armed clean
            # path's dominant cost (bench_serving_resilience)
            self.journal.record_progress(progress)
        for e in finished:
            e.status, e.t_done = "ok", t_now
            e.finish_reason = (
                "eos" if (e.eos_id is not None and e.tokens
                          and e.tokens[-1] == e.eos_id)
                else "budget")
            self._finish(e, sink)
        # deadline cancels finish AFTER the token extension above folded
        # in anything the flying window carried
        for e in cancelled:
            e.status, e.finish_reason = "timeout", "deadline"
            e.t_done = now
            self._finish(e, sink)
        return emitted

    def _abort_running(self, exc: Exception) -> None:
        """Engine failure cleanup: mark every in-flight entry failed and
        release its slot so the engine/queue are not wedged when the
        caller survives the re-raised error."""
        now = self.clock()
        detail = f"{type(exc).__name__}: {exc}"
        for slot, e in list(self._running.items()):
            try:
                self.engine.release(slot)
            except Exception:  # noqa: S110 — engine already failed;
                pass           # cleanup must reach every slot regardless
            e.status, e.finish_reason = "error", "error"
            e.error, e.t_done = detail, now
            self._finish(e, self._failed)
        self._running.clear()
        for slot, e in list(self._prefilling.items()):
            try:
                self.engine.cancel_prefill(slot)
            except Exception:  # noqa: S110 — same: reach every slot
                pass
            e.status, e.finish_reason = "error", "error"
            e.error, e.t_done = detail, now
            self._finish(e, self._failed)
        self._prefilling.clear()
        # a window the failed engine still considers in flight would
        # wedge idle()/collect(); the device work is lost either way
        self.engine.abort_window()

    def _finish(self, e: Entry, done: list[Entry]) -> None:
        done.append(e)
        if self.journal is not None:
            self.journal.record_finish(e.rid, e.status,
                                       reason=e.finish_reason)
        # close the lifecycle chain: the queued child first (a no-op if
        # admission already closed it — `expired` only lands on entries
        # that died IN the queue; Span.close applies attrs on the first
        # close only), then the whole serve.request span with the
        # terminal state
        if e.queue_span is not None:
            e.queue_span.close(expired=True)
        if e.span is not None:
            e.span.close(status=e.status, reason=e.finish_reason,
                         tokens=len(e.tokens))
        if self.metrics:
            ttft = (e.t_first - e.t_submit
                    if e.t_first is not None else None)
            decode_s = (e.t_done - e.t_first
                        if e.t_first is not None and e.t_done is not None
                        else 0.0)
            self.metrics.on_finish(
                e.rid, n_tokens=len(e.tokens), ttft_s=ttft,
                decode_s=decode_s,
                reason=(e.finish_reason or e.status), t=e.t_done,
                tenant=e.tenant)
