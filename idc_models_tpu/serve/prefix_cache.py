"""Radix prefix cache: KV snapshots at chunk boundaries, reused across
requests that share a token prefix.

Two requests carrying the same system prompt recompute identical K/V
state from scratch under plain admission; with chunked prefill
(models/lm.py `prefill_chunk`) every completed chunk boundary is a
natural snapshot point — the caches at boundary k*C are a pure function
of tokens[:k*C]. This module stores those snapshots in a radix tree
whose edges are CHUNK-sized token tuples (vLLM/SGLang's prefix reuse,
quantized to the chunk grid), and `SlotEngine.start_prefill` asks it for
the longest cached prefix before prefilling only the suffix.

Two storage flavors share ONE radix/LRU core (`_RadixPrefixBase`: the
chunk grid, the longest-prefix walk, never-hit-first LRU eviction,
pruning, counters, the serve_prefix_* instruments and summary) so the
policy cannot drift between them:

- `PrefixCache` (contiguous engines) stores ARRAY snapshots under a
  byte budget. A HIT hands back deep COPIES of the stored arrays — the
  chunk program donates its input caches, so the stored master must
  never enter a donating dispatch; a hit is bit-identical to
  recomputing the prefix because the stored snapshot IS the chunk
  program's output for those tokens. Snapshots are device-resident by
  default; `host=True` stores numpy copies, trading hit latency for
  HBM.
- `PagedPrefixCache` (paged engines, ISSUE 11) stores REFCOUNTED PAGE
  LISTS under a page budget — zero copies; see its docstring for the
  sharing invariant.

In both flavors eviction only ever causes EXTRA prefill work: a lookup
after evict misses and the engine re-prefills from scratch — stale
state is structurally impossible because snapshots are keyed by the
full token prefix and never mutated in place (gated by
tests/test_prefix_cache.py and tests/test_paged_kv.py). Counters feed
`ServingMetrics.summary()` and stream as `serve_prefix_*` events when a
logger is attached.
"""

from __future__ import annotations

import numpy as np


def _tree_bytes(tree) -> int:
    import jax

    return sum(int(a.nbytes) for a in jax.tree.leaves(tree))


def _copy_tree(tree, host: bool):
    # host=True: genuine numpy COPIES (np.asarray would alias an
    # already-numpy master — the contract is that nothing handed out or
    # taken in shares buffers with the stored snapshot)
    import jax
    import jax.numpy as jnp

    if host:
        return jax.tree.map(lambda a: np.array(a, copy=True), tree)
    return jax.tree.map(lambda a: jnp.array(a, copy=True), tree)


class _Node:
    __slots__ = ("children", "snapshot", "nbytes", "stamp", "parent",
                 "edge", "hit_count")

    def __init__(self, parent=None, edge=None):
        self.children: dict[tuple, _Node] = {}
        self.snapshot = None          # storage-flavor payload or None
        self.nbytes = 0
        self.stamp = 0                # LRU clock at last touch
        self.parent = parent
        self.edge = edge              # the chunk tuple leading here
        self.hit_count = 0            # lookups served from this node


class _RadixPrefixBase:
    """The storage-agnostic radix/LRU core both cache flavors run on:
    chunk-grid tokenization, the longest-cached-prefix walk with
    hit/miss bookkeeping, radix insert-or-dedupe, never-hit-first LRU
    victim selection, pruning, pause_writes, and the serve_prefix_*
    instruments/counters/summary. Subclasses own only what a snapshot
    IS (arrays vs page refs), how it is handed out, and the budget it
    lives under — `_release_snapshot(node)` is the one storage hook
    eviction calls."""

    def __init__(self, chunk: int, *, logger=None, registry=None):
        if chunk < 1:
            raise ValueError(f"need chunk >= 1, got {chunk}")
        self.chunk = int(chunk)
        self.logger = logger
        from idc_models_tpu.observe import metrics_registry as mreg

        reg = registry if registry is not None else mreg.REGISTRY
        self._m_lookups = reg.counter(
            "serve_prefix_lookups_total",
            "prefix-cache lookups by outcome", labels=("result",))
        self._m_evictions = reg.counter(
            "serve_prefix_evictions_total", "LRU snapshot evictions")
        self._m_bytes = reg.gauge(
            "serve_prefix_cache_bytes", "bytes of stored snapshots")
        # brownout hook: while True, insert() stores nothing (lookups
        # still serve hits) — snapshot work is the first thing a
        # degrading server sheds
        self.writes_paused = False
        self._root = _Node()
        self._clock = 0
        self.n_snapshots = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.hit_tokens = 0           # prefix tokens served from cache
        self.lookup_tokens = 0        # prompt tokens seen by lookup

    # -- the chunk grid ---------------------------------------------------

    def _chunks(self, tokens) -> list[tuple]:
        # one C-level tolist() (not a python int() per element): insert
        # runs once per completed chunk boundary, so an admission pays
        # O(P) host tokenization per boundary — with this constant it
        # is dominated by the device chunk dispatch it accompanies
        toks = np.asarray(tokens).reshape(-1).tolist()
        n_full = len(toks) // self.chunk
        return [tuple(toks[i * self.chunk:(i + 1) * self.chunk])
                for i in range(n_full)]

    def _check_boundary(self, tokens) -> np.ndarray:
        toks = np.asarray(tokens).reshape(-1)
        if toks.size == 0 or toks.size % self.chunk:
            raise ValueError(
                f"prefix length {toks.size} is not a multiple of the "
                f"chunk {self.chunk} — snapshots live on chunk "
                f"boundaries only")
        return toks

    # -- lookup / insert plumbing -----------------------------------------

    def _lookup_node(self, tokens):
        """Longest cached prefix on the chunk grid, with the hit/miss
        bookkeeping applied: ``(node, start)`` or ``(None, 0)``."""
        node = self._root
        best, best_depth = None, 0
        depth = 0
        for edge in self._chunks(tokens):
            node = node.children.get(edge)
            if node is None:
                break
            depth += 1
            if node.snapshot is not None:
                best, best_depth = node, depth
        self.lookup_tokens += int(np.asarray(tokens).size)
        if best is None:
            self.misses += 1
            self._m_lookups.inc(result="miss")
            self._log(event="serve_prefix_miss",
                      prompt_tokens=int(np.asarray(tokens).size))
            return None, 0
        self._clock += 1
        best.stamp = self._clock
        best.hit_count += 1
        self.hits += 1
        self._m_lookups.inc(result="hit")
        start = best_depth * self.chunk
        self.hit_tokens += start
        self._log(event="serve_prefix_hit", prefix_tokens=start,
                  prompt_tokens=int(np.asarray(tokens).size))
        return best, start

    def _insert_node(self, toks):
        """Create-or-walk the radix path for `toks` and LRU-touch it;
        returns the node, or None when a snapshot already sits there
        (the existing entry keeps answering — dedupe)."""
        node = self._root
        for edge in self._chunks(toks):
            node = node.children.setdefault(edge, _Node(node, edge))
        self._clock += 1
        node.stamp = self._clock
        return None if node.snapshot is not None else node

    # -- eviction ---------------------------------------------------------

    def _walk(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.snapshot is not None:
                yield n

    def _evict_lru(self, protect=None, victim=None) -> int:
        """Evict ONE snapshot — never-hit (speculative) ones before
        hit-proven ones, LRU within each class: a burst of long
        unique-tail prompts then churns its own useless snapshots
        instead of flushing the shared system-prefix state the cache
        exists for. `victim` overrides the selection (the paged
        flavor's reclaim ranks by freeable pages first). Returns
        whatever `_release_snapshot` reports freed (pool pages for the
        paged flavor, 0 for arrays)."""
        if victim is None:
            victims = [n for n in self._walk() if n is not protect]
            if not victims:
                return 0
            victim = min(victims,
                         key=lambda n: (min(n.hit_count, 1), n.stamp))
        v = victim
        freed_bytes = v.nbytes
        freed = self._release_snapshot(v)
        v.snapshot, v.nbytes = None, 0
        self.n_snapshots -= 1
        self.evictions += 1
        self._m_evictions.inc()
        self._m_bytes.set(self.nbytes)
        self._log(event="serve_prefix_evict", freed_bytes=freed_bytes)
        self._prune(v)
        return freed

    def _release_snapshot(self, node) -> int:
        raise NotImplementedError

    def _prune(self, node) -> None:
        while (node is not self._root and node.snapshot is None
               and not node.children and node.parent is not None):
            del node.parent.children[node.edge]
            node = node.parent

    def pause_writes(self, paused: bool) -> None:
        """Brownout stage-1 side effect (serve/brownout.py): toggle
        snapshot storage. Reads are never paused — a warm cache keeps
        serving hits through the brownout."""
        self.writes_paused = bool(paused)

    # -- observability ----------------------------------------------------

    def hit_rate(self) -> float | None:
        total = self.hits + self.misses
        return None if total == 0 else self.hits / total

    def token_hit_rate(self) -> float | None:
        return (None if self.lookup_tokens == 0
                else self.hit_tokens / self.lookup_tokens)

    def summary(self) -> dict:
        """The `serve_prefix_*` fields merged into the serving rollup —
        identical keys for both storage flavors."""
        return {
            "serve_prefix_hits": self.hits,
            "serve_prefix_misses": self.misses,
            "serve_prefix_evictions": self.evictions,
            "serve_prefix_hit_rate": (
                None if self.hit_rate() is None
                else round(self.hit_rate(), 4)),
            "serve_prefix_token_hit_rate": (
                None if self.token_hit_rate() is None
                else round(self.token_hit_rate(), 4)),
            "serve_prefix_bytes": self.nbytes,
            "serve_prefix_snapshots": self.n_snapshots,
        }

    def _log(self, **record) -> None:
        if self.logger is not None:
            self.logger.log(**record)


class PrefixCache(_RadixPrefixBase):
    """Radix tree of chunk-boundary ARRAY snapshots with an LRU byte
    budget — the contiguous engines' flavor.

    `chunk` fixes the snapshot grid: node depth d holds the state after
    tokens[:d*chunk]. `max_bytes` bounds the summed nbytes of stored
    snapshots (0 disables storage entirely — lookups always miss)."""

    def __init__(self, chunk: int, max_bytes: int, *,
                 host: bool = False, logger=None, registry=None,
                 shared=None):
        if max_bytes < 0:
            raise ValueError(f"need max_bytes >= 0, got {max_bytes}")
        super().__init__(chunk, logger=logger, registry=registry)
        self.max_bytes = int(max_bytes)
        self.host = bool(host)
        self._pack = None             # (caches, n_tokens) -> stored tree
        self._unpack = None           # stored tree -> caller tree
        self.nbytes = 0
        # shared: a cluster-wide PrefixRegistry
        # (serve/cluster/registry.py). Inserts PUBLISH each boundary
        # snapshot (as host numpy packed trees — device-agnostic, so
        # any replica's engine can re-place them under its own mesh),
        # and a lookup whose local walk falls short ADOPTS the
        # registry's longer prefix: a hot system prompt prefilled once
        # on any replica is reused everywhere. Array snapshots only —
        # the paged flavor's page ids name physical pages of ONE
        # engine's pool and cannot cross replicas.
        if shared is not None and shared.chunk != self.chunk:
            raise ValueError(
                f"shared registry chunk {shared.chunk} != cache chunk "
                f"{self.chunk} — snapshots live on one chunk grid")
        self.shared = shared
        self.shared_hits = 0
        self.shared_hit_tokens = 0

    def set_packer(self, pack, unpack) -> None:
        """Install a storage transform: ``pack(caches, n_tokens)`` maps
        the live caches to what is STORED (the engine slices rows to
        the prefix length — positions past it are zeros by
        construction, so storing them buys nothing and a budget sized
        for N prefixes would otherwise hold ~N*prefix/t_max of them);
        ``unpack(stored)`` maps a stored tree back to what lookup hands
        out (pad + re-place under the ring sharding — bit-identical
        resume, and `unpack` must return FRESH arrays, never aliases of
        the stored master). Identity when unset."""
        self._pack, self._unpack = pack, unpack

    # -- lookup / insert --------------------------------------------------

    def lookup(self, tokens):
        """Longest cached prefix of `tokens` on the chunk grid.

        Returns ``(start, caches, logits)``: `start` tokens are already
        in the returned caches (0, None, None on a miss). The arrays are
        fresh copies, safe to feed a donating chunk program; the stored
        master is untouched."""
        best, start = self._lookup_node(tokens)
        # the shared registry may know a LONGER prefix (another replica
        # prefilled it): adopt it — warm the local radix so the next
        # lookup hits without the registry hop (a no-op while brownout
        # pauses writes), and hand out fresh unpacked arrays exactly
        # like a local hit. Gated on the pure-read covered() first:
        # once the local cache covers the prefix, admissions must not
        # pay the registry's snapshot copy (or skew its hit stats)
        # for data they would throw away.
        if (self.shared is not None
                and self.shared.covered(tokens) > start):
            s2, packed, logits2 = self.shared.lookup(tokens)
            if s2 > start:
                self.shared_hits += 1
                self.shared_hit_tokens += s2 - start
                self._log(event="serve_prefix_shared_hit",
                          prefix_tokens=s2,
                          prompt_tokens=int(np.asarray(tokens).size))
                self.insert(np.asarray(tokens).reshape(-1)[:s2],
                            packed, logits2)
                caches = (self._unpack(packed)
                          if self._unpack is not None
                          else _copy_tree(packed, self.host))
                return s2, caches, _copy_tree(logits2, self.host)
        if best is None:
            return 0, None, None
        caches, logits = best.snapshot
        # BOTH halves leave as fresh arrays — logits too, even though
        # today's call sites never donate or mutate them: the stored
        # master must survive any future caller, not just the current
        # ones. (unpack allocates fresh padded arrays by contract, so
        # it subsumes the copy.)
        caches = (self._unpack(caches) if self._unpack is not None
                  else _copy_tree(caches, self.host))
        return start, caches, _copy_tree(logits, self.host)

    def insert(self, tokens, caches, logits) -> bool:
        """Store the state after `tokens` (length must sit on the chunk
        grid). Copies the arrays; returns False (and stores nothing)
        when the snapshot alone exceeds the whole budget or the key is
        already present (the existing entry is LRU-touched)."""
        toks = self._check_boundary(tokens)
        if self.writes_paused:
            return False
        node = self._insert_node(toks)
        if node is None:
            return False
        if self._pack is not None:
            caches = self._pack(caches, int(toks.size))
        snap = (_copy_tree(caches, self.host),
                _copy_tree(logits, self.host))
        size = _tree_bytes(snap)
        if size > self.max_bytes:
            self._prune(node)
            return False
        node.snapshot = snap
        node.nbytes = size
        self.nbytes += size
        self.n_snapshots += 1
        while self.nbytes > self.max_bytes and self.n_snapshots > 1:
            self._evict_lru(protect=node)
        self._m_bytes.set(self.nbytes)
        # publish to the cluster registry (it deep-copies to host numpy
        # and dedupes by key, so republishing an adopted prefix is a
        # no-op) — local eviction above never un-publishes: the
        # registry has its own budget and LRU
        if self.shared is not None:
            self.shared.publish(toks, snap[0], snap[1])
        return True

    def _release_snapshot(self, node) -> int:
        self.nbytes -= node.nbytes
        return 0

    def summary(self) -> dict:
        out = super().summary()
        if self.shared is not None:
            # additive keys, present only when a cluster registry is
            # attached — single-replica summaries are unchanged
            out["serve_prefix_shared_hits"] = self.shared_hits
            out["serve_prefix_shared_hit_tokens"] = self.shared_hit_tokens
        return out

    def clear(self) -> None:
        self._root = _Node()
        self.nbytes = 0
        self.n_snapshots = 0


class PagedPrefixCache(_RadixPrefixBase):
    """Radix prefix cache for the PAGED engine (ISSUE 11): a snapshot
    is a LIST OF POOL PAGE IDS plus a copy of the boundary logits —
    never a copy of the K/V itself.

    The sharing story that makes snapshots free: chunk boundaries land
    on the page grid (page_size | chunk, enforced by the engine), so
    the pages covering a completed boundary are FULLY WRITTEN and — by
    the engine's write discipline (chunks splice [start, p_end),
    decode appends at >= p_len) — never written again. A snapshot
    therefore just takes a refcount on the prefilling slot's own pages
    (`PageAllocator.retain`), and a hit hands the page ids to the new
    slot, which retains them too: N requests sharing a system prompt
    hold ONE physical copy of its K/V. "Copy-on-write" never triggers
    because no write ever targets a shared page — the alignment
    invariant is the whole mechanism.

    Eviction is the base LRU under a budget counted in PAGES; evicting
    a snapshot drops its refs, and a page returns to the free list
    only when no slot still shares it — eviction can only ever cost
    re-prefill, exactly like the array flavor's contract. `reclaim(n)`
    is the allocator-pressure hook: admission and mid-decode growth
    evict snapshots to free pages before declaring exhaustion."""

    is_paged = True

    def __init__(self, chunk: int, max_pages: int | None = None, *,
                 budget_mb: float | None = None, logger=None,
                 registry=None):
        if (max_pages is None) == (budget_mb is None):
            raise ValueError("pass exactly one of max_pages (a page "
                             "budget) or budget_mb (resolved to pages "
                             "when the engine binds its allocator)")
        if max_pages is not None and max_pages < 0:
            raise ValueError(f"need max_pages >= 0, got {max_pages}")
        if budget_mb is not None and budget_mb < 0:
            raise ValueError(f"need budget_mb >= 0, got {budget_mb}")
        super().__init__(chunk, logger=logger, registry=registry)
        self.max_pages = None if max_pages is None else int(max_pages)
        self._budget_mb = budget_mb
        self._alloc = None
        self._page_bytes = 0
        # distinct pages this cache references -> snapshot refcount
        # (a page shared by k snapshots counts ONCE against the page
        # budget; the allocator holds k refs for it)
        self._page_refs: dict[int, int] = {}

    def bind(self, allocator, page_bytes: int) -> None:
        """Attach the engine's allocator (refcount authority) and the
        page byte size; resolves a budget_mb construction into pages.

        Rebinding a POPULATED cache to a different allocator (the
        warm-restart pattern: a server rebuilt after a crash reuses
        the dead engine's cache object) DROPS every snapshot first:
        unlike the array flavor's snapshots, page ids name physical
        pages of the pool that died with the old engine — carrying
        them across would retain/release pages the new allocator
        hands to unrelated live requests (silent cross-request
        corruption). The rebuilt cache starts cold and re-warms."""
        if self._alloc is not None and allocator is not self._alloc:
            self._root = _Node()
            self._page_refs.clear()
            self.n_snapshots = 0
        self._alloc = allocator
        self._page_bytes = int(page_bytes)
        if self.max_pages is None:
            self.max_pages = int(self._budget_mb * 1024 * 1024
                                 // max(page_bytes, 1))

    # -- accounting -------------------------------------------------------

    def cached_pages(self) -> int:
        return len(self._page_refs)

    def reclaimable_pages(self) -> int:
        """Pages that evicting EVERY snapshot would actually free:
        those whose allocator refcount is entirely cache-held (a page
        a live slot still shares frees nothing). The admission gate
        checks this before evicting, so a hopeless query cannot
        destroy the cache for zero admission benefit."""
        if self._alloc is None:
            return 0
        return sum(1 for p, refs in self._page_refs.items()
                   if self._alloc.refcount(p) == refs)

    @property
    def nbytes(self) -> int:
        return self.cached_pages() * self._page_bytes

    # -- lookup / insert --------------------------------------------------

    def lookup(self, tokens):
        """Longest cached prefix of `tokens` on the chunk grid:
        ``(start, page_ids, logits)`` — `start` tokens already live in
        the returned pages (0, None, None on a miss). The CALLER
        retains the pages for its own lifetime; the ids themselves are
        a fresh list and the logits a fresh copy."""
        import jax.numpy as jnp

        best, start = self._lookup_node(tokens)
        if best is None:
            return 0, None, None
        pages, logits = best.snapshot
        return start, list(pages), jnp.array(logits, copy=True)

    def insert(self, tokens, pages, logits) -> bool:
        """Snapshot the state after `tokens` as the page ids covering
        them (length must sit on the chunk grid and the pages must
        exactly cover it). Takes cache-owned refcounts — zero copies.
        Returns False (nothing stored) when writes are paused, the key
        exists, or the page budget cannot fit it even after
        eviction."""
        import jax.numpy as jnp

        if self._alloc is None:
            raise RuntimeError("PagedPrefixCache.bind(allocator, "
                               "page_bytes) must run before insert — "
                               "the engine does this at construction")
        toks = self._check_boundary(tokens)
        if self.writes_paused:
            return False
        pages = [int(p) for p in pages]
        node = self._insert_node(toks)
        if node is None:
            return False
        new_distinct = sum(1 for p in pages
                           if p not in self._page_refs)
        while (self.cached_pages() + new_distinct > self.max_pages
               and self.n_snapshots > 0):
            before = self.n_snapshots
            self._evict_lru(protect=node)
            if self.n_snapshots == before:      # nothing evictable
                break
            new_distinct = sum(1 for p in pages
                               if p not in self._page_refs)
        if self.cached_pages() + new_distinct > self.max_pages:
            self._prune(node)
            return False
        self._alloc.retain(pages)
        for p in pages:
            self._page_refs[p] = self._page_refs.get(p, 0) + 1
        node.snapshot = (tuple(pages), jnp.array(logits, copy=True))
        node.nbytes = len(pages) * self._page_bytes
        self.n_snapshots += 1
        self._m_bytes.set(self.nbytes)
        return True

    # -- eviction / reclaim -----------------------------------------------

    def _release_snapshot(self, node) -> int:
        pages = node.snapshot[0]
        for p in pages:
            self._page_refs[p] -= 1
            if self._page_refs[p] == 0:
                del self._page_refs[p]
        return self._alloc.release(pages)

    def reclaim(self, n_pages: int) -> int:
        """Free at least `n_pages` pool pages by evicting snapshots
        (or as many as evictions can free); returns the count actually
        freed. The allocator-pressure hook admission and decode-growth
        call before declaring page exhaustion.

        Victim ranking puts FREEABILITY before the LRU policy: a
        snapshot whose eviction frees pages NOW (it holds the last
        reference) beats one that merely unblocks a later eviction
        (pages shared with other snapshots), and snapshots pinned
        entirely by LIVE SLOTS are never evicted here at all — they
        free nothing this reclaim and destroying a hit-proven shared
        system prefix for zero pages is the waste the admission gate's
        reclaimable check exists to prevent."""
        freed = 0
        while freed < n_pages and self.n_snapshots > 0:
            best, best_key = None, None
            for node in self._walk():
                pages = node.snapshot[0]
                frees = sum(1 for p in pages
                            if self._alloc.refcount(p) == 1)
                # progress = some page would free once its OTHER
                # cache-held refs go too (chained boundary snapshots)
                progress = any(self._alloc.refcount(p)
                               == self._page_refs[p] for p in pages)
                if not frees and not progress:
                    continue                   # slot-pinned: keep it
                key = (frees == 0, min(node.hit_count, 1), node.stamp)
                if best is None or key < best_key:
                    best, best_key = node, key
            if best is None:
                break
            freed += self._evict_lru(victim=best)
        return freed
