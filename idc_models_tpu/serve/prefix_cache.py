"""Radix prefix cache: KV snapshots at chunk boundaries, reused across
requests that share a token prefix.

Two requests carrying the same system prompt recompute identical K/V
state from scratch under plain admission; with chunked prefill
(models/lm.py `prefill_chunk`) every completed chunk boundary is a
natural snapshot point — the caches at boundary k*C are a pure function
of tokens[:k*C]. This module stores those snapshots in a radix tree
whose edges are CHUNK-sized token tuples (vLLM/SGLang's prefix reuse,
quantized to the chunk grid), and `SlotEngine.start_prefill` asks it for
the longest cached prefix before prefilling only the suffix.

Correctness contract (gated by tests/test_prefix_cache.py):

- a HIT hands back deep COPIES of the stored arrays — the chunk program
  donates its input caches, so the stored master must never enter a
  donating dispatch;
- a hit is bit-identical to recomputing the prefix, because the stored
  snapshot IS the chunk program's output for those tokens (same
  executables, same values — nothing approximate is stored);
- eviction (LRU under `max_bytes`) only ever causes EXTRA prefill work:
  a lookup after evict misses and the engine re-prefills from scratch —
  stale state is structurally impossible because snapshots are keyed by
  the full token prefix and never mutated in place.

Snapshots are device-resident by default (HBM — a hit costs one device
copy per array, no host round-trip); `host=True` stores numpy copies
instead, trading hit latency for HBM (the budget then bounds host RSS).
Counters (`hits`/`misses`/`evictions`/token-weighted hit rate) feed
`ServingMetrics.summary()` and stream as `serve_prefix_*` events when a
logger is attached — new event types only, the existing serve.jsonl
record schema is untouched.
"""

from __future__ import annotations

import numpy as np


def _tree_bytes(tree) -> int:
    import jax

    return sum(int(a.nbytes) for a in jax.tree.leaves(tree))


def _copy_tree(tree, host: bool):
    # host=True: genuine numpy COPIES (np.asarray would alias an
    # already-numpy master — the contract is that nothing handed out or
    # taken in shares buffers with the stored snapshot)
    import jax
    import jax.numpy as jnp

    if host:
        return jax.tree.map(lambda a: np.array(a, copy=True), tree)
    return jax.tree.map(lambda a: jnp.array(a, copy=True), tree)


class _Node:
    __slots__ = ("children", "snapshot", "nbytes", "stamp", "parent",
                 "edge", "hit_count")

    def __init__(self, parent=None, edge=None):
        self.children: dict[tuple, _Node] = {}
        self.snapshot = None          # (caches, logits) or None
        self.nbytes = 0
        self.stamp = 0                # LRU clock at last touch
        self.parent = parent
        self.edge = edge              # the chunk tuple leading here
        self.hit_count = 0            # lookups served from this node


class PrefixCache:
    """Radix tree of chunk-boundary KV snapshots with an LRU byte budget.

    `chunk` fixes the snapshot grid: node depth d holds the state after
    tokens[:d*chunk]. `max_bytes` bounds the summed nbytes of stored
    snapshots (0 disables storage entirely — lookups always miss)."""

    def __init__(self, chunk: int, max_bytes: int, *,
                 host: bool = False, logger=None, registry=None):
        if chunk < 1:
            raise ValueError(f"need chunk >= 1, got {chunk}")
        if max_bytes < 0:
            raise ValueError(f"need max_bytes >= 0, got {max_bytes}")
        self.chunk = int(chunk)
        self.max_bytes = int(max_bytes)
        self.host = bool(host)
        self.logger = logger
        # registry mirrors of the instance counters below — additive
        # (the jsonl events and summary() fields are unchanged);
        # registry=None uses the process-wide default, same knob as
        # ServingMetrics so tests can isolate instruments
        from idc_models_tpu.observe import metrics_registry as mreg

        reg = registry if registry is not None else mreg.REGISTRY
        self._m_lookups = reg.counter(
            "serve_prefix_lookups_total",
            "prefix-cache lookups by outcome", labels=("result",))
        self._m_evictions = reg.counter(
            "serve_prefix_evictions_total", "LRU snapshot evictions")
        self._m_bytes = reg.gauge(
            "serve_prefix_cache_bytes", "bytes of stored snapshots")
        self._pack = None             # (caches, n_tokens) -> stored tree
        self._unpack = None           # stored tree -> caller tree
        # brownout hook: while True, insert() stores nothing (lookups
        # still serve hits) — snapshot copies + eviction churn are the
        # first work a degrading server sheds
        self.writes_paused = False
        self._root = _Node()
        self._clock = 0
        self.nbytes = 0
        self.n_snapshots = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.hit_tokens = 0           # prefix tokens served from cache
        self.lookup_tokens = 0        # prompt tokens seen by lookup

    def set_packer(self, pack, unpack) -> None:
        """Install a storage transform: ``pack(caches, n_tokens)`` maps
        the live caches to what is STORED (the engine slices rows to
        the prefix length — positions past it are zeros by
        construction, so storing them buys nothing and a budget sized
        for N prefixes would otherwise hold ~N*prefix/t_max of them);
        ``unpack(stored)`` maps a stored tree back to what lookup hands
        out (pad + re-place under the ring sharding — bit-identical
        resume, and `unpack` must return FRESH arrays, never aliases of
        the stored master). Identity when unset."""
        self._pack, self._unpack = pack, unpack

    # -- lookup / insert --------------------------------------------------

    def _chunks(self, tokens) -> list[tuple]:
        # one C-level tolist() (not a python int() per element): insert
        # runs once per completed chunk boundary, so an admission pays
        # O(P) host tokenization per boundary — with this constant it
        # is dominated by the device chunk dispatch it accompanies
        toks = np.asarray(tokens).reshape(-1).tolist()
        n_full = len(toks) // self.chunk
        return [tuple(toks[i * self.chunk:(i + 1) * self.chunk])
                for i in range(n_full)]

    def lookup(self, tokens):
        """Longest cached prefix of `tokens` on the chunk grid.

        Returns ``(start, caches, logits)``: `start` tokens are already
        in the returned caches (0, None, None on a miss). The arrays are
        fresh copies, safe to feed a donating chunk program; the stored
        master is untouched."""
        node, depth = self._root, 0
        best, best_depth = None, 0
        for edge in self._chunks(tokens):
            node = node.children.get(edge)
            if node is None:
                break
            depth += 1
            if node.snapshot is not None:
                best, best_depth = node, depth
        self.lookup_tokens += int(np.asarray(tokens).size)
        if best is None:
            self.misses += 1
            self._m_lookups.inc(result="miss")
            self._log(event="serve_prefix_miss",
                      prompt_tokens=int(np.asarray(tokens).size))
            return 0, None, None
        self._clock += 1
        best.stamp = self._clock
        best.hit_count += 1
        self.hits += 1
        self._m_lookups.inc(result="hit")
        start = best_depth * self.chunk
        self.hit_tokens += start
        self._log(event="serve_prefix_hit", prefix_tokens=start,
                  prompt_tokens=int(np.asarray(tokens).size))
        caches, logits = best.snapshot
        # BOTH halves leave as fresh arrays — logits too, even though
        # today's call sites never donate or mutate them: the stored
        # master must survive any future caller, not just the current
        # ones. (unpack allocates fresh padded arrays by contract, so
        # it subsumes the copy.)
        caches = (self._unpack(caches) if self._unpack is not None
                  else _copy_tree(caches, self.host))
        return start, caches, _copy_tree(logits, self.host)

    def insert(self, tokens, caches, logits) -> bool:
        """Store the state after `tokens` (length must sit on the chunk
        grid). Copies the arrays; returns False (and stores nothing)
        when the snapshot alone exceeds the whole budget or the key is
        already present (the existing entry is LRU-touched)."""
        toks = np.asarray(tokens).reshape(-1)
        if toks.size == 0 or toks.size % self.chunk:
            raise ValueError(
                f"prefix length {toks.size} is not a multiple of the "
                f"chunk {self.chunk} — snapshots live on chunk "
                f"boundaries only")
        if self.writes_paused:
            return False
        node = self._root
        for edge in self._chunks(toks):
            node = node.children.setdefault(edge, _Node(node, edge))
        self._clock += 1
        node.stamp = self._clock
        if node.snapshot is not None:
            return False
        if self._pack is not None:
            caches = self._pack(caches, int(toks.size))
        snap = (_copy_tree(caches, self.host),
                _copy_tree(logits, self.host))
        size = _tree_bytes(snap)
        if size > self.max_bytes:
            self._prune(node)
            return False
        node.snapshot = snap
        node.nbytes = size
        self.nbytes += size
        self.n_snapshots += 1
        while self.nbytes > self.max_bytes and self.n_snapshots > 1:
            self._evict_lru(protect=node)
        self._m_bytes.set(self.nbytes)
        return True

    # -- eviction ---------------------------------------------------------

    def _walk(self):
        stack = [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.snapshot is not None:
                yield n

    def _evict_lru(self, protect=None) -> None:
        # every chunk boundary of every prompt is snapshotted
        # speculatively; only some ever serve a hit. Evict never-hit
        # (speculative) snapshots before hit-proven ones, LRU within
        # each class — a burst of long unique-tail prompts then churns
        # its own useless snapshots instead of flushing the shared
        # system-prefix state the cache exists for.
        victims = [n for n in self._walk() if n is not protect]
        if not victims:
            return
        v = min(victims, key=lambda n: (min(n.hit_count, 1), n.stamp))
        self.nbytes -= v.nbytes
        self.n_snapshots -= 1
        self.evictions += 1
        self._m_evictions.inc()
        self._m_bytes.set(self.nbytes)
        self._log(event="serve_prefix_evict", freed_bytes=v.nbytes)
        v.snapshot, v.nbytes = None, 0
        self._prune(v)

    def _prune(self, node) -> None:
        while (node is not self._root and node.snapshot is None
               and not node.children and node.parent is not None):
            del node.parent.children[node.edge]
            node = node.parent

    def pause_writes(self, paused: bool) -> None:
        """Brownout stage-1 side effect (serve/brownout.py): toggle
        snapshot storage. Reads are never paused — a warm cache keeps
        serving hits through the brownout."""
        self.writes_paused = bool(paused)

    def clear(self) -> None:
        self._root = _Node()
        self.nbytes = 0
        self.n_snapshots = 0

    # -- observability ----------------------------------------------------

    def hit_rate(self) -> float | None:
        total = self.hits + self.misses
        return None if total == 0 else self.hits / total

    def token_hit_rate(self) -> float | None:
        return (None if self.lookup_tokens == 0
                else self.hit_tokens / self.lookup_tokens)

    def summary(self) -> dict:
        """The `serve_prefix_*` fields merged into the serving rollup."""
        return {
            "serve_prefix_hits": self.hits,
            "serve_prefix_misses": self.misses,
            "serve_prefix_evictions": self.evictions,
            "serve_prefix_hit_rate": (
                None if self.hit_rate() is None
                else round(self.hit_rate(), 4)),
            "serve_prefix_token_hit_rate": (
                None if self.token_hit_rate() is None
                else round(self.token_hit_rate(), 4)),
            "serve_prefix_bytes": self.nbytes,
            "serve_prefix_snapshots": self.n_snapshots,
        }

    def _log(self, **record) -> None:
        if self.logger is not None:
            self.logger.log(**record)
