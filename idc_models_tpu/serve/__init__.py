from idc_models_tpu.serve.api import (  # noqa: F401
    LMServer, Request, Result, load_trace, poisson_trace, save_trace,
)
from idc_models_tpu.serve.engine import SlotEngine  # noqa: F401
from idc_models_tpu.serve.metrics import ServingMetrics  # noqa: F401
from idc_models_tpu.serve.prefix_cache import PrefixCache  # noqa: F401
from idc_models_tpu.serve.scheduler import (  # noqa: F401
    AdmissionQueue, Scheduler,
)
