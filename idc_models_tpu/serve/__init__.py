from idc_models_tpu.serve.api import (  # noqa: F401
    LMServer, Request, Result, load_trace, poisson_trace, save_trace,
)
from idc_models_tpu.serve.brownout import BrownoutController  # noqa: F401
from idc_models_tpu.serve.cluster import (  # noqa: F401
    AutoscaleConfig, Autoscaler, ClusterTelemetry, ClusterWatchdog,
    PrefixRegistry, Replica, Router, WatchdogConfig, build_replica,
)
from idc_models_tpu.serve.compile_cache import (  # noqa: F401
    CompileCache, enable_persistent_xla_cache,
)
from idc_models_tpu.serve.engine import SlotEngine  # noqa: F401
from idc_models_tpu.serve.faults import (  # noqa: F401
    InjectedEngineCrash, InjectedPrefillError, ServeFault,
    ServeFaultPlan, parse_serve_fault_spec,
)
from idc_models_tpu.serve.journal import (  # noqa: F401
    RequestJournal, load_journal, pending_requests,
)
from idc_models_tpu.models.draft import NGramDrafter  # noqa: F401
from idc_models_tpu.serve.metrics import ServingMetrics  # noqa: F401
from idc_models_tpu.serve.pages import (  # noqa: F401
    PageAllocator, PageExhausted,
)
from idc_models_tpu.serve.prefix_cache import (  # noqa: F401
    PagedPrefixCache, PrefixCache,
)
from idc_models_tpu.serve.scheduler import (  # noqa: F401
    AdmissionQueue, RetryPolicy, Scheduler,
)
from idc_models_tpu.serve.tenancy import (  # noqa: F401
    AdapterBank, Tenancy, TenantQuota, TenantRegistry,
)
