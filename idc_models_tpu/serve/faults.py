"""Deterministic, seeded fault injection for the SERVING path.

PR 3 gave the federated loop a reproducible failure vocabulary
(`idc_models_tpu/faults.py`); this module is the serving analogue. The
serve stack's failure modes land in different places — a poisoned slot's
logits, a prefill dispatch that dies, a stalled tick, an arrival burst,
a hard engine crash — so the plan is indexed by the scheduler's CYCLE
counter instead of the federated round index, and every fault is a pure
function of (plan, tick), so a faulted run replays bit-identically
(gated by tests/test_serve_resilience.py).

Fault kinds (`ServeFault.kind`):

- ``nan_logits``      overwrite a chosen slot's last-token logits row
                      with NaN at a chosen tick — the numerical-
                      corruption failure the per-window slot health
                      check must catch BEFORE a token is sampled from it;
- ``garbage_logits``  the finite flavor (±1e32): non-finiteness checks
                      are blind to it, the magnitude bound is not;
- ``prefill_error``   the next prefill-chunk dispatch raises
                      `InjectedPrefillError` — a request-scoped
                      admission failure (quarantine the request, not
                      the server);
- ``stall``           the tick sleeps `seconds` before doing anything —
                      a straggling dispatch / GC pause / noisy
                      neighbor, the latency fault the SLO burn detects;
- ``crash``           the tick raises `InjectedEngineCrash` after
                      failing every in-flight entry — the hard
                      mid-run death the request journal
                      (serve/journal.py) exists to recover from;
- ``burst``           `n` synthetic requests (seeded prompts, pure
                      function of (plan.seed, tick, i)) are submitted
                      at the tick — the overload wave the brownout
                      controller sheds.

The plan is threaded through `Scheduler(fault_plan=...)` /
`LMServer(fault_plan=...)` behind a default-off hook: with no plan
armed the serve loop's fault path is one `is None` check per tick.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Sequence

import numpy as np

from idc_models_tpu.faults import format_spec_error, parse_id_field
from idc_models_tpu.serve.api import Request


class InjectedEngineCrash(RuntimeError):
    """A declarative `crash` fault firing: the whole engine dies
    mid-run. In-flight entries are failed through the scheduler's
    normal engine-failure cleanup before this propagates, and a
    request journal (serve/journal.py) makes the loss recoverable."""


class InjectedPrefillError(RuntimeError):
    """A declarative `prefill_error` fault firing: one prefill-chunk
    dispatch dies. Request-scoped — with a retry policy armed the
    scheduler quarantines only the prefilling request."""


KINDS = ("nan_logits", "garbage_logits", "prefill_error", "stall",
         "crash", "burst")
GRAMMAR = ("comma-separated kind:ticks[:param] groups; ticks = a single "
           "tick, an inclusive a-b range, or a +-joined list; param = "
           "slot for nan_logits/garbage_logits, seconds for stall, "
           "request count for burst (crash/prefill_error take none)")

# the kinds whose third spec field means what
_PARAM_OF = {"nan_logits": "slot", "garbage_logits": "slot",
             "stall": "seconds", "burst": "n"}


@dataclasses.dataclass(frozen=True)
class ServeFault:
    """One declarative serving fault, fired at scheduler cycle `tick`.
    `slot` targets the logit-poisoning kinds; `seconds` is the stall
    length; `n`/`prompt_len`/`budget` shape a burst's synthetic
    requests."""

    kind: str
    tick: int
    slot: int = 0
    seconds: float = 0.05
    n: int = 8
    prompt_len: int = 4
    budget: int = 8

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown serve fault kind {self.kind!r}; "
                             f"valid kinds: {', '.join(KINDS)}")
        if self.tick < 0:
            raise ValueError(f"tick must be >= 0, got {self.tick}")
        if self.slot < 0:
            raise ValueError(f"slot must be >= 0, got {self.slot}")
        if not self.seconds > 0:
            raise ValueError(f"stall seconds must be > 0, got "
                             f"{self.seconds}")
        if self.n < 1 or self.prompt_len < 1 or self.budget < 1:
            raise ValueError(
                f"burst needs n/prompt_len/budget >= 1, got "
                f"{self.n}/{self.prompt_len}/{self.budget}")


class ServeFaultPlan:
    """A deterministic serve fault schedule.

    `at(tick)` / `bursts_at(tick)` are pure functions of the plan and
    the tick, and a burst's synthetic prompts are a pure function of
    (seed, tick, index) — so a faulted serving run replays
    bit-identically: same plan + same trace -> the same failure at the
    same cycle with the same recovery (gated by test)."""

    def __init__(self, faults: Sequence[ServeFault] = (), *,
                 seed: int = 0):
        self.faults = tuple(faults)
        self.seed = int(seed)
        for f in self.faults:
            if not isinstance(f, ServeFault):
                raise TypeError(f"expected ServeFault, got {type(f)}")

    def at(self, tick: int) -> list[ServeFault]:
        """The non-burst faults firing at scheduler cycle `tick`
        (bursts are arrivals, not engine events — the api layer injects
        them via `bursts_at`)."""
        return [f for f in self.faults
                if f.tick == tick and f.kind != "burst"]

    def bursts_at(self, tick: int) -> list[ServeFault]:
        return [f for f in self.faults
                if f.tick == tick and f.kind == "burst"]

    def burst_requests(self, fault: ServeFault, *, vocab: int,
                       t_max: int) -> list[Request]:
        """The synthetic requests one burst fault submits — seeded by
        (plan.seed, fault.tick, i), so two runs of the same plan see
        the identical arrival wave. Ids carry a ``!burst`` prefix so
        they cannot collide with caller request ids."""
        p_len = min(fault.prompt_len, t_max - 1)
        budget = min(fault.budget, t_max - p_len)
        out = []
        for i in range(fault.n):
            rng = np.random.default_rng((self.seed, fault.tick, i))
            prompt = tuple(int(x) for x in rng.integers(0, vocab, p_len))
            out.append(Request(id=f"!burst-{fault.tick}-{i}",
                               prompt=prompt, max_new_tokens=budget))
        return out

    @property
    def max_tick(self) -> int:
        return max((f.tick for f in self.faults), default=-1)

    def __repr__(self) -> str:
        return (f"ServeFaultPlan(faults={list(self.faults)!r}, "
                f"seed={self.seed})")


def parse_serve_fault_spec(spec: str, *, seed: int = 0) -> ServeFaultPlan:
    """CLI serve-fault grammar — same shape as the federated
    `parse_fault_spec`, tick-indexed:

        "nan_logits:3:0"         poison slot 0's logits at tick 3
        "stall:5-8:0.02"         20 ms stall on ticks 5..8
        "burst:2:16,crash:40"    16-request burst at tick 2, crash at 40

    Every parse error enumerates the valid kinds and shows the grammar
    (the shared `format_spec_error` helper — satellite of the same
    ISSUE that fixed the federated messages)."""
    faults: list[ServeFault] = []
    for group in spec.split(","):
        group = group.strip()
        if not group:
            continue
        parts = group.split(":")
        if len(parts) not in (2, 3):
            raise ValueError(format_spec_error(
                group, "want kind:ticks[:param]", kinds=KINDS,
                grammar=GRAMMAR))
        kind, ticks = parts[0].strip(), parts[1].strip()
        if kind not in KINDS:
            raise ValueError(format_spec_error(
                group, f"unknown fault kind {kind!r}", kinds=KINDS,
                grammar=GRAMMAR))
        kw = {}
        if len(parts) == 3:
            param = parts[2].strip()
            field = _PARAM_OF.get(kind)
            if field is None:
                raise ValueError(format_spec_error(
                    group, f"fault kind {kind!r} takes no parameter, "
                           f"got {param!r}", kinds=KINDS,
                    grammar=GRAMMAR))
            try:
                kw[field] = (float(param) if field == "seconds"
                             else int(param))
            except ValueError:
                raise ValueError(format_spec_error(
                    group, f"bad {field} parameter {param!r}",
                    kinds=KINDS, grammar=GRAMMAR)) from None
        tick_list = parse_id_field(ticks, what="ticks", group=group,
                                   kinds=KINDS, grammar=GRAMMAR)
        try:
            faults.extend(ServeFault(kind, int(t), **kw)
                          for t in tick_list)
        except ValueError as e:
            # out-of-range values (negative tick/slot, zero seconds or
            # burst size) get the same teaching message as syntax
            # errors — ServeFault's own validation supplies the detail
            raise ValueError(format_spec_error(
                group, str(e), kinds=KINDS, grammar=GRAMMAR)) from None
    return ServeFaultPlan(faults, seed=seed)
