"""User-facing serving surface: `Request`/`Result`, the synchronous
`submit()`/`poll()` API, and `run(trace)` trace replay.

`LMServer` composes the three serving layers — `SlotEngine` (device
state machine), `Scheduler` (admission queue, deadlines, interleave,
recycling), `ServingMetrics` (TTFT/throughput/occupancy) — behind the
smallest API that exercises them end to end:

    server = LMServer(params, embed_dim=..., num_heads=...,
                      num_blocks=..., t_max=..., n_slots=4, window=8)
    server.submit(Request(id="a", prompt=(1, 2, 3), max_new_tokens=16))
    while server.poll("a") is None:
        server.step()                  # one scheduler tick
    print(server.poll("a").tokens)

Traces replay real arrival processes without a network frontend:
`poisson_trace` synthesizes open-loop Poisson arrivals (the standard
serving-benchmark arrival model) and `load_trace`/`save_trace` move the
same `(arrival_s, Request)` list through a JSONL file, one request per
line. `run(trace)` replays either kind — by wall clock (`realtime=True`,
the honest TTFT measurement) or as a burst (deterministic tests).
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path

import numpy as np


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. `seed` derives the request's PRIVATE
    sampling stream (identical to passing `jax.random.key(seed)` to a
    serial `Generator` call — token parity is per-request, not
    per-batch); `deadline_s` is seconds from submit after which the
    request is dropped (queued) or cancelled mid-generation (running);
    `eos_id` overrides the server default stop token (None = server's,
    -1 = never stop early); `trace_id` labels the request's lifecycle
    spans in exported traces (None = the scheduler assigns a
    process-unique one at submit — it comes back on the Result);
    `tenant` names the registered tenant this request bills against on
    a multi-tenant server (serve/tenancy.py — None = the default
    tenant; an unknown name is a loud caller error)."""
    id: str
    prompt: tuple
    max_new_tokens: int
    eos_id: int | None = None
    seed: int | None = None
    deadline_s: float | None = None
    trace_id: str | None = None
    tenant: str | None = None


@dataclasses.dataclass
class Result:
    """What came back: `tokens` are the GENERATED ids only (prompt not
    echoed), truncated at EOS (inclusive) when one is configured.
    `status` is "ok" (ran to EOS/budget), "timeout" (deadline hit —
    possibly with partial tokens), "rejected" (queue full at submit
    with on_full="reject"), "shed" (refused by the brownout
    controller's shed stage — explicit overload, retry elsewhere/later),
    or "error" (the engine failed mid-flight, or a quarantined slot
    exhausted its retries — `error` carries the detail and `tokens`
    whatever clean prefix was generated). `attempts`/`retried` expose
    the retry policy's work: a request recovered from a poisoned slot
    finishes with attempts > 1 and its output bit-identical to an
    unfaulted run (the engine's serial-parity contract)."""
    id: str
    tokens: list
    status: str
    finish_reason: str | None = None
    ttft_ms: float | None = None
    latency_ms: float | None = None
    error: str | None = None
    # the id stamped on every span of this request's lifecycle chain in
    # an exported trace (serve.request/queued/first_token + rid attrs)
    trace_id: str | None = None
    attempts: int = 1
    retried: bool = False


class LMServer:
    """Continuous-batching server over one `attention_lm` parameter
    tree. Construction compiles (or reuses from the process-wide cache)
    every program the serve loop touches when `warmup=True`, so the
    first request pays no XLA latency and later requests of ANY prompt
    length/budget compile nothing (gated by test)."""

    def __init__(self, params, *, embed_dim: int, num_heads: int,
                 num_blocks: int, t_max: int, n_slots: int = 4,
                 window: int = 8, mesh=None, cache_dtype=None,
                 block_impl: str = "jnp", temperature: float = 0.0,
                 top_k: int | None = None, pad_id: int = 0,
                 eos_id: int | None = None, max_queue_depth: int = 64,
                 max_prefills_per_cycle: int = 1,
                 admit_after_collect: bool = True, logger=None,
                 warmup: bool = True, clock=time.monotonic,
                 prefill_chunk: int | None = None,
                 prefix_cache_mb: float = 0.0,
                 kv_dtype: str | None = None, slo=None,
                 retry=None, fault_plan=None,
                 health_checks: bool | None = None, journal=None,
                 brownout=None, prefix_cache=None,
                 spec_decode: bool = False, draft_k: int = 8,
                 draft_order: int = 3, drafter=None,
                 kv_page_size: int | None = None,
                 kv_pages: int | None = None,
                 kv_decode_reserve: int | None = None,
                 registry=None, tenancy=None, partition_rules=None,
                 draft_partition_rules=None, compile_cache=None):
        import jax.numpy as jnp

        from idc_models_tpu.serve.engine import SlotEngine
        from idc_models_tpu.serve.metrics import ServingMetrics
        from idc_models_tpu.serve.prefix_cache import (
            PagedPrefixCache, PrefixCache,
        )
        from idc_models_tpu.serve.scheduler import Scheduler

        # prefix reuse rides the chunk grid: snapshots are taken at
        # chunk boundaries and extended by the chunk program, so the
        # knob implies chunked admission. An EXISTING PrefixCache may be
        # passed instead of a budget — the warm-restart path: a server
        # rebuilt after an engine crash reuses the dead engine's
        # snapshots and recovered requests re-prefill only their
        # uncached suffix (gated by test). With paged KV
        # (kv_page_size/kv_pages) the budget builds a PagedPrefixCache
        # instead — snapshots are refcounted page lists in the pool,
        # and the MB budget converts to pages when the engine binds
        # its allocator.
        paged = kv_page_size is not None or kv_pages is not None
        # everything canary_clone needs to build a config-identical
        # second server over candidate weights (same shapes/mesh ->
        # the process-wide jit cache serves both, zero new compiles)
        self._clone_cfg = dict(
            embed_dim=embed_dim, num_heads=num_heads,
            num_blocks=num_blocks, t_max=t_max, n_slots=n_slots,
            window=window, mesh=mesh, cache_dtype=cache_dtype,
            block_impl=block_impl, temperature=temperature,
            top_k=top_k, pad_id=pad_id, eos_id=eos_id,
            max_queue_depth=max_queue_depth,
            max_prefills_per_cycle=max_prefills_per_cycle,
            admit_after_collect=admit_after_collect, clock=clock,
            prefill_chunk=prefill_chunk, kv_dtype=kv_dtype,
            spec_decode=spec_decode, draft_k=draft_k,
            draft_order=draft_order, drafter=drafter,
            draft_partition_rules=draft_partition_rules,
            kv_page_size=kv_page_size,
            kv_pages=kv_pages, kv_decode_reserve=kv_decode_reserve,
            partition_rules=partition_rules,
            compile_cache=compile_cache)
        self._clone_logger = logger
        # compile_cache: a serve.compile_cache.CompileCache — warmup
        # then AOT-loads (or compiles-and-stores) the serve programs
        # from disk, so a replica spin-up on a warmed cache is a
        # deserialize, not an XLA run (cluster elasticity; cloned into
        # canaries via _clone_cfg so a rollout's second server spins
        # warm too)
        self.compile_cache = compile_cache
        # registry: an observe MetricsRegistry for this server's
        # instruments (None = the process-wide default). A multi-
        # replica process (serve/cluster) gives each replica its OWN
        # registry so the serve_* gauges don't stomp each other and
        # each replica's /healthz stays an honest per-replica document.
        self.registry = registry
        if prefix_cache is not None and prefix_cache_mb:
            raise ValueError("pass prefix_cache OR prefix_cache_mb, "
                             "not both")
        if prefix_cache is None and prefix_cache_mb and prefix_cache_mb > 0:
            if prefill_chunk is None:
                raise ValueError("prefix_cache_mb needs prefill_chunk")
            if paged:
                prefix_cache = PagedPrefixCache(
                    prefill_chunk, budget_mb=prefix_cache_mb,
                    logger=logger, registry=registry)
            else:
                prefix_cache = PrefixCache(
                    prefill_chunk, int(prefix_cache_mb * 1024 * 1024),
                    logger=logger, registry=registry)
        # speculative decoding (ISSUE 10): spec_decode compiles the
        # fixed-k verify program into the engine and arms the
        # scheduler's draft-and-verify window mode. The default
        # drafter is n-gram prompt-lookup (models/draft.py) — no
        # second model; pass `drafter` (any object with
        # propose(history) -> k tokens | None) to plug in a draft LM
        if drafter is not None and not spec_decode:
            raise ValueError("a custom drafter needs spec_decode=True")
        if spec_decode and drafter is None:
            from idc_models_tpu.models.draft import NGramDrafter

            drafter = NGramDrafter(draft_k, order=draft_order)
        # a LEARNED drafter (models/draft_lm.DraftLM, or a
        # ChainedDrafter wrapping one) exposes `.learned` — the model
        # handle that arms the engine's device-resident drafter state
        # (per-slot ring caches + the batched propose program); host
        # drafters leave it None and the engine builds spec-off-cheap
        draft_model = getattr(drafter, "learned", None)
        if draft_model is None and draft_partition_rules is not None:
            raise ValueError(
                "draft_partition_rules without a learned drafter: the "
                "rules place models/draft_lm.DraftLM params — pass "
                "drafter=DraftLM(...) (or a ChainedDrafter containing "
                "one), or drop the rules")
        # tenancy (serve/tenancy.py, ISSUE 14): accept a built Tenancy
        # runtime OR a TenantRegistry (built here against THIS model's
        # vocab with the server's logger/registry/clock — adapter
        # shape mismatches fail the build, not the first request)
        if tenancy is not None and hasattr(tenancy, "build"):
            tenancy = tenancy.build(
                vocab=params["head"]["kernel"].shape[1],
                logger=logger, registry=registry, clock=clock)
        self.tenancy = tenancy
        self.engine = SlotEngine(
            params, embed_dim=embed_dim, num_heads=num_heads,
            num_blocks=num_blocks, t_max=t_max, n_slots=n_slots,
            mesh=mesh,
            cache_dtype=(jnp.bfloat16 if cache_dtype is None
                         else cache_dtype),
            block_impl=block_impl, temperature=temperature, top_k=top_k,
            pad_id=pad_id, eos_id=eos_id, prefill_chunk=prefill_chunk,
            prefix_cache=prefix_cache, kv_dtype=kv_dtype,
            draft_k=draft_k if spec_decode else None,
            kv_page_size=kv_page_size, kv_pages=kv_pages,
            kv_decode_reserve=kv_decode_reserve,
            adapter_bank=(tenancy.bank if tenancy is not None
                          else None),
            partition_rules=partition_rules, draft_model=draft_model,
            draft_partition_rules=draft_partition_rules)
        # slo: an optional observe.slo.SLOEngine — the metrics hooks
        # feed its declared objectives (ttft/queue_wait/error_rate) and
        # evaluate burn rates once per scheduler cycle
        self.metrics = ServingMetrics(logger, prefix_cache=prefix_cache,
                                      slo=slo, registry=registry,
                                      tenancy=tenancy)
        # journal: a RequestJournal or a path — the WAL of accepted
        # work a rebuilt server recovers in-flight requests from
        # (resubmit_pending / serve/journal.py)
        if journal is not None and not hasattr(journal, "record_submit"):
            from idc_models_tpu.serve.journal import RequestJournal

            journal = RequestJournal(journal)
        self.journal = journal
        # brownout: a BrownoutController; it degrades the prefix cache
        # first, so hand it ours unless the caller wired its own
        if (brownout is not None and brownout.prefix_cache is None
                and prefix_cache is not None):
            brownout.prefix_cache = prefix_cache
        self.brownout = brownout
        self._fault_plan = fault_plan
        self.scheduler = Scheduler(
            self.engine, window=window, max_queue_depth=max_queue_depth,
            max_prefills_per_cycle=max_prefills_per_cycle,
            admit_after_collect=admit_after_collect,
            metrics=self.metrics, clock=clock, retry=retry,
            fault_plan=fault_plan, health_checks=health_checks,
            journal=journal, brownout=brownout, drafter=drafter,
            tenancy=tenancy)
        self._results: dict[str, Result] = {}
        self._inflight: set[str] = set()
        if warmup:
            self.engine.warmup(window, compile_cache=compile_cache)
        if compile_cache is not None:
            self.metrics.on_compile_cache(compile_cache)

    # -- synchronous API -------------------------------------------------

    def submit(self, request: Request, *,
               parent_span=None) -> bool:
        """Enqueue a request. False = backpressure (queue at max depth);
        raises ValueError for requests that could never be served.
        `parent_span` (a span id) parents this request's serve.request
        span under a caller-owned span — the cluster router passes its
        cluster.request root so the cross-replica export is one tree."""
        from idc_models_tpu.serve.scheduler import Entry

        prior = self._results.get(request.id)
        if ((prior is not None and prior.status != "shed")
                or request.id in self._inflight):
            # includes QUEUED/RUNNING ids: a duplicate in flight would
            # silently overwrite the other's Result at finish. A SHED
            # id is the one exception — the brownout refused it without
            # serving anything, and its docstring tells the client to
            # retry later, so the same id may try again
            raise ValueError(f"request id {request.id!r} already used")
        entry = Entry(
            rid=request.id,
            prompt=np.asarray(request.prompt, np.int32),
            budget=int(request.max_new_tokens),
            eos_id=request.eos_id,
            # integer seeds ride through as-is: the engine derives the
            # key data on the host (identical to jax.random.key(seed))
            rng=request.seed,
            deadline=request.deadline_s,
            trace_id=request.trace_id,
            parent_span=parent_span,
            tenant=request.tenant)
        ok = self.scheduler.submit(entry)
        if not ok:
            if entry.status == "shed":
                # a brownout shed is a TERMINAL outcome, not transient
                # backpressure: record the honest Result so poll()
                # answers for it
                r = _to_result(entry)
                self._results[r.id] = r
                return False
            # backpressure: leave no Result — the caller may retry the
            # same id later
            return False
        # a resubmit after a terminal shed/rejection must not leave the
        # stale Result answering poll() while the request actually
        # queues — poll's contract is None until it finishes
        self._results.pop(request.id, None)
        self._inflight.add(request.id)
        return True

    def close(self) -> None:
        """Shut the server down: submit() afterwards raises
        RuntimeError (the scheduler's close contract) and the request
        journal, if any, is flushed closed. Accepted work can still be
        drained first."""
        self.scheduler.close()
        if self.journal is not None:
            self.journal.close()

    def resubmit_pending(self, journal_path) -> list[str]:
        """Crash recovery: re-admit every request `journal_path` shows
        accepted but unfinished (in original submit order) through the
        NORMAL admission path — chunked prefill and prefix-cache reuse
        included — and return the re-admitted ids. Each recovered
        request keeps its journaled id, seed, deadline, trace_id, and
        tenant tag, and its greedy/seeded output is bit-identical to
        what an uncrashed run would have produced (the engine's
        serial-parity contract; gated by test).

        A journaled request the REBUILT server can never serve — a
        tenant since decommissioned from the registry, a prompt past a
        shrunken t_max — is SKIPPED with a warning instead of aborting
        the whole recovery: one stale entry must not block every other
        tenant's requests from coming back (the entry stays in the
        WAL, so a rerun against a fixed configuration still recovers
        it)."""
        import warnings

        from idc_models_tpu.serve.journal import pending_requests

        out = []
        for req in pending_requests(journal_path):
            try:
                ok = self.submit(req)
            except ValueError as e:
                warnings.warn(
                    f"journal recovery skipped request {req.id!r}: "
                    f"{e} — it remains in the WAL; rerun against a "
                    f"configuration that can serve it",
                    stacklevel=2)
                continue
            if ok:
                out.append(req.id)
        return out

    def _fire_bursts(self) -> None:
        """Inject the fault plan's burst arrivals scheduled for the
        NEXT scheduler cycle — synthetic overload waves, submitted
        through the normal (backpressure/shed-visible) path. Runs once
        per step(), and the cycle counter strictly increments per tick,
        so each burst fires exactly once."""
        cycle = self.scheduler._cycle
        for f in self._fault_plan.bursts_at(cycle):
            self.metrics.on_fault_injected("burst", tick=cycle)
            vocab = self.engine._logits.shape[1]
            for req in self._fault_plan.burst_requests(
                    f, vocab=vocab, t_max=self.engine.t_max):
                self.submit(req)

    def step(self) -> list[Result]:
        """One scheduler tick (admissions + one fused decode window);
        returns the requests that finished on it. If the ENGINE fails
        mid-tick the error propagates, but the in-flight requests are
        first recorded as status="error" Results (slots released, queue
        intact) so poll() answers for them and a recovering caller can
        keep serving."""
        if self._fault_plan is not None:
            self._fire_bursts()
        return self._cycle(self.scheduler.tick)

    def quiesce(self) -> list[Result]:
        """One cycle that collects the in-flight decode window without
        dispatching another (Scheduler.quiesce) — the dispatch-idle
        point a paged engine's rollout spot-check needs. Same
        result/failure bookkeeping as step()."""
        return self._cycle(self.scheduler.quiesce)

    def _cycle(self, tick_fn) -> list[Result]:
        finished = []
        try:
            ticked = tick_fn()
        except Exception:
            for e in self.scheduler.pop_failed():
                r = _to_result(e)
                self._results[r.id] = r
                self._inflight.discard(r.id)
            raise
        for e in ticked:
            r = _to_result(e)
            self._results[r.id] = r
            self._inflight.discard(r.id)
            finished.append(r)
        return finished

    # -- hot weight rollout (ROADMAP 4) ----------------------------------

    def swap_params(self, params) -> None:
        """Promote candidate weights onto THIS server's engine — see
        `SlotEngine.swap_params` for the zero-recompile/zero-drop
        contract. The rollout metrics hook is the caller's job
        (checkpoint/rollout.py owns the state machine)."""
        self.engine.swap_params(params)

    def swap_adapters(self, u, v) -> None:
        """Hot-swap the per-tenant adapter bank — the cheap first rung
        of a rollout (no full-tree placement, no canary needed: the
        base weights are untouched). See `SlotEngine.swap_adapters`
        for the shape contract and the tenant-less teaching error."""
        self.engine.swap_adapters(u, v)

    def canary_clone(self, params, *, registry=None,
                     logger=None) -> "LMServer":
        """A second, config-identical server over CANDIDATE weights —
        the canary a rollout routes a controlled traffic fraction
        onto. Same shapes, mesh, and programs, so the process-wide jit
        cache serves both and construction compiles NOTHING new (the
        cluster tier's N-replicas-one-process pattern).

        Deliberately NOT shared: the prefix cache (its KV snapshots
        were computed under the LIVE weights — resuming them under
        candidate weights would silently mix two models' caches), the
        journal (one WAL system of record; canary requests are
        journaled by the controller against the live server), fault
        plan, brownout, and the metrics registry (a fresh one per
        canary, like cluster replicas, so live gauges are never
        stomped). Tenancy IS shared: quotas and per-tenant SLOs bill
        across both sides of the split."""
        if registry is None:
            from idc_models_tpu.observe.metrics_registry import (
                MetricsRegistry,
            )

            registry = MetricsRegistry()
        return LMServer(
            params, tenancy=self.tenancy, registry=registry,
            logger=self._clone_logger if logger is None else logger,
            **self._clone_cfg)

    def poll(self, rid: str) -> Result | None:
        """The finished Result for `rid`, or None while it is still
        queued/running."""
        return self._results.get(rid)

    def results(self) -> list[Result]:
        """Snapshot of every finished Result so far — what a caller
        salvages when run() is interrupted by an engine crash (the
        in-flight requests were already finalized as error Results by
        the failure cleanup)."""
        return list(self._results.values())

    def drain(self) -> list[Result]:
        """Tick until idle; returns everything that finished."""
        out = []
        while not self.scheduler.idle():
            out.extend(self.step())
        return out

    # -- trace replay ----------------------------------------------------

    def run(self, trace, *, realtime: bool = False,
            on_full: str = "block") -> list[Result]:
        """Replay `[(arrival_s, Request), ...]` and drain. With
        `realtime=True` requests are held until their arrival offset on
        the wall clock (the honest open-loop TTFT measurement); with
        False the trace is replayed as fast as the engine drains it —
        arrival ORDER kept, deterministic for tests. `on_full` is the
        client-side backpressure policy: "block" re-offers the head
        request every tick until the queue accepts it; "reject" records
        a rejected Result and moves on."""
        if on_full not in ("block", "reject"):
            raise ValueError(f"on_full must be 'block' or 'reject', "
                             f"got {on_full!r}")
        trace = sorted(trace, key=lambda tr: tr[0])
        clock = self.scheduler.clock
        t0 = clock()
        out, i = [], 0
        while i < len(trace) or not self.scheduler.idle():
            now = clock() - t0
            while i < len(trace) and (not realtime
                                      or trace[i][0] <= now):
                # in block mode, don't OFFER a request the queue cannot
                # take: every refused submit() counts as a rejection in
                # the metrics, and a head request re-offered for 50
                # ticks is one blocked request, not 50 rejected ones.
                # While the brownout SHEDS, offer anyway — a shed is a
                # terminal answer, not a queue race to wait out.
                shedding = (self.brownout is not None
                            and self.brownout.shedding)
                if (on_full == "block" and not shedding
                        and len(self.scheduler.queue)
                        >= self.scheduler.queue.max_depth):
                    break               # blocked: re-offer next tick
                if self.submit(trace[i][1]):
                    i += 1
                    continue
                shed = self._results.get(trace[i][1].id)
                if shed is not None and shed.status == "shed":
                    out.append(shed)
                    i += 1
                elif on_full == "reject":
                    r = Result(id=trace[i][1].id, tokens=[],
                               status="rejected")
                    self._results[r.id] = r
                    out.append(r)
                    i += 1
                else:
                    break               # blocked: re-offer next tick
            if (realtime and self.scheduler.idle() and i < len(trace)):
                # nothing running and the next arrival is in the future
                time.sleep(min(max(trace[i][0] - (clock() - t0), 0.0),
                               0.005))
                continue
            out.extend(self.step())
        return out

    def summary(self) -> dict:
        return self.metrics.summary()


def _to_result(e) -> Result:
    return Result(
        id=e.rid, tokens=list(e.tokens), status=e.status,
        finish_reason=e.finish_reason, error=e.error,
        trace_id=e.trace_id, attempts=e.attempts, retried=e.retried,
        ttft_ms=(None if e.t_first is None
                 else (e.t_first - e.t_submit) * 1e3),
        latency_ms=(None if e.t_done is None
                    else (e.t_done - e.t_submit) * 1e3))


# -- traces ---------------------------------------------------------------


def poisson_trace(n_requests: int, *, rate_per_s: float, vocab: int,
                  t_max: int, prompt_lens=(4, 16), budgets=(4, 16),
                  eos_id: int | None = None,
                  deadline_s: float | None = None, seed: int = 0,
                  sampled: bool = False, tenants=None):
    """Synthetic open-loop arrivals: exponential inter-arrival times at
    `rate_per_s`, prompt lengths and budgets uniform over the given
    inclusive ranges (clamped so prompt + budget <= t_max). With
    `sampled=True` each request carries its own seed (for temperature>0
    servers). `tenants` (a sequence of names) tags arrivals round-robin
    for a multi-tenant server — round-robin, not random, so every
    tenant's sub-trace is a deterministic function of the trace alone.
    Returns `[(arrival_s, Request), ...]`."""
    rng = np.random.default_rng(seed)
    t, trace = 0.0, []
    lo_p, hi_p = prompt_lens
    lo_b, hi_b = budgets
    tenants = list(tenants) if tenants else None
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_per_s))
        p_len = int(rng.integers(lo_p, hi_p + 1))
        p_len = min(p_len, t_max - 1)
        budget = int(rng.integers(lo_b, hi_b + 1))
        budget = min(budget, t_max - p_len)
        prompt = tuple(int(x) for x in rng.integers(0, vocab, p_len))
        trace.append((t, Request(
            id=f"r{i}", prompt=prompt, max_new_tokens=budget,
            eos_id=eos_id, deadline_s=deadline_s,
            seed=(int(rng.integers(0, 2**31)) if sampled else None),
            tenant=(tenants[i % len(tenants)] if tenants else None))))
    return trace


def save_trace(path, trace) -> str:
    """Write `[(arrival_s, Request), ...]` as JSONL, one request per
    line — the interchange format `run`/`load_trace` and the CLI's
    `serve --trace` share."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        for t, r in trace:
            rec = {
                "t": t, "id": r.id, "prompt": list(r.prompt),
                "max_new_tokens": r.max_new_tokens, "eos_id": r.eos_id,
                "seed": r.seed, "deadline_s": r.deadline_s}
            if r.tenant is not None:
                # written only when tagged: untagged traces stay
                # byte-identical to every file this format ever wrote
                rec["tenant"] = r.tenant
            f.write(json.dumps(rec) + "\n")
    return str(path)


def load_trace(path):
    """Read a `save_trace` JSONL file back into `[(t, Request), ...]`."""
    trace = []
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        d = json.loads(line)
        trace.append((float(d.get("t", 0.0)), Request(
            id=str(d["id"]), prompt=tuple(d["prompt"]),
            max_new_tokens=int(d["max_new_tokens"]),
            eos_id=d.get("eos_id"), seed=d.get("seed"),
            deadline_s=d.get("deadline_s"), tenant=d.get("tenant"))))
    return trace
