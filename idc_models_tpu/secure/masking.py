"""Pairwise one-time-mask secure aggregation primitives (TPU fast path).

The reference's secure aggregation is Paillier homomorphic encryption of a
fraction of the weight tensors (secure_fed_model.py:109-129): the server
averages ciphertexts it cannot read. Pure-Python bignum crypto does not map
to XLA, so the TPU-native design (SURVEY.md D4) is Bonawitz-style pairwise
masking: every ordered client pair (i, j) shares a PRG seed; client i adds
`+mask_ij` for j > i and `-mask_ij` for j < i to its update before the
`psum`. Each device's contribution is indistinguishable from random to the
aggregator, but the masks cancel *exactly* in the sum.

Exact cancellation requires integer arithmetic (fp addition of large masks
would destroy precision): updates are quantized to int32 fixed-point,
masks are uniform int32, and addition wraps mod 2^32 (two's-complement),
so `psum` of masked updates == `psum` of plain quantized updates bit-for-bit.

The reference's `percent` knob — encrypt the first `int(num_tensors *
percent)` weight tensors (secure_fed_model.py:115-121) — maps to a boolean
selection pytree over the same flatten order (`first_fraction_selection`).

Seed agreement: the reference generates one global keypair visible to all
parties (quirk Q9); the analogous simplification here is deriving the
pairwise seed from a shared base key via `fold_in(fold_in(key, lo),
hi)` — both endpoints of a pair compute the same seed with no exchange. A
deployment would replace `pair_key` with a Diffie-Hellman-agreed seed; the
cancellation algebra is unchanged.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_SCALE_BITS = 20  # fixed-point fractional bits
DEFAULT_CLIP_ABS = 64.0  # quantization clipping range for weights


def choose_scale_bits(n_clients: int,
                      clip_abs: float = DEFAULT_CLIP_ABS) -> int:
    """Largest scale_bits such that the un-masked sum over `n_clients`
    values of magnitude <= clip_abs cannot overflow int32 — strictly
    2^scale * clip_abs * n_clients <= 2^31 - 1 (2^31 itself wraps to
    INT32_MIN and sign-flips a fully saturated element). Mask wraparound
    is mod-2^32 by design and cancels; it is the *unwrapped* sum of
    quantized values that must stay in range for dequantize to be
    correct."""
    n = max(n_clients, 1)
    bits = 31 - math.ceil(math.log2(n * clip_abs))
    while bits > 0 and (2.0 ** bits) * clip_abs * n > 2**31 - 1:
        bits -= 1
    if bits < 1:
        raise ValueError(
            f"no int32 headroom for {n_clients} clients at clip {clip_abs}")
    return min(bits, DEFAULT_SCALE_BITS)


def quantize(x: jax.Array, scale_bits: int = DEFAULT_SCALE_BITS, *,
             clip_abs: float | None = DEFAULT_CLIP_ABS) -> jax.Array:
    """fp32 -> int32 fixed point (round-to-nearest), clipped to
    +-clip_abs so the value always fits its headroom budget (see
    `choose_scale_bits`) instead of silently wrapping."""
    x = x.astype(jnp.float32)
    if clip_abs is not None:
        x = jnp.clip(x, -clip_abs, clip_abs)
    return jnp.round(x * (2.0 ** scale_bits)).astype(jnp.int32)


def dequantize(q: jax.Array, scale_bits: int = DEFAULT_SCALE_BITS,
               *, count: jax.Array | float = 1.0) -> jax.Array:
    """int32 fixed point -> fp32, dividing by `count` (for the mean).

    Evaluated in two exact pieces: the integer part (|q| < 2^31 -> below
    2^(31-scale_bits)) and the fractional part (< 2^scale_bits <= 2^23)
    are each exactly representable in fp32, so rounding happens only in
    the final add/divide — a few ulps of the *result*. A straight
    `q.astype(f32)` would instead drop low bits of any sum above 2^24
    (reachable with clip_abs=64, scale_bits=20, 8 clients), losing the
    advertised 2^-scale_bits resolution even when the mean is small.
    """
    scale = 1 << scale_bits
    hi = q // scale                  # floor division: exact, lo stays >= 0
    lo = q - hi * scale              # in [0, scale)
    return (hi.astype(jnp.float32)
            + lo.astype(jnp.float32) / jnp.float32(scale)) / count


def pair_key(base: jax.Array, i: jax.Array, j: jax.Array) -> jax.Array:
    """The shared PRG key for the unordered pair {i, j}: both endpoints
    compute fold_in(fold_in(base, min), max) and get the same key."""
    lo = jnp.minimum(i, j)
    hi = jnp.maximum(i, j)
    return jax.random.fold_in(jax.random.fold_in(base, lo), hi)


def pairwise_mask(base: jax.Array, my_id: jax.Array, n_clients: int,
                  shape, round_index: jax.Array | int = 0) -> jax.Array:
    """Client `my_id`'s total mask: sum over peers j of sign(i,j)*PRG(i,j).

    Signs are antisymmetric (+ for j > i, - for j < i) and the PRG stream
    for a pair is identical at both endpoints, so summing all clients'
    masks gives exactly zero mod 2^32. `round_index` is folded in so masks
    are one-time per round.

    Implemented as a `fori_loop` so the traced program is O(1) in client
    count (one PRG op, n iterations at runtime) instead of unrolling
    n_clients full-tensor streams per protected tensor.
    """
    base = jax.random.fold_in(base, round_index)
    iinfo = jnp.iinfo(jnp.int32)
    my_id = jnp.asarray(my_id, jnp.int32)

    def body(j, total):
        j = jnp.asarray(j, jnp.int32)
        k = pair_key(base, my_id, j)
        m = jax.random.randint(k, shape, iinfo.min, iinfo.max,
                               dtype=jnp.int32)
        sign = jnp.sign(j - my_id)
        return total + sign * m

    return lax.fori_loop(0, n_clients, body, jnp.zeros(shape, jnp.int32))


# Keras get_weights() enumerates each layer's variables in creation order:
# kernel before bias (Conv2D/Dense), gamma(scale) -> beta(bias) -> moving
# mean -> moving var (BatchNorm). jax's dict flatten is alphabetical, so
# ordered selection must re-rank within a layer too.
_WITHIN_LAYER_RANK = {"kernel": 0, "depthwise_kernel": 0, "scale": 0,
                      "bias": 1, "mean": 2, "var": 3}


def first_fraction_selection(tree, percent: float,
                             layer_order: tuple[str, ...] | None = None):
    """Boolean pytree: True for the first int(L * percent) tensors — the
    reference's partial-encryption selection (secure_fed_model.py:115-121
    slices `self.weights[:num_enc]`, i.e. Keras get_weights() order).

    With `layer_order` (a Module's `layer_names`), "first" follows the
    model's layer order with Keras within-layer variable order — matching
    the reference's get_weights() enumeration for Sequential models.
    Without it, jax's (alphabetical) flatten order is used; that is a
    well-defined deterministic order but NOT the reference's, so callers
    wanting parity must pass the order.

    For models with mutable state (BatchNorm), use
    `first_fraction_selection_weights` — the reference slices the FULL
    get_weights() list, which interleaves moving statistics.
    """
    return first_fraction_selection_weights(tree, {}, percent,
                                            layer_order)[0]


# Auto-selection threshold for the fused Pallas mask kernel (secure
# fedavg mask_impl="auto"): measured on a v5 lite chip with dispatch
# overhead amortized INSIDE one jit (experiments/mask_crossover.jsonl),
# the fused kernel never loses — 1.04x at 262k elements rising to 2.48x
# at 33.5M — but below ~4M elements the win is ~0.1 ms (noise) while
# the round path pays one kernel call per local client; above it the
# win is >=1.5x of a cost that actually matters. Off-TPU, interpret
# mode makes the kernel unusable, so auto always resolves to threefry.
MASK_PALLAS_MIN_ELEMS = 4_194_304


def first_fraction_selection_weights(params, state, percent: float,
                                     layer_order: tuple[str, ...] | None
                                     = None):
    """`first_fraction_selection` over the FULL get_weights() enumeration:
    trainable params AND mutable state (BN moving statistics) interleaved
    in model layer order, which is what the reference actually slices —
    Keras get_weights() yields gamma, beta, moving_mean, moving_var per
    BatchNorm layer and `self.weights[:num_enc]` cuts across that list
    (secure_fed_model.py:115-121). Selecting over params alone would
    protect a different tensor set for any BN-bearing model.

    Returns ``(params_flags, state_flags)`` boolean pytrees; the count of
    True flags across both is ``int((P + S) * percent)``. For stateless
    models this degrades to exactly `first_fraction_selection(params)`.
    """
    p_paths = leaf_paths(params)
    s_paths = leaf_paths(state)
    paths = p_paths + s_paths
    n_enc = int(len(paths) * percent)
    flags = [False] * len(paths)
    for i in ranked_indices(paths, layer_order)[:n_enc]:
        flags[i] = True
    _, p_def = jax.tree.flatten(params)
    _, s_def = jax.tree.flatten(state)
    return (jax.tree.unflatten(p_def, flags[:len(p_paths)]),
            jax.tree.unflatten(s_def, flags[len(p_paths):]))


def ranked_indices(paths: list[tuple[str, ...]],
                   layer_order: tuple[str, ...] | None) -> list[int]:
    """Permutation of range(len(paths)) ranking leaf paths in model layer
    order (Keras get_weights() enumeration); identity without an order.

    `layer_order` entries may be dotted paths ("backbone.block1_conv1") as
    produced by `core.classifier`; a leaf is assigned the longest matching
    prefix of its own dotted path, so nested composites rank by their true
    layer order rather than collapsing to the top-level key.
    """
    if not layer_order:
        return list(range(len(paths)))
    order_index = {name: i for i, name in enumerate(layer_order)}

    def rank(path):
        li = len(layer_order)
        # longest-prefix match, INCLUDING the full path (a length-1 path's
        # only prefix is itself)
        for k in range(len(path), 0, -1):
            hit = order_index.get(".".join(path[:k]))
            if hit is not None:
                li = hit
                break
        wi = _WITHIN_LAYER_RANK.get(path[-1], 1)
        return (li, wi, path)

    return sorted(range(len(paths)), key=lambda i: rank(paths[i]))


def leaf_paths(tree) -> list[tuple[str, ...]]:
    """Key paths of a pytree's leaves in jax flatten order."""
    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [tuple(k.key for k in p) for p, _ in paths_and_leaves]


def pack_leaves(leaves, dtype=jnp.float32, *, lead_axes: int = 0):
    """Concatenate arrays into ONE flat vector (+ static split metadata).

    The round boundary uses this to turn per-tensor collectives into a
    single psum/pmean over one buffer — O(1) collectives per round
    instead of O(tensors), and one PRG stream covers every protected
    element. Returns (flat, meta); `unpack_leaves(flat, meta)` inverts.

    `lead_axes=n` treats each leaf's first n axes as batch dims (the
    k-clients-per-device round stacks client updates on a leading axis):
    the result is [*lead, P] and the meta describes the per-item tail
    shapes, so `unpack_leaves` recovers single-item leaves.
    """
    shapes = [tuple(x.shape[lead_axes:]) for x in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dtypes = [x.dtype for x in leaves]
    if not leaves:
        return jnp.zeros((0,), dtype), (sizes, shapes, dtypes)
    lead = leaves[0].shape[:lead_axes]
    flat = jnp.concatenate(
        [x.reshape(lead + (-1,)).astype(dtype) for x in leaves],
        axis=lead_axes)
    return flat, (sizes, shapes, dtypes)


def unpack_leaves(flat, meta):
    sizes, shapes, dtypes = meta
    out, off = [], 0
    for size, shape, dt in zip(sizes, shapes, dtypes):
        out.append(flat[off:off + size].reshape(shape).astype(dt))
        off += size
    return out
