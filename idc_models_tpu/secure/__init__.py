from idc_models_tpu.secure.masking import (  # noqa: F401
    choose_scale_bits,
    dequantize,
    first_fraction_selection,
    first_fraction_selection_weights,
    pairwise_mask,
    quantize,
)
from idc_models_tpu.secure.fedavg import (  # noqa: F401
    make_secure_fedavg_round,
    resolve_mask_impl,
)
