"""Secure-aggregation FedAvg: the TPU pairwise-mask round and the
host-side Paillier parity classes.

Capability parity with the reference's secure federated stack (SURVEY.md
C12-C15, D4; secure_fed_model.py:101-236):

- each client trains E local epochs on its private shard,
- "encrypts" a `percent` fraction of its weight tensors,
- the server aggregates an (unweighted, quirk Q7) elementwise mean while
  only ever seeing ciphertext for the protected tensors,
- clients decrypt the aggregate and adopt it,
- per-round evaluation on a global held-out set (loss / BinaryAccuracy /
  AUROC — C16) is the caller's step (see cli.secure_fed).

The TPU fast path replaces Paillier with pairwise one-time masks
(`secure.masking`): inside one jitted `shard_map` program the protected
tensors are quantized to int32, masked with antisymmetric pairwise PRG
streams, and `psum`-ed — the sum the "server" observes per device is
uniformly random, but the masks cancel bit-for-bit and the dequantized
result equals the plain mean to quantization precision (2^-scale_bits).
Unprotected tensors ride a plain `pmean`, mirroring the reference's
partial encryption.

The host-side `PaillierClient` / `PaillierServer` classes reproduce the
reference's object-level protocol (Client.client_fit / enc_model /
client_update, Server.aggregate — secure_fed_model.py:101-168) with the
from-scratch `secure.paillier` in place of `phe`, kept as the
cross-checkable reference mode for the masking path.
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib
from idc_models_tpu.federated.fedavg import (
    ServerState, finite_clients, make_local_trainer,
)
from idc_models_tpu.models import core
from idc_models_tpu.secure import masking
from idc_models_tpu.secure.paillier import (
    PaillierPrivateKey, PaillierPublicKey,
)

LossFn = Callable[[jax.Array, jax.Array], jax.Array]
from idc_models_tpu.compat import shard_map

# Protected model_state tensors (BN moving statistics) are prescaled by
# 1/256 before quantization and rescaled after aggregation: ImageNet-scale
# BN moving variances run in the hundreds-to-thousands, far outside the
# +-clip_abs=64 weight clipping range, and clipping them would silently
# corrupt the server's BN state. The power-of-two prescale is exact in
# fp32, identical on every client (so the mask algebra and layout
# invariance are untouched), extends the state range to +-16384, and
# costs state resolution only (256 * 2^-scale_bits ~ 1e-4 absolute —
# noise-level for moving statistics). Weights keep full resolution.
_STATE_PRESCALE = 256.0


def resolve_mask_impl(model: core.Module, percent: float, *,
                      platform: str | None = None) -> str:
    """Resolve ``mask_impl="auto"``: the fused Pallas kernel iff we are
    on a TPU backend AND the protected buffer (the first `percent` of
    the full get_weights() enumeration) reaches
    `masking.MASK_PALLAS_MIN_ELEMS` — the crossover measured in
    experiments/mask_crossover.jsonl (see the constant's comment).
    Pure and cheap: element counts come from `jax.eval_shape`, no
    arrays are materialized. "auto" is an explicit opt-in, not the
    round default: it trades threefry's cryptographic mask stream for
    the hash-PRG kernel's throughput (see make_secure_fedavg_round's
    docstring for the threat-model caveat)."""
    platform = platform if platform is not None else jax.default_backend()
    if platform not in ("tpu", "axon"):
        return "threefry"
    p, s = jax.eval_shape(
        lambda rng: (lambda v: (v.params, v.state))(model.init(rng)),
        jax.random.key(0))
    pf, sf = masking.first_fraction_selection_weights(
        p, s, percent, model.layer_names)
    n_prot = sum(
        leaf.size for leaf, flag in zip(
            jax.tree.leaves(p) + jax.tree.leaves(s),
            jax.tree.leaves(pf) + jax.tree.leaves(sf)) if flag)
    return ("pallas" if n_prot >= masking.MASK_PALLAS_MIN_ELEMS
            else "threefry")


def make_secure_fedavg_round(
    model: core.Module,
    optimizer: optax.GradientTransformation,
    loss_fn: LossFn,
    mesh: Mesh,
    *,
    percent: float,
    local_epochs: int = 5,
    batch_size: int = 32,
    scale_bits: int | None = None,
    clip_abs: float = masking.DEFAULT_CLIP_ABS,
    compute_dtype=jnp.float32,
    mask_impl: str = "threefry",
    recover_nonfinite: bool = True,
    aggregator=None,
):
    """Build the jitted one-round secure-FedAvg program.

    Returns ``round_fn(server_state, images [C,S,...], labels [C,S], rng)
    -> (server_state, metrics)``. The aggregate is the unweighted mean
    (reference parity, quirk Q7); the first `percent` fraction of the
    model's weight tensors — params AND mutable state interleaved in
    model layer order, Keras get_weights() enumeration, matching both
    the reference's slice (secure_fed_model.py:115-121) and this
    module's PaillierClient — go through the masked integer path.

    The round boundary packs the protected tensors into ONE flat int32
    buffer (single masked psum) and everything else — unprotected params
    and model state — into ONE flat f32 buffer (single pmean): exactly
    two weight collectives per round regardless of model depth.

    `mask_impl` selects how the flat protected buffer is quantized+masked:
    ``"threefry"`` (default) is XLA's threefry PRG via
    `masking.pairwise_mask`; ``"pallas"`` is the fused single-pass Pallas
    kernel (`ops.secure_masking_kernel.fused_masked_quantize`, hash-PRG,
    interpret mode off-TPU); ``"auto"`` resolves at build time via
    `resolve_mask_impl` — pallas on TPU when the protected buffer
    reaches `masking.MASK_PALLAS_MIN_ELEMS` (the measured crossover,
    BASELINE.md), threefry otherwise. The default stays threefry ON
    PURPOSE: the Pallas kernel's murmur-style hash PRG is fast but NOT
    cryptographic, and mask unpredictability against a curious
    aggregator — not just exact cancellation — is the property the
    protocol exists for. Opt into "auto"/"pallas" only where the threat
    model tolerates a non-cryptographic mask stream (e.g. benchmarking,
    or aggregators trusted not to attack masks). Both impls cancel
    exactly under psum; they produce different (each internally
    consistent) mask streams, so all clients of one aggregation must use
    the same impl — guaranteed here since the whole round is one
    program.

    `scale_bits` defaults to the largest fixed-point precision whose
    cross-client sum of clipped (+-clip_abs) values cannot overflow int32
    (`masking.choose_scale_bits`) — overflow would silently corrupt the
    aggregate, so the headroom is budgeted, not assumed.

    ``recover_nonfinite`` (default on) is failure handling for a path
    where DROPPING a participant is cryptographically hard: removing a
    client from the unweighted masked mean would leave its pairwise
    masks uncancelled (full Bonawitz dropout recovery needs
    secret-shared mask reconstruction — out of scope). Instead, a client
    whose local update goes non-finite has its update replaced with the
    incoming global weights BEFORE quantization/masking — a no-op
    contribution that keeps the mask algebra and the divisor intact —
    and is excluded from the training metrics;
    ``metrics["clients_recovered"]`` reports the count. The reference
    has no failure handling at all (SURVEY.md §5).

    ``aggregator`` (federated/robust.py) must be SECURE-COMPATIBLE: the
    masked path sums quantized per-client contributions, so only
    aggregators that are a per-client transform followed by a mean can
    ride it — "mean" (default) and "norm_clip" (clip each client's
    update delta before quantization/masking; the Byzantine-influence
    bound then holds against the masked aggregate too, and
    ``metrics["clients_clipped"]`` reports the count). trimmed_mean /
    median need plaintext cross-client views per coordinate — exactly
    what the protocol forbids — and are rejected at build time.
    """
    from idc_models_tpu.federated import robust

    agg = robust.get_aggregator(aggregator)
    if not agg.secure_compatible:
        raise ValueError(
            f"aggregator {agg!r} is not compatible with secure "
            f"aggregation: the masked path sums quantized per-client "
            f"contributions, so only per-client-transform + mean "
            f"aggregators (mean, norm_clip) can ride it; trimmed_mean/"
            f"median need plaintext cross-client views, which the "
            f"protocol exists to prevent — use the plain "
            f"make_fedavg_round for those")
    if mask_impl not in ("auto", "threefry", "pallas"):
        raise ValueError(f"unknown mask_impl {mask_impl!r}")
    # platform decisions key on the MESH's devices, not the process
    # default backend — a CPU-device client mesh in a TPU-backed
    # process must neither auto-select the Mosaic kernel nor lower it
    # uninterpreted (same convention as ring_attention's interp_mode)
    mesh_platform = mesh.devices.flat[0].platform
    if mask_impl == "auto":
        mask_impl = resolve_mask_impl(model, percent,
                                      platform=mesh_platform)
    n_devices = mesh.shape[meshlib.CLIENT_AXIS]
    local_train = make_local_trainer(
        model, optimizer, loss_fn, local_epochs=local_epochs,
        batch_size=batch_size, compute_dtype=compute_dtype)

    def make_per_device(n_total: int, n_real: int, k: int, sb: int):
        def per_device(params, model_state, imgs, labels, rng, mask_key):
            # [k, S, ...] block: this device's k clients. Masks belong to
            # CLIENTS (global ids), so the cancellation algebra — and the
            # aggregate, bit-for-bit on the int32 path — is invariant to
            # how clients are laid out over devices.
            #
            # Clients with id >= n_real are mesh-padding DUMMIES
            # (VERDICT r2 #6): they participate fully in mask generation
            # — every pairwise stream must appear at both endpoints or
            # nothing cancels — but their quantized update is forced to
            # zero and the divisor stays n_real, so the aggregate is
            # bit-identical (int32 path) to the same clients run on a
            # mesh that divides their count, while using every device.
            dev = collectives.axis_index(meshlib.CLIENT_AXIS)
            cids = dev * k + jnp.arange(k)
            real = cids < n_real
            rngs = jax.vmap(lambda c: jax.random.fold_in(rng, c))(cids)

            new_params, new_model_state, (losses, accs) = jax.vmap(
                local_train, in_axes=(None, None, 0, 0, 0))(
                params, model_state, imgs, labels, rngs)

            ok = jnp.ones((k,), bool)
            recovered = jnp.zeros((), jnp.float32)
            if recover_nonfinite:
                # failure recovery: a diverged client contributes the
                # incoming global weights instead of garbage (see the
                # factory docstring — dropping would break the masks)
                ok = finite_clients(k, new_params, new_model_state, losses)
                recovered = collectives.psum(
                    jnp.sum(~ok & real).astype(jnp.float32),
                    meshlib.CLIENT_AXIS)

                def keep(new, old):
                    okr = ok.reshape((k,) + (1,) * (new.ndim - 1))
                    return jnp.where(okr, new, old[None])

                new_params = jax.tree.map(keep, new_params, params)
                new_model_state = jax.tree.map(keep, new_model_state,
                                               model_state)

            # secure-compatible robustness: the per-client transform
            # (e.g. norm_clip's delta clipping) runs BEFORE quantization
            # and masking, so the aggregate the server unmasks is
            # already influence-bounded; metrics count real live clients
            upd, per_client_m = agg.per_client(
                {"params": new_params, "model_state": new_model_state},
                {"params": params, "model_state": model_state})
            new_params = upd["params"]
            new_model_state = upd["model_state"]
            agg_metrics = {
                key: collectives.psum(
                    jnp.sum(jnp.where(ok & real, vals, 0.0)),
                    meshlib.CLIENT_AXIS)
                for key, vals in per_client_m.items()}

            # "First fraction" follows the model's layer order over the
            # FULL get_weights() enumeration — params and BN moving
            # statistics interleaved, exactly the list the reference
            # slices (secure_fed_model.py:115-121) — not jax's
            # alphabetical flatten and not params alone.
            p_protect, s_protect = masking.first_fraction_selection_weights(
                new_params, new_model_state, percent, model.layer_names)
            leaves, treedef = jax.tree.flatten(new_params)
            state_leaves, state_def = jax.tree.flatten(new_model_state)
            all_leaves = leaves + state_leaves
            all_flags = (jax.tree.leaves(p_protect)
                         + jax.tree.leaves(s_protect))

            is_state = [False] * len(leaves) + [True] * len(state_leaves)
            # protected state rides the int path at 1/256 scale (see
            # _STATE_PRESCALE above) so BN moving variances clear the
            # clip range that is sized for weights
            prot = [x / _STATE_PRESCALE if s else x
                    for x, f, s in zip(all_leaves, all_flags, is_state)
                    if f]
            prot_scales = [s for s, f in zip(is_state, all_flags) if f]
            plain = [x for x, f in zip(all_leaves, all_flags) if not f]

            # -- protected: quantize+mask per client, local int32 sum
            #    (mod 2^32, exactly like psum), then ONE psum ----------
            prot_agg: list = []
            clip_saturated = jnp.zeros((), jnp.float32)
            if prot:
                flat_k, meta = masking.pack_leaves(prot, lead_axes=1)
                # dummies contribute exactly zero (quantize(0) == 0), so
                # only their masks enter the sum — and those cancel
                flat_k = jnp.where(real[:, None], flat_k, 0.0)
                # Saturation detection (advisor r3): a protected value at
                # the clip boundary — e.g. a BN moving variance beyond
                # clip_abs * _STATE_PRESCALE on unnormalized inputs — is
                # silently truncated into the aggregate; count and
                # surface it so callers can raise clip_abs/prescale
                # instead of debugging corrupted server BN state.
                clip_saturated = collectives.psum(
                    jnp.sum(jnp.abs(flat_k) >= clip_abs)
                    .astype(jnp.float32), meshlib.CLIENT_AXIS)
                if mask_impl == "pallas":
                    from idc_models_tpu.ops import secure_masking_kernel as smk

                    seed = jax.random.bits(mask_key, (), jnp.uint32)
                    interp = mesh_platform not in ("tpu", "axon")
                    masked_total = jnp.zeros((flat_k.shape[1],), jnp.int32)
                    for i in range(k):  # k is static and small
                        seeds, signs = smk.pair_seeds_and_signs(
                            seed, cids[i], n_total)
                        masked_total = masked_total + smk.fused_masked_quantize(
                            flat_k[i], seeds, signs, scale_bits=sb,
                            clip_abs=clip_abs, interpret=interp)
                else:
                    q = masking.quantize(flat_k, sb, clip_abs=clip_abs)
                    masks = jax.vmap(
                        lambda c: masking.pairwise_mask(
                            mask_key, c, n_total, (flat_k.shape[1],)))(cids)
                    masked_total = (q + masks).sum(axis=0)
                summed = collectives.psum(masked_total, meshlib.CLIENT_AXIS)
                deq = masking.dequantize(summed, sb, count=n_real)
                prot_agg = [x * _STATE_PRESCALE if s else x
                            for x, s in zip(masking.unpack_leaves(deq, meta),
                                            prot_scales)]

            # -- everything else (unprotected params + state): local sum
            #    then ONE psum / C_real (the unweighted mean, quirk Q7) --
            plain_agg: list = []
            if plain:
                flat_k, meta = masking.pack_leaves(plain, lead_axes=1)
                flat_k = jnp.where(real[:, None], flat_k, 0.0)
                mean = collectives.psum(flat_k.sum(axis=0),
                                        meshlib.CLIENT_AXIS) / n_real
                plain_agg = masking.unpack_leaves(mean, meta)

            prot_it, plain_it = iter(prot_agg), iter(plain_agg)
            agg_all = [next(prot_it) if f else next(plain_it)
                       for f in all_flags]
            agg_params = jax.tree.unflatten(treedef, agg_all[:len(leaves)])
            agg_state = jax.tree.unflatten(state_def, agg_all[len(leaves):])
            # training metrics over the clients that actually trained
            # (weighted_pmean_local masks dead clients' NaNs exactly
            # like the plain round); NaN — not a perfect-looking 0.0 —
            # if every client diverged
            live = ok & real
            alive = collectives.psum(
                live.astype(jnp.float32).sum(), meshlib.CLIENT_AXIS)
            metrics = collectives.weighted_pmean_local(
                jax.tree.map(
                    lambda x: jnp.mean(x, axis=tuple(range(1, x.ndim))),
                    {"loss": losses, "accuracy": accs}),
                live.astype(jnp.float32), meshlib.CLIENT_AXIS)
            metrics = jax.tree.map(
                lambda x: jnp.where(alive > 0, x, jnp.float32(jnp.nan)),
                metrics)
            metrics["clients_recovered"] = recovered
            # same all-dead masking as the trained metrics: a round where
            # no real client survives reports NaN across the board, not a
            # lone finite 0 that a finite-filtering consumer would keep
            metrics["clip_saturated"] = jnp.where(
                alive > 0, clip_saturated, jnp.float32(jnp.nan))
            metrics.update(agg_metrics)
            return agg_params, agg_state, metrics

        return per_device

    def make_round(n_total: int, n_real: int, sb: int):
        mapped = shard_map(
            make_per_device(n_total, n_real, n_total // n_devices, sb),
            mesh=mesh,
            in_specs=(P(), P(), P(meshlib.CLIENT_AXIS),
                      P(meshlib.CLIENT_AXIS), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )

        def round_fn(server: ServerState, images, labels, rng):
            # One-time masks: the mask key is derived from the fresh
            # per-round rng (distinct fold from the training rng), so
            # streams are never reused across rounds.
            params, model_state, metrics = mapped(
                server.params, server.model_state, images, labels, rng,
                jax.random.fold_in(rng, jnp.int32(-1)))
            new_server = server.replace(
                round=server.round + 1, params=params,
                model_state=model_state)
            return new_server, metrics

        return jax.jit(round_fn, donate_argnums=(0,))

    rounds: dict[int, Callable] = {}
    warned_pad: list = []  # one-time flag for the host-resident pad path

    def round_fn(server: ServerState, images, labels, rng, *,
                 n_real: int | None = None):
        # Non-dividing client counts run on the FULL mesh by padding the
        # client axis with dummy clients: they train on zero shards (the
        # vmap lane is there either way), join mask generation so every
        # pairwise stream cancels, and contribute a forced-zero quantized
        # update with divisor n_real — the aggregate is bit-identical
        # (int32 path) to a run on a dividing mesh, on all devices.
        #
        # Callers with device-resident data should pre-pad ONCE and pass
        # `n_real` (see cli._run_secure): the convenience pad below
        # concatenates fresh arrays every round, which re-uploads the
        # whole stacked dataset on host-resident inputs.
        if n_real is None:
            n_real = images.shape[0]
        pad = -images.shape[0] % n_devices
        if pad:
            if not isinstance(images, jax.Array) and not warned_pad:
                import warnings

                warnings.warn(
                    f"secure round_fn is padding {images.shape[0]} "
                    f"host-resident clients to {images.shape[0] + pad} "
                    f"every round, re-uploading the stacked dataset "
                    f"each call; pre-pad once on device and pass "
                    f"n_real={n_real} (see cli._run_secure) for the "
                    f"steady-state path", stacklevel=2)
                warned_pad.append(True)
            images = jnp.asarray(images)  # settles host dtypes (f64->f32)
            labels = jnp.asarray(labels)
            images = jnp.concatenate(
                [images,
                 jnp.zeros((pad,) + tuple(images.shape[1:]),
                           images.dtype)])
            labels = jnp.concatenate(
                [labels,
                 jnp.zeros((pad,) + tuple(labels.shape[1:]),
                           labels.dtype)])
        n_total = images.shape[0]  # post-pad client-slot count
        if (n_total, n_real) not in rounds:
            # headroom is budgeted over the REAL contributions; dummies
            # add exact zeros
            sb = (scale_bits if scale_bits is not None
                  else masking.choose_scale_bits(n_real, clip_abs))
            rounds[(n_total, n_real)] = make_round(n_total, n_real, sb)
        return rounds[(n_total, n_real)](server, images, labels, rng)

    return round_fn


# ---------------------------------------------------------------------------
# Host-side Paillier parity mode (the reference's actual mechanism)
# ---------------------------------------------------------------------------

class PaillierClient:
    """Object-level parity with the reference's `Client`
    (secure_fed_model.py:101-154): owns a model replica and a private
    shard; trains locally, encrypts the first `int(L * percent)` weight
    tensors scalar-by-scalar, decrypts aggregates, and adopts them."""

    def __init__(self, model: core.Module,
                 optimizer: optax.GradientTransformation, loss_fn: LossFn,
                 images: np.ndarray, labels: np.ndarray, client_id: int,
                 percent: float, public_key: PaillierPublicKey,
                 private_key: PaillierPrivateKey, *,
                 local_epochs: int = 5, batch_size: int = 32, seed: int = 0):
        self.model = model
        self.percent = percent
        self.public_key = public_key
        self.private_key = private_key
        self.images = images
        self.labels = labels
        self.client_id = client_id
        variables = model.init(jax.random.key(seed))
        self.params = variables.params
        self.model_state = variables.state
        self._trainer = jax.jit(make_local_trainer(
            model, optimizer, loss_fn, local_epochs=local_epochs,
            batch_size=batch_size))
        self._rng = jax.random.fold_in(jax.random.key(seed + 1), client_id)

    def _flat_weights(self):
        """All model weights — params AND mutable state (BN moving stats),
        like Keras get_weights() (the reference exchanges and averages the
        full list, secure_fed_model.py:115,160-168) — as float64 ndarrays
        in model layer order. Returns (ordered leaves, restore fn)."""
        p_leaves, p_def = jax.tree.flatten(self.params)
        s_leaves, s_def = jax.tree.flatten(self.model_state)
        paths = (masking.leaf_paths(self.params)
                 + masking.leaf_paths(self.model_state))
        order = masking.ranked_indices(paths, self.model.layer_names)
        combined = [np.asarray(x, np.float64)
                    for x in jax.device_get(p_leaves + s_leaves)]
        ordered = [combined[i] for i in order]

        def restore(ordered_tensors):
            flat = [None] * len(combined)
            for slot, t in zip(order, ordered_tensors):
                flat[slot] = jnp.asarray(np.asarray(t), jnp.float32)
            params = jax.tree.unflatten(p_def, flat[:len(p_leaves)])
            state = jax.tree.unflatten(s_def, flat[len(p_leaves):])
            return params, state

        return ordered, restore

    def _num_encrypted(self) -> int:
        n = len(jax.tree.leaves(self.params)) + len(
            jax.tree.leaves(self.model_state))
        return int(n * self.percent)

    def client_fit(self):
        """Local epochs, then (possibly partially encrypted) weights out
        (secure_fed_model.py:131-141)."""
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.model_state, stats = self._trainer(
            self.params, self.model_state, jnp.asarray(self.images),
            jnp.asarray(self.labels), sub)
        return self.enc_model(), jax.device_get(stats)

    def enc_model(self):
        """Flat list of weight tensors in model layer order; the first
        `int(L*percent)` are object arrays of EncryptedNumber
        (secure_fed_model.py:115-121)."""
        leaves, _ = self._flat_weights()
        n_enc = self._num_encrypted()
        enc = np.vectorize(self.public_key.encrypt, otypes=[object])
        return [enc(leaf) if i < n_enc else leaf
                for i, leaf in enumerate(leaves)]

    def dec_model(self, tensors):
        n_enc = self._num_encrypted()
        dec = np.vectorize(self.private_key.decrypt, otypes=[np.float64])
        return [dec(t) if i < n_enc else t for i, t in enumerate(tensors)]

    def client_update(self, aggregated):
        """Decrypt + adopt the aggregate — params and moving statistics
        both (secure_fed_model.py:143-149)."""
        plain = self.dec_model(aggregated)
        _, restore = self._flat_weights()
        self.params, self.model_state = restore(plain)

    def evaluate(self, images: np.ndarray, labels: np.ndarray, loss_fn: LossFn):
        """loss / binary accuracy / AUROC on a held-out set
        (secure_fed_model.py:152-154 with the C16 AUROC metric)."""
        from idc_models_tpu.train import metrics as metrics_lib

        logits, _ = self.model.apply(self.params, self.model_state,
                                     jnp.asarray(images), train=False)
        logits = logits.astype(jnp.float32)
        return {
            "loss": float(loss_fn(logits, jnp.asarray(labels))),
            "accuracy": float(metrics_lib.binary_accuracy(
                logits, jnp.asarray(labels))),
            "auroc": float(metrics_lib.auroc(
                jax.nn.sigmoid(logits), jnp.asarray(labels))),
        }


class PaillierServer:
    """Parity with the reference's stateless `Server.aggregate`
    (secure_fed_model.py:156-168): elementwise unweighted mean per tensor,
    operating transparently on EncryptedNumber object arrays (homomorphic
    add + scalar divide) and plain ndarrays alike."""

    @staticmethod
    def aggregate(client_weights):
        n = len(client_weights)
        out = []
        for tensors in zip(*client_weights):
            acc = tensors[0]
            for t in tensors[1:]:
                acc = acc + t
            out.append(acc / n)
        return out
