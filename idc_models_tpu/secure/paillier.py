"""Paillier additively-homomorphic encryption (host-side parity mode).

The reference encrypts weights with the `phe` library
(secure_fed_model.py:32,79,109-129): `generate_paillier_keypair()`, scalar
`encrypt`/`decrypt`, ciphertext addition and plaintext-scalar
multiplication — which is what makes the server's elementwise *mean* work
in ciphertext space (homomorphic add + multiply-by-1/K,
secure_fed_model.py:160-168). `phe` is not available in this environment,
so this module is a from-scratch implementation of the same surface:

- `generate_paillier_keypair(n_length)` -> (PaillierPublicKey, PaillierPrivateKey)
- `pub.encrypt(float) -> EncryptedNumber`, `priv.decrypt(EncryptedNumber) -> float`
- `EncryptedNumber + EncryptedNumber`, `EncryptedNumber * float`,
  `EncryptedNumber / int`

Floats use base-2 mantissa/exponent encoding (like phe's EncodedNumber):
value = mantissa * 2**exponent with mantissa taken mod n (negatives wrap).
Ciphertext addition aligns exponents by scaling the higher-exponent
operand; scalar multiplication raises the ciphertext to the scalar's
mantissa and adds exponents. This is bignum math on the host CPU — it does
not (and should not) touch the TPU; the TPU fast path is
`secure.masking`. Keys default to 2048 bits; tests use smaller keys for
speed.

Paillier with g = n + 1: enc(m) = (1 + n*m) * r^n mod n^2;
dec(c) = L(c^lambda mod n^2) * mu mod n, L(x) = (x - 1) / n.
"""

from __future__ import annotations

import dataclasses
import math
import secrets

_MANTISSA_BITS = 53  # float64 precision


def _is_probable_prime(n: int, rounds: int = 40) -> bool:
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for _ in range(rounds):
        a = secrets.randbelow(n - 3) + 2
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = (x * x) % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _random_prime(bits: int) -> int:
    while True:
        p = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if _is_probable_prime(p):
            return p


@dataclasses.dataclass(frozen=True)
class PaillierPublicKey:
    n: int

    @property
    def nsquare(self) -> int:
        return self.n * self.n

    def raw_encrypt(self, m: int) -> int:
        """Encrypt an integer already reduced mod n."""
        n, n2 = self.n, self.nsquare
        while True:
            r = secrets.randbelow(n)
            if r > 0 and math.gcd(r, n) == 1:
                break
        return ((1 + n * m) % n2) * pow(r, n, n2) % n2

    def encrypt(self, value: float | int) -> "EncryptedNumber":
        mantissa, exponent = _encode(value)
        return EncryptedNumber(self, self.raw_encrypt(mantissa % self.n),
                               exponent)


@dataclasses.dataclass(frozen=True)
class PaillierPrivateKey:
    public_key: PaillierPublicKey
    p: int
    q: int

    @property
    def _lambda(self) -> int:
        return math.lcm(self.p - 1, self.q - 1)

    @property
    def _mu(self) -> int:
        n = self.public_key.n
        lx = (pow(1 + n, self._lambda, n * n) - 1) // n
        return pow(lx, -1, n)

    def raw_decrypt(self, ciphertext: int) -> int:
        n = self.public_key.n
        lx = (pow(ciphertext, self._lambda, n * n) - 1) // n
        return (lx * self._mu) % n

    def decrypt(self, enc: "EncryptedNumber") -> float:
        m = self.raw_decrypt(enc.ciphertext)
        n = self.public_key.n
        if m > n // 2:  # negative wraparound
            m -= n
        return _decode(m, enc.exponent)


def generate_paillier_keypair(n_length: int = 2048
                              ) -> tuple[PaillierPublicKey, PaillierPrivateKey]:
    """Keypair generation (parity: phe.generate_paillier_keypair,
    secure_fed_model.py:79)."""
    while True:
        p = _random_prime(n_length // 2)
        q = _random_prime(n_length // 2)
        if p != q:
            break
    pub = PaillierPublicKey(p * q)
    return pub, PaillierPrivateKey(pub, p, q)


def _encode(value: float | int) -> tuple[int, int]:
    """value -> (mantissa, exponent) with value ~= mantissa * 2**exponent."""
    if value == 0:
        return 0, 0
    frac, exp = math.frexp(float(value))
    mantissa = int(round(frac * (1 << _MANTISSA_BITS)))
    return mantissa, exp - _MANTISSA_BITS


def _decode(mantissa: int, exponent: int) -> float:
    return math.ldexp(mantissa, exponent)


@dataclasses.dataclass(frozen=True)
class EncryptedNumber:
    """A Paillier ciphertext with a fixed-point exponent.

    Supports the operations the reference's server applies to encrypted
    tensors: ciphertext + ciphertext, ciphertext * scalar, ciphertext /
    scalar (secure_fed_model.py:160-168 computes mean via add and divide).
    """

    public_key: PaillierPublicKey
    ciphertext: int
    exponent: int
    # upper bound on bits of |plaintext mantissa|, tracked through every
    # homomorphic op: sign decode (negative wraps above n/2) breaks as
    # soon as a mantissa reaches n/2, silently, so each op budgets its
    # growth and _scaled_to / __mul__ raise before wrap can happen
    mantissa_bits: int = _MANTISSA_BITS

    def _check_bits(self, bits: int, what: str) -> int:
        if bits > self.public_key.n.bit_length() - 2:
            raise ValueError(
                f"{what} would overflow the "
                f"{self.public_key.n.bit_length()}-bit modulus (mantissa "
                f"bound 2^{bits} reaches n/2 and would wrap, decrypting "
                f"to garbage — use a larger key or rescale operands)")
        return bits

    def _scaled_to(self, exponent: int) -> "EncryptedNumber":
        """Re-express at a smaller exponent (multiply mantissa by 2^diff).

        Guarded against encoding overflow (mirroring phe): easiest to hit
        by adding operands of wildly different magnitudes under a small
        (e.g. 512-bit) key.
        """
        if exponent > self.exponent:
            raise ValueError("can only decrease exponent")
        diff = self.exponent - exponent
        bits = self._check_bits(self.mantissa_bits + diff,
                                f"exponent alignment by 2^{diff}")
        factor = 1 << diff
        c = pow(self.ciphertext, factor, self.public_key.nsquare)
        return EncryptedNumber(self.public_key, c, exponent, bits)

    def __add__(self, other):
        if isinstance(other, EncryptedNumber):
            if other.public_key is not self.public_key and \
                    other.public_key != self.public_key:
                raise ValueError("mismatched public keys")
            exp = min(self.exponent, other.exponent)
            a = self._scaled_to(exp)
            b = other._scaled_to(exp)
            bits = self._check_bits(
                max(a.mantissa_bits, b.mantissa_bits) + 1, "addition")
            c = (a.ciphertext * b.ciphertext) % self.public_key.nsquare
            return EncryptedNumber(self.public_key, c, exp, bits)
        return self + self.public_key.encrypt(other)

    __radd__ = __add__

    def __mul__(self, scalar: float | int) -> "EncryptedNumber":
        mantissa, exp = _encode(scalar)
        bits = self._check_bits(self.mantissa_bits + _MANTISSA_BITS,
                                "scalar multiplication")
        n, n2 = self.public_key.n, self.public_key.nsquare
        c = pow(self.ciphertext, mantissa % n, n2)
        return EncryptedNumber(self.public_key, c, self.exponent + exp, bits)

    __rmul__ = __mul__

    def __truediv__(self, scalar: float | int) -> "EncryptedNumber":
        return self * (1.0 / scalar)
