"""Training state: the explicit pytree that replaces Keras' compiled model.

The reference never owns its step function — `model.fit` / TFF internals do
(SURVEY.md §3.5). Here the full state (params, BN stats, optimizer state,
step counter) is one pytree, so checkpointing, federated broadcast, secure
masking, and sharding all operate on it uniformly.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import optax

from idc_models_tpu.models import core


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    model_state: Any          # BatchNorm moving statistics etc.
    opt_state: Any

    def replace(self, **kw) -> "TrainState":
        return dataclasses.replace(self, **kw)


def create_train_state(model: core.Module, optimizer: optax.GradientTransformation,
                       rng: jax.Array) -> TrainState:
    variables = model.init(rng)
    opt_state = optimizer.init(variables.params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables.params,
        model_state=variables.state,
        opt_state=opt_state,
    )


def rmsprop(learning_rate: float, *, rho: float = 0.9, eps: float = 1e-7,
            trainable_mask: Any | None = None) -> optax.GradientTransformation:
    """RMSprop matching Keras defaults (the reference's only optimizer —
    dist_model_tf_vgg.py:130, fed_model.py:208), with an optional
    trainability mask replacing freeze/recompile (quirk Q6)."""
    # eps_in_sqrt=False: Keras updates with g / (sqrt(nu) + eps); optax's
    # default puts eps inside the sqrt, which damps very differently at nu~0.
    import inspect

    if "eps_in_sqrt" in inspect.signature(optax.rmsprop).parameters:
        opt = optax.rmsprop(learning_rate, decay=rho, eps=eps,
                            eps_in_sqrt=False)
    else:
        # older optax has no eps_in_sqrt knob and hard-codes the
        # inside-the-sqrt form; hand-roll the same Keras-form transform.
        # The state is optax's own ScaleByRmsState(nu=...) inside the
        # standard two-element chain, so the opt_state PYTREE STRUCTURE
        # matches what new optax.rmsprop produces — checkpoints
        # round-trip across optax versions, and numerics agree.
        def _init(params):
            return optax.ScaleByRmsState(
                nu=jax.tree.map(jnp.zeros_like, params))

        def _update(updates, state, params=None):
            del params
            nu = jax.tree.map(lambda n, g: rho * n + (1 - rho) * g * g,
                              state.nu, updates)
            upd = jax.tree.map(lambda g, n: g / (jnp.sqrt(n) + eps),
                               updates, nu)
            return upd, optax.ScaleByRmsState(nu=nu)

        opt = optax.chain(
            optax.GradientTransformation(_init, _update),
            optax.scale(-learning_rate))
    return freeze_where(opt, trainable_mask)


def freeze_where(opt: optax.GradientTransformation,
                 trainable_mask: Any | None) -> optax.GradientTransformation:
    """Zero updates where mask is False. (optax.masked alone is NOT a
    freeze: it passes raw gradients through untransformed leaves.)"""
    if trainable_mask is None:
        return opt
    labels = jax.tree.map(lambda t: "train" if t else "freeze", trainable_mask)
    return optax.multi_transform(
        {"train": opt, "freeze": optax.set_to_zero()}, labels)
