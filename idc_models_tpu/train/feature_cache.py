"""Frozen-backbone feature cache for phase-2 fine-tuning.

A TPU-first optimization with no reference equivalent: during the
fine-tune phase only layers with Keras index >= fine_tune_at train
(dist_model_tf_vgg.py:144-147), so the frozen prefix of the backbone is
a *constant function* of each input image — recomputing it every step of
every epoch (as the reference's `model.fit` must) spends most of the
step's FLOPs and HBM traffic reproducing identical activations. Here the
prefix runs ONCE per dataset; phase 2 then trains only the live suffix
(+ GAP + head) on the cached features, keeping the MXU busy exclusively
on parameters that can actually change. For the flagship VGG16 config
(fine_tune_at=15: blocks 1-4 frozen), the live suffix is ~15% of the
forward FLOPs.

Numerics are unchanged: the frozen prefix is deterministic (no dropout in
any zoo backbone; BatchNorm below fine_tune_at is built frozen =
inference mode), so prefix-once + suffix-per-step computes the same
function as full-model-per-step, and `tests/test_feature_cache.py` pins
the cached and uncached phase-2 training trajectories against each other.

Splitting strategies: sequential backbones (VGG16) split at the first
live layer via `core.split_sequential`; non-sequential topologies
provide a model `splitter` built on `core.unit_backbone` (MobileNetV2
splits at inverted-residual unit edges, DenseNet201 at dense-layer /
transition edges — every unit is a pure function of its input, so
residual adds and dense concats stay whole). `plan_feature_cache`
returns None for models it cannot split (small_cnn) and callers fall
back to the uncached path.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.models import core
from idc_models_tpu.train.step import jit_data_parallel, replicate


@dataclasses.dataclass(frozen=True)
class FeatureCachePlan:
    """The split program: `prefix` (frozen, cache once) and
    `suffix_model` (train per step on cached features)."""

    prefix: core.Module          # backbone[:boundary]
    suffix_model: core.Module    # classifier(backbone[boundary:]) + GAP + head
    # first backbone layer of the SUFFIX (None: empty suffix). On the
    # sequential path this is the first live layer; on the unit-granular
    # splitter path it may be a frozen layer of the boundary unit (the
    # split rounds down to a unit edge).
    boundary: str | None
    suffix_keys: tuple[str, ...]  # backbone child keys the suffix owns


def _param_keys(module: core.Module) -> tuple[str, ...]:
    """The top-level param/state keys a section consumes: its children's
    keys for sequential composites, its layer_names for splitter-built
    flat sections."""
    if module.children:
        return tuple(k for k, _ in module.children)
    return module.layer_names


def plan_feature_cache(model: core.Module, layer_index: dict[str, int],
                       fine_tune_at: int, feature_dim: int,
                       num_outputs: int) -> FeatureCachePlan | None:
    """Split `model` (a `core.classifier` composite) at the fine-tune
    boundary. Sequential backbones (VGG) split at the first live layer;
    backbones with non-sequential topology provide a `splitter`
    (MobileNetV2: unit granularity). Returns None when the model is not
    splittable or nothing frozen precedes the boundary."""
    children = dict(model.children)
    backbone = children.get("backbone")
    if backbone is None:
        return None
    if backbone.children:
        keys = [k for k, _ in backbone.children]
        live = [k for k in keys
                if layer_index.get(k, -1) >= fine_tune_at]
        if live:
            boundary = live[0]
            if boundary == keys[0]:
                return None  # nothing frozen before the boundary — no win
            prefix, suffix_bb = core.split_sequential(backbone, boundary)
        else:
            # everything frozen: cache the backbone, train GAP+head only
            boundary = None
            prefix = backbone
            suffix_bb = core.subsequence(backbone, [],
                                         name=f"{backbone.name}[empty]")
    elif backbone.splitter is not None:
        split = backbone.splitter(fine_tune_at)
        if split is None:
            return None
        prefix, suffix_bb = split
        boundary = (suffix_bb.layer_names[0] if suffix_bb.layer_names
                    else None)
    else:
        return None
    suffix_model = core.classifier(suffix_bb, feature_dim, num_outputs,
                                   name=f"{model.name}_suffix")
    return FeatureCachePlan(prefix=prefix, suffix_model=suffix_model,
                            boundary=boundary,
                            suffix_keys=_param_keys(suffix_bb))


def _subset(tree: dict, keys) -> dict:
    return {k: tree[k] for k in keys if k in tree}


def suffix_variables(plan: FeatureCachePlan, params, model_state):
    """Project the full model's {"backbone", "head"} trees onto the
    suffix model's param/state structure (shared keys, shared arrays)."""
    sp = {"backbone": _subset(params["backbone"], plan.suffix_keys),
          "head": params["head"]}
    ss = {"backbone": _subset(model_state.get("backbone", {}),
                              plan.suffix_keys)}
    return sp, ss


def merge_suffix_variables(plan: FeatureCachePlan, params, model_state,
                           trained_params, trained_state):
    """Graft the trained suffix trees back into the full model's trees
    (frozen prefix entries pass through untouched)."""
    bb = dict(params["backbone"])
    bb.update(trained_params["backbone"])
    out_params = {"backbone": bb, "head": trained_params["head"]}
    bb_state = dict(model_state.get("backbone", {}))
    bb_state.update(trained_state.get("backbone", {}))
    out_state = dict(model_state)
    if bb_state:
        out_state = {**model_state, "backbone": bb_state}
    return out_params, out_state


def compute_features(plan: FeatureCachePlan, params, model_state,
                     ds: ArrayDataset, mesh: Mesh, *, batch_size: int,
                     compute_dtype=jnp.float32) -> ArrayDataset:
    """Run the frozen prefix over `ds` once (eval mode, DP-sharded over
    the mesh) and return the activations as a host dataset with the same
    labels and ordering. Values are computed in `compute_dtype` (exactly
    what the uncached per-step forward would produce) and stored f32."""
    prefix_keys = _param_keys(plan.prefix)
    prefix_params = _subset(params["backbone"], prefix_keys)
    prefix_state = _subset(model_state.get("backbone", {}), prefix_keys)

    def fwd(p, s, x):
        h, _ = plan.prefix.apply(p, s, x.astype(compute_dtype), train=False)
        return h.astype(jnp.float32)

    step = jit_data_parallel(lambda st, x, y: fwd(st["p"], st["s"], x),
                             mesh, donate_state=False)
    st = replicate(mesh, {"p": prefix_params, "s": prefix_state})
    gather = jax.jit(lambda x: x, out_shardings=meshlib.replicated(mesh))
    from idc_models_tpu.train.loop import batched_forward

    features = batched_forward(mesh, gather, ds, batch_size, None,
                               lambda x, y: step(st, x, y))
    return ArrayDataset(features, ds.labels)
