"""Training orchestration: epoch loops, evaluation, and the two-phase
transfer-learning schedule.

Parity target (SURVEY.md C7, dist_model_tf_vgg.py:130-160): compile with
RMSprop + from-logits loss -> `evaluate` the un-trained floor on a few
validation batches -> fit N epochs with the backbone frozen -> unfreeze
above `fine_tune_at`, recompile at lr/10 -> fit the remaining epochs
continuing the epoch counter. The reference hides the loop inside
`model.fit`; here it is explicit: host loader -> HBM prefetch -> jitted
DP train step -> per-epoch validation metrics -> Keras-style history
dicts, with named Timers (C17), jsonl records, and the training-curve
plot artifact (C18).

Freeze/unfreeze is an optimizer mask (core.trainability_mask via the
registry's mask builders) instead of the reference's recompile dance
(quirk Q6); recompiling at lr/10 maps to a fresh optimizer (and fresh
optimizer state, matching Keras recompile) over the same params.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.data.idc import ArrayDataset
from idc_models_tpu.data.pipeline import (
    Loader, prefetch_eval_batches, prefetch_to_mesh,
)
from idc_models_tpu.models import core, registry
from idc_models_tpu.observe import Timer, plot_history
from idc_models_tpu.observe import metrics_registry as mreg
from idc_models_tpu.observe import profile as prof
from idc_models_tpu.observe import trace
from idc_models_tpu.train import metrics as metrics_lib
from idc_models_tpu.train import step as step_mod
from idc_models_tpu.train.state import TrainState, create_train_state, rmsprop
from idc_models_tpu.train.step import (
    jit_data_parallel, make_eval_step, make_train_step, place_state,
    replicate, shard_batch,
)

History = dict[str, list[float]]


class Evaluator:
    """Holds one jitted eval step so repeated (per-epoch) evaluation does
    not recompile. Call with (state, ds) -> metrics dict.

    `steps` limits evaluation to the first `steps` batches — the
    reference's `validation_steps=20` floor sample (quirk Q3,
    dist_model_tf_vgg.py:15,134); None means the exact full set (padded
    final batch, every example counted once).
    """

    def __init__(self, model: core.Module, loss_fn, mesh: Mesh, *,
                 batch_size: int = 32, compute_dtype=jnp.float32,
                 with_auroc: bool = False, rules=None):
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.batch_size = batch_size
        self.with_auroc = with_auroc
        self.rules = rules
        # under partition rules the state keeps its placed (sharded)
        # layout — FOLLOW leaves the eval step's state pin to placement
        self._step = jit_data_parallel(
            make_eval_step(model, loss_fn, compute_dtype=compute_dtype),
            mesh, donate_state=False,
            state_shardings=(step_mod.FOLLOW if rules is not None
                             else None))
        # multi-host: batch-sharded logits span other processes' devices
        # and cannot be fetched directly; this identity jit re-places them
        # fully replicated (XLA all-gather over ICI/DCN) first
        self._gather = jax.jit(lambda x: x,
                               out_shardings=meshlib.replicated(mesh))

    def __call__(self, state: TrainState, ds: ArrayDataset, *,
                 steps: int | None = None) -> dict[str, float]:
        state = place_state(self.mesh, state, rules=self.rules)
        logits = jnp.asarray(batched_forward(
            self.mesh, self._gather, ds, self.batch_size, steps,
            lambda x, y: self._step(state, x, y)["logits"]))
        # the kept rows are exactly the first len(logits) examples
        labels = jnp.asarray(ds.labels[:len(logits)])
        out = {
            "loss": float(self.loss_fn(logits, labels)),
            "accuracy": float(metrics_lib.auto_accuracy(logits, labels)),
        }
        if self.with_auroc:
            out["auroc"] = float(metrics_lib.auroc(
                jax.nn.sigmoid(logits.reshape(-1)), labels))
        return out


def batched_forward(mesh: Mesh, gather, ds: ArrayDataset, batch_size: int,
                    steps: int | None, run) -> np.ndarray:
    """Shared eval/predict logits loop: batches of `ds` through `run(x, y)
    -> logits` on the sharded pipeline, padding rows dropped, results
    concatenated in order. `gather` is the identity jit with replicated
    out_shardings that makes batch-sharded logits fetchable on multi-host
    meshes (see Evaluator.__init__)."""
    parts = []
    for x, y, size in prefetch_eval_batches(ds, mesh, batch_size,
                                            steps=steps):
        logits = run(x, y)
        if not logits.is_fully_addressable:
            logits = gather(logits)
        parts.append(np.asarray(logits)[:size])
    return np.concatenate(parts)


def evaluate(model: core.Module, state: TrainState, ds: ArrayDataset,
             loss_fn, mesh: Mesh, *, batch_size: int = 32,
             steps: int | None = None, compute_dtype=jnp.float32,
             with_auroc: bool = False, rules=None) -> dict[str, float]:
    """One-shot evaluation (builds a throwaway Evaluator)."""
    ev = Evaluator(model, loss_fn, mesh, batch_size=batch_size,
                   compute_dtype=compute_dtype, with_auroc=with_auroc,
                   rules=rules)
    return ev(state, ds, steps=steps)


def predict(model: core.Module, state: TrainState, images, mesh: Mesh, *,
            batch_size: int = 32, compute_dtype=jnp.float32) -> np.ndarray:
    """Inference over a batch-sharded dataset: logits for every example,
    in order (the `model.predict` convenience of the Keras surface the
    reference's users come from). Runs the same sharded eval pipeline as
    the Evaluator — transfers overlapped, final batch padded to the mesh
    and the padding rows dropped — and works on DP, client, and
    ("data", "model") TP meshes alike."""
    images = np.asarray(images)
    if len(images) == 0:
        # Keras model.predict returns an empty array, not a crash; the
        # trailing shape comes from an abstract single-example eval
        # (batch 0 itself would break flatten's reshape(-1) inference)
        shape = jax.eval_shape(
            lambda x: model.apply(state.params, state.model_state, x,
                                  train=False)[0],
            jax.ShapeDtypeStruct((1,) + images.shape[1:],
                                 jnp.float32)).shape
        return np.zeros((0,) + shape[1:], np.float32)
    ds = ArrayDataset(images, np.zeros((len(images),), np.int32))
    placed = place_state(mesh, state)
    step = jit_data_parallel(
        lambda s, x, y: model.apply(s.params, s.model_state,
                                    x.astype(compute_dtype),
                                    train=False)[0].astype(jnp.float32),
        mesh, donate_state=False)
    gather = jax.jit(lambda x: x, out_shardings=meshlib.replicated(mesh))
    return batched_forward(mesh, gather, ds, batch_size, None,
                           lambda x, y: step(placed, x, y))


def fit(model: core.Module, optimizer: optax.GradientTransformation,
        loss_fn, state: TrainState, train_ds: ArrayDataset,
        val_ds: ArrayDataset | None, mesh: Mesh, *, epochs: int,
        batch_size: int = 32, initial_epoch: int = 0, seed: int = 0,
        logger=None, verbose: bool = True, central_storage: bool = False,
        compute_dtype=jnp.float32, repeats: int = 1,
        checkpoint_dir: str | None = None, checkpoint_every: int = 1,
        rules=None) -> tuple[TrainState, History]:
    """Keras-`fit`-shaped epoch loop over the jitted DP train step.

    Returns the final state and a Keras-style history dict
    ({"loss", "accuracy", "val_loss", "val_accuracy"} per epoch).
    `initial_epoch` continues a previous schedule's epoch numbering
    (dist_model_tf_vgg.py:159 `initial_epoch=history.epoch[-1]`).

    `checkpoint_dir` enables epoch-granular resume (SURVEY.md §5 build
    target: checkpoint every loop, not just the pretrainer): the full
    TrainState + history are saved every `checkpoint_every` epochs
    (plus always after the final one — a blocking orbax save per epoch
    can dominate short epochs), and a restart picks up at the epoch
    after the last save. Per-step rng keys are derived by folding the
    epoch into the seed, so a resumed run consumes the exact stream a
    straight-through run would have.

    `central_storage=True` is the parity toggle for the reference's
    `CentralStorageStrategy` variant (D2, dist_model_tf_dense.py:18,21-24):
    the master copy of the state lives in HOST memory between steps and is
    broadcast to the devices each step, with the updated state fetched
    back — numerically identical to the mirrored mode, paying a host
    round-trip per step exactly like variables-on-CPU compute-on-device.

    `rules` (partition.PartitionRules) shards the FULL state — params,
    BN stats, optimizer moments — by the regex->PartitionSpec policy
    (FSDP over "data", TP over "model"; models/registry.py holds the
    per-model defaults). The resolved shardings pin the step's state in
    AND out, so the layout is stable across donated steps (zero jit
    growth, gated by test) and the optimizer state shards with its
    param.
    """
    state_sh = (rules.shardings(mesh, state) if rules is not None
                else None)
    base_step = jit_data_parallel(
        make_train_step(model, optimizer, loss_fn,
                        compute_dtype=compute_dtype), mesh,
        state_shardings=state_sh)
    if central_storage:
        if rules is not None:
            raise NotImplementedError(
                "central_storage broadcasts a host-resident replica "
                "each step and cannot keep a rule-sharded (FSDP/TP) "
                "layout; drop partition rules or central_storage")
        if jax.process_count() > 1:
            raise NotImplementedError(
                "central_storage is a single-host parity mode (the "
                "reference's CentralStorageStrategy, "
                "dist_model_tf_dense.py:18, is single-host too); use the "
                "default mirrored mode on multi-host pods")
        from idc_models_tpu import tp

        if tp.has_model_axis(mesh):
            raise NotImplementedError(
                "central_storage broadcasts a host-resident replica each "
                "step and cannot keep a model-sharded layout; drop "
                "model parallelism or central_storage")
        state = jax.device_get(state)

        def step_fn(host_state, x, y, rng):
            out, m = base_step(replicate(mesh, host_state), x, y, rng)
            return jax.device_get(out), m
    else:
        step_fn = base_step
        state = place_state(mesh, state, rules=rules)
    # repeats>1 reproduces the reference CIFAR pipeline's `.repeat(2)`
    # (dist_model_tf_dense.py:122-123): each epoch passes over the train
    # set `repeats` times, freshly shuffled per pass. A Loader-shaped
    # stream (data.pipeline.FileStream) may be passed instead of an
    # ArrayDataset; it keeps its decode configuration but fit imposes
    # the FULL schedule (batch/shuffle/seed/repeat) so both paths train
    # identically for the same arguments (e.g. phase 2's seed+1).
    if isinstance(train_ds, ArrayDataset):
        loader = Loader(train_ds, batch_size, shuffle=True, seed=seed,
                        repeat=repeats)
    else:
        loader = train_ds.replace(batch_size=batch_size, shuffle=True,
                                  seed=seed, repeat=repeats)
    evaluator = (Evaluator(model, loss_fn, mesh, batch_size=batch_size,
                           compute_dtype=compute_dtype, rules=rules)
                 if val_ds is not None else None)
    history: History = {"loss": [], "accuracy": [],
                        "val_loss": [], "val_accuracy": []}
    start_epoch = initial_epoch
    fingerprint = None
    if checkpoint_dir is not None:
        # the loader's own knobs (== the fit args for ArrayDataset; the
        # stream's configuration otherwise) identify the data schedule
        fingerprint = _fit_fingerprint(state, loader.seed,
                                       loader.batch_size, loader.repeat,
                                       initial_epoch)
        restored = _restore_fit_checkpoint(checkpoint_dir, state, epochs,
                                           fingerprint)
        if restored is not None:
            state, history, start_epoch = restored
            start_epoch = max(start_epoch, initial_epoch)
            if verbose and start_epoch > initial_epoch:
                print(f"resuming fit from epoch {start_epoch + 1}")
    # process-wide instruments (idempotent; observe/metrics_registry.py)
    # — the history dict / jsonl epoch records above stay the schema
    # contract, the registry adds the operational rollup
    m_steps = mreg.REGISTRY.counter("train_steps_total",
                                    "optimizer steps taken")
    m_epochs = mreg.REGISTRY.counter("train_epochs_total",
                                     "epochs completed")
    m_loss = mreg.REGISTRY.gauge("train_loss",
                                 "last completed epoch's train loss")
    # program accounting only when a profile driver armed it (it costs
    # one extra compile of the step); central_storage's step_fn is a
    # host wrapper around base_step, so base_step is registered either
    # way — same executable, honest account
    accounted = not prof.accounting_enabled()
    for epoch in range(start_epoch, epochs):
        # epoch folded into the seed (not a running split) so a resumed
        # run reproduces the straight-through rng stream
        key = jax.random.fold_in(jax.random.key(seed), epoch)
        losses, accs = [], []
        with trace.span("train.epoch", epoch=epoch) as ep_span:
            for x, y in prefetch_to_mesh(loader.epoch(epoch), mesh):
                key, sub = jax.random.split(key)
                # the span covers host wait + async step DISPATCH; the
                # device time it hides is fenced by the epoch-mean
                # fetch below, inside train.epoch
                with trace.span("train.step"):
                    state, m = step_fn(state, x, y, sub)
                if not accounted:
                    # opt-in program accounting (profile.py): one
                    # AOT accounting compile, named in PROGRAMS +
                    # program_* gauges; never on by default
                    accounted = True
                    prof.register_jit("train.step", base_step, state,
                                      x, y, sub)
                losses.append(m["loss"])
                accs.append(m["accuracy"])
            m_steps.inc(len(losses))
            # the epoch-mean fetch is where this loop BLOCKS on the
            # device — bracketed as device.sync so a DeviceTimeline
            # can split train.epoch into device-wait vs host gap
            with trace.span("device.sync"):
                ep = {
                    "loss": float(jnp.mean(jnp.stack(losses))),
                    "accuracy": float(jnp.mean(jnp.stack(accs))),
                }
            ep_span.set(steps=len(losses), loss=ep["loss"])
        if not np.isfinite(ep["loss"]):
            # fail FAST and loudly: a NaN here would silently poison
            # every remaining epoch AND the saved checkpoint (the
            # optimizer state is already corrupt) — find the first bad
            # step so the error names where training went over the edge
            bad = next((i for i, l in enumerate(losses)
                        if not np.isfinite(float(l))), None)
            where = (f"epoch {epoch + 1}, step {bad + 1}/{len(losses)}"
                     if bad is not None else f"epoch {epoch + 1}")
            raise FloatingPointError(
                f"non-finite training loss ({ep['loss']}) at {where}: "
                f"the parameters and optimizer state are corrupt from "
                f"that step on, so continuing (or checkpointing) would "
                f"only persist garbage — lower the lr, check the input "
                f"data for NaN/Inf, or enable loss scaling")
        if evaluator is not None:
            with trace.span("train.eval", epoch=epoch):
                vm = evaluator(state, val_ds)
            ep["val_loss"] = vm["loss"]
            ep["val_accuracy"] = vm["accuracy"]
        for k, v in ep.items():
            history[k].append(v)
        m_epochs.inc()
        m_loss.set(ep["loss"])
        if verbose:
            msg = " ".join(f"{k}={v:.4f}" for k, v in ep.items())
            print(f"epoch {epoch + 1}/{epochs} {msg}")
        if logger is not None:
            logger.log(event="epoch", epoch=epoch, **ep)
        if checkpoint_dir is not None and (
                (epoch + 1) % max(checkpoint_every, 1) == 0
                or epoch + 1 == epochs):
            _save_fit_checkpoint(checkpoint_dir, state, history, epoch + 1,
                                 fingerprint)
    return state, history


def _fit_fingerprint(state: TrainState, seed: int, batch_size: int,
                     repeats: int, initial_epoch: int) -> str:
    """Identifies the training run a checkpoint belongs to: the rng/data
    schedule knobs plus a digest of the STARTING parameters (so e.g. a
    re-trained upstream phase invalidates a downstream phase's
    checkpoint instead of silently restoring stale state). The optimizer
    is not captured — changing lr between runs is not detected."""
    import hashlib

    h = hashlib.sha1(
        f"{seed}/{batch_size}/{repeats}/{initial_epoch}".encode())
    for leaf in jax.tree.leaves(jax.device_get(state.params)):
        a = np.asarray(leaf)
        h.update(str(a.shape).encode())
        h.update(np.float64(a.astype(np.float64).sum()).tobytes())
    return h.hexdigest()


def _save_fit_checkpoint(ckpt_dir, state: TrainState, history: History,
                         next_epoch: int, fingerprint: str) -> None:
    """Commit protocol: the epoch-versioned orbax save lands first, then
    meta.json is atomically renamed to point at it. A crash between the
    two leaves meta pointing at the previous consistent (state, epoch)
    pair, so resume retrains at most the one interrupted epoch — never a
    state/counter mismatch. orbax save is a collective (it opens with an
    all-host barrier), so EVERY process calls it — orbax itself elects
    the writing host; only the tiny meta.json commit is process-0-gated
    (the checkpoint dir is assumed shared on pods)."""
    import json
    import shutil
    from pathlib import Path

    from idc_models_tpu.train.checkpoint import save_checkpoint

    d = Path(ckpt_dir)
    name = f"state_e{next_epoch}"
    save_checkpoint(d / name, jax.device_get(state))
    if jax.process_index() != 0:
        return
    tmp = d / "meta.json.tmp"
    tmp.write_text(json.dumps({"epoch": next_epoch, "state": name,
                               "fingerprint": fingerprint,
                               "history": history}))
    tmp.replace(d / "meta.json")
    for old in d.glob("state_e*"):
        if old.name != name:
            shutil.rmtree(old, ignore_errors=True)


def _restore_fit_checkpoint(ckpt_dir, target: TrainState, epochs: int,
                            fingerprint: str):
    import json
    import warnings
    from pathlib import Path

    from idc_models_tpu.train.checkpoint import (
        checkpoint_exists, restore_checkpoint,
    )

    d = Path(ckpt_dir)
    meta = d / "meta.json"
    if not meta.exists():
        return None
    info = json.loads(meta.read_text())
    if info.get("fingerprint") != fingerprint:
        warnings.warn(
            f"checkpoint {d} belongs to a different run (seed/batch/"
            f"repeats or starting parameters changed); ignoring it and "
            f"training from scratch", stacklevel=2)
        return None
    epoch = int(info["epoch"])
    if epoch > epochs:
        raise ValueError(
            f"checkpoint {d} was trained for {epoch} epochs but this run "
            f"asks for {epochs}; refusing to silently return the longer "
            f"run — delete the checkpoint dir or raise --epochs")
    state_dir = d / info.get("state", "state")
    if not checkpoint_exists(state_dir):
        return None
    state = restore_checkpoint(state_dir, jax.device_get(target))
    return state, dict(info["history"]), epoch


@dataclasses.dataclass(frozen=True)
class TwoPhaseConfig:
    """The reference's training hyperparameters in one place (its
    module-level constants, e.g. dist_model_tf_vgg.py:8-17)."""

    lr: float = 1e-3
    epochs: int = 10               # phase-1 (frozen backbone) epochs
    fine_tune_epochs: int = 10     # additional phase-2 epochs
    batch_size: int = 32
    fine_tune_at: int | None = None  # None -> registry default
    eval_steps: int | None = 20    # baseline-floor sample size (quirk Q3)
    repeats: int = 1               # dataset passes per epoch (dense: 2,
    #                                dist_model_tf_dense.py:122-123)
    cache_features: bool = False   # phase 2 on cached frozen-prefix
    #                                activations (train/feature_cache.py)
    seed: int = 0
    compute_dtype: Any = jnp.float32
    central_storage: bool = False  # D2: host-resident params per step


@dataclasses.dataclass
class TwoPhaseResult:
    state: TrainState
    model: core.Module             # the phase-2 model (for inference)
    history: History
    history_fine: History
    baseline: dict[str, float]
    pretrain_seconds: float
    fine_tune_seconds: float


def _build_model(spec: registry.ModelSpec, num_outputs: int,
                 in_channels: int, bn_frozen_below: int) -> core.Module:
    """Build with BN-freeze config when the model supports it (BN-bearing
    backbones must run frozen BN in inference mode — SURVEY.md §7
    'hard parts')."""
    params = inspect.signature(spec.build).parameters
    if "bn_frozen_below" in params:
        return spec.build(num_outputs, in_channels,
                          bn_frozen_below=bn_frozen_below)
    return spec.build(num_outputs, in_channels)


_FREEZE_ALL = 10_000  # larger than any Keras layer index


def two_phase_fit(model_name: str, num_outputs: int, train_ds: ArrayDataset,
                  val_ds: ArrayDataset, mesh: Mesh,
                  config: TwoPhaseConfig = TwoPhaseConfig(), *,
                  in_channels: int = 3, loss_fn=None,
                  pretrained_params=None, pretrained_state=None,
                  pretrained_weights: str | None = None,
                  artifact_path: str | None = None,
                  checkpoint_dir: str | None = None,
                  checkpoint_every: int = 1,
                  logger=None) -> TwoPhaseResult:
    """The reference's full two-phase transfer-learning program (C7).

    Phase 1: head-only training at `lr` with the backbone frozen
    (dist_model_tf_vgg.py:122,130-138). Phase 2: layers with Keras index
    >= fine_tune_at unfrozen, fresh RMSprop at lr/10, epoch counter
    continued (dist_model_tf_vgg.py:141-160). Saves the C18 plot artifact
    under `artifact_path` when given. `checkpoint_dir` enables
    epoch-granular resume of both phases (per-phase subdirectories).
    """
    from idc_models_tpu.train.losses import (
        binary_cross_entropy, sparse_categorical_cross_entropy,
    )

    if loss_fn is None:
        loss_fn = (binary_cross_entropy if num_outputs == 1
                   else sparse_categorical_cross_entropy)
    spec = registry.get_model(model_name)
    fine_tune_at = (config.fine_tune_at if config.fine_tune_at is not None
                    else spec.default_fine_tune_at)

    model1 = _build_model(spec, num_outputs, in_channels, _FREEZE_ALL)
    model2 = _build_model(spec, num_outputs, in_channels, fine_tune_at)

    init_rng = jax.random.key(config.seed)
    variables = model1.init(init_rng)
    params = pretrained_params if pretrained_params is not None else variables.params
    model_state = (pretrained_state if pretrained_state is not None
                   else variables.state)
    if pretrained_weights is not None:
        # ImageNet-backbone start (dist_model_tf_vgg.py:119-121): graft a
        # converted weight artifact onto the fresh init before phase 1.
        from idc_models_tpu.models.pretrained import maybe_load_pretrained

        params, model_state = maybe_load_pretrained(
            params, pretrained_weights, state=model_state)

    # Phase 1: head-only mask at lr
    opt1 = rmsprop(config.lr, trainable_mask=spec.head_only_mask(params))
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       model_state=model_state, opt_state=opt1.init(params))

    baseline = evaluate(model1, state, val_ds, loss_fn, mesh,
                        batch_size=config.batch_size,
                        steps=config.eval_steps,
                        compute_dtype=config.compute_dtype)
    print(f"initial loss: {baseline['loss']:.2f}")
    print(f"initial accuracy: {baseline['accuracy']:.2f}")

    with Timer(f"Pre-training for {config.epochs} epochs",
               logger=logger) as t1:
        state, history = fit(
            model1, opt1, loss_fn, state, train_ds, val_ds, mesh,
            epochs=config.epochs, batch_size=config.batch_size,
            seed=config.seed, logger=logger,
            central_storage=config.central_storage,
            compute_dtype=config.compute_dtype, repeats=config.repeats,
            checkpoint_dir=(f"{checkpoint_dir}/phase1"
                            if checkpoint_dir else None),
            checkpoint_every=checkpoint_every)

    # Phase 2: "recompile" = fresh optimizer (and state) at lr/10 with the
    # fine-tune mask; BN below fine_tune_at stays in inference mode.
    mask2 = spec.fine_tune_mask(state.params, fine_tune_at)
    opt2 = rmsprop(config.lr / 10.0, trainable_mask=mask2)
    state = TrainState(step=state.step, params=state.params,
                       model_state=state.model_state,
                       opt_state=opt2.init(state.params))

    plan = None
    if config.cache_features:
        if not isinstance(train_ds, ArrayDataset):
            raise ValueError(
                "cache_features needs a materialized ArrayDataset (the "
                "cache runs the frozen prefix over the whole train set); "
                "drop --stream or --cache-features")
        from idc_models_tpu.train import feature_cache as fc

        plan = fc.plan_feature_cache(model2, spec.layer_index or {},
                                     fine_tune_at, spec.feature_dim,
                                     num_outputs)
        if plan is None:
            print(f"[idc_models_tpu] {model_name} is not splittable at "
                  f"fine_tune_at={fine_tune_at}; feature cache disabled")

    total_epochs = config.epochs + config.fine_tune_epochs
    with Timer(f"Fine tuning for {config.fine_tune_epochs} epochs",
               logger=logger) as t2:
        phase2_ckpt = f"{checkpoint_dir}/phase2" if checkpoint_dir else None
        if plan is not None:
            state, history_fine = _fit_cached_phase2(
                plan, spec, state, train_ds, val_ds, mesh, config,
                fine_tune_at, loss_fn, total_epochs, logger,
                checkpoint_dir=phase2_ckpt,
                checkpoint_every=checkpoint_every)
        else:
            state, history_fine = fit(
                model2, opt2, loss_fn, state, train_ds, val_ds, mesh,
                epochs=total_epochs, batch_size=config.batch_size,
                initial_epoch=config.epochs, seed=config.seed + 1,
                logger=logger, central_storage=config.central_storage,
                compute_dtype=config.compute_dtype, repeats=config.repeats,
                checkpoint_dir=phase2_ckpt,
                checkpoint_every=checkpoint_every)

    print(history)
    print(history_fine)
    if artifact_path is not None:
        plot_history(artifact_path, history, history_fine,
                     mesh.devices.size, initial_epochs=config.epochs)

    return TwoPhaseResult(
        state=state, model=model2, history=history,
        history_fine=history_fine, baseline=baseline,
        pretrain_seconds=t1.seconds, fine_tune_seconds=t2.seconds)


def _fit_cached_phase2(plan, spec, state: TrainState, train_ds, val_ds,
                       mesh: Mesh, config: TwoPhaseConfig,
                       fine_tune_at: int, loss_fn, total_epochs: int,
                       logger,
                       checkpoint_dir: str | None = None,
                       checkpoint_every: int = 1
                       ) -> tuple[TrainState, History]:
    """Phase 2 on cached frozen-prefix features (train/feature_cache.py):
    run the prefix once over train/val, fit the suffix model on the
    features with the same mask/optimizer/seed schedule the uncached path
    would use, then graft the trained suffix back into the full trees.

    Returns a TrainState for the FULL model; its optimizer state is
    freshly initialized (the suffix moments live only inside this phase).
    """
    from idc_models_tpu.train import feature_cache as fc

    with Timer("Caching frozen-backbone features", logger=logger):
        feat_train = fc.compute_features(
            plan, state.params, state.model_state, train_ds, mesh,
            batch_size=config.batch_size, compute_dtype=config.compute_dtype)
        feat_val = (fc.compute_features(
            plan, state.params, state.model_state, val_ds, mesh,
            batch_size=config.batch_size, compute_dtype=config.compute_dtype)
            if val_ds is not None else None)

    sp, ss = fc.suffix_variables(plan, state.params, state.model_state)
    opt = rmsprop(config.lr / 10.0,
                  trainable_mask=spec.fine_tune_mask(sp, fine_tune_at))
    sstate = TrainState(step=state.step, params=sp, model_state=ss,
                        opt_state=opt.init(sp))
    sstate, history_fine = fit(
        plan.suffix_model, opt, loss_fn, sstate, feat_train, feat_val,
        mesh, epochs=total_epochs, batch_size=config.batch_size,
        initial_epoch=config.epochs, seed=config.seed + 1, logger=logger,
        central_storage=config.central_storage,
        compute_dtype=config.compute_dtype, repeats=config.repeats,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every)

    params, model_state = fc.merge_suffix_variables(
        plan, state.params, state.model_state,
        jax.device_get(sstate.params), jax.device_get(sstate.model_state))
    mask2 = spec.fine_tune_mask(params, fine_tune_at)
    opt2 = rmsprop(config.lr / 10.0, trainable_mask=mask2)
    full = TrainState(step=sstate.step, params=params,
                      model_state=model_state,
                      opt_state=opt2.init(params))
    return full, history_fine

