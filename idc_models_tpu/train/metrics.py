"""On-device metrics in jnp: accuracy, binary accuracy, AUROC.

Parity targets: Keras `metrics=['accuracy']` (dist_model_tf_vgg.py:132),
`BinaryAccuracy` (fed_model.py:205), and `roc_auc_score` wrapped in a
py_func (quirk-free replacement for secure_fed_model.py:81-82 — here AUROC
is computed on-device with a sort, no host round-trip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Multiclass accuracy; logits [B,C], integer labels [B]."""
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def auto_accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Dispatch on logits shape: [B,C>1] multiclass, else binary — the
    Keras `metrics=['accuracy']` auto-selection (dist_model_tf_vgg.py:132
    vs dist_model_tf_dense.py:144). Sequence logits [B,T,V] with token
    labels [B,T] (the LM convention, models/lm.py) score shifted
    next-token accuracy, matching `next_token_loss`'s objective.
    Soft labels [B,T,V] (teacher logits, models/draft_lm.py distillation)
    score UNSHIFTED greedy agreement — teacher and student logits at
    position t both predict token t+1, so no shift applies."""
    if logits.ndim == 3 and logits.shape[-1] > 1:
        if labels.ndim == 3:
            pred = jnp.argmax(logits, -1)
            return jnp.mean((pred == jnp.argmax(labels, -1))
                            .astype(jnp.float32))
        pred = jnp.argmax(logits[:, :-1], -1)
        return jnp.mean((pred == labels[:, 1:].astype(pred.dtype))
                        .astype(jnp.float32))
    if logits.ndim == 2 and logits.shape[-1] > 1:
        return accuracy(logits, labels)
    return binary_accuracy(logits, labels)


def binary_accuracy(logits: jax.Array, labels: jax.Array,
                    threshold: float = 0.0) -> jax.Array:
    """Binary accuracy on logits (threshold 0 == probability 0.5)."""
    pred = (logits.reshape(-1) > threshold)
    return jnp.mean((pred == (labels.reshape(-1) > 0.5)).astype(jnp.float32))


def auroc(scores: jax.Array, labels: jax.Array) -> jax.Array:
    """AUROC via the rank-sum (Mann-Whitney U) identity, with tie handling.

    Entirely on-device (sort + segment ops); matches
    sklearn.metrics.roc_auc_score on tied and untied inputs.
    """
    scores = scores.reshape(-1).astype(jnp.float32)
    labels = (labels.reshape(-1) > 0.5).astype(jnp.float32)
    n = scores.shape[0]
    order = jnp.argsort(scores)
    s = scores[order]
    l = labels[order]
    # average ranks over ties: rank_i = mean of positions of equal scores
    idx = jnp.arange(n, dtype=jnp.float32)
    # For each element, first and last index of its tie group.
    is_new = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    group = jnp.cumsum(is_new) - 1  # group id per sorted element
    group_sum = jax.ops.segment_sum(idx, group, num_segments=n)
    group_cnt = jax.ops.segment_sum(jnp.ones_like(idx), group, num_segments=n)
    avg_rank = (group_sum / jnp.maximum(group_cnt, 1.0))[group] + 1.0  # 1-based
    n_pos = jnp.sum(l)
    n_neg = n - n_pos
    rank_sum_pos = jnp.sum(avg_rank * l)
    u = rank_sum_pos - n_pos * (n_pos + 1.0) / 2.0
    denom = jnp.maximum(n_pos * n_neg, 1.0)
    return jnp.where((n_pos == 0) | (n_neg == 0), jnp.nan, u / denom)
