"""Loss functions (from-logits, matching the reference's compile() choices)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def binary_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """BCE from logits; logits [B,1] or [B], labels [B] in {0,1}.
    Parity: BinaryCrossentropy(from_logits=True), dist_model_tf_vgg.py:131."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(logits.dtype)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def sparse_categorical_cross_entropy(logits: jax.Array,
                                     labels: jax.Array) -> jax.Array:
    """Softmax CE against integer labels.

    The reference uses dense `CategoricalCrossentropy` against integer
    labels (quirk Q4, dist_model_tf_dense.py:143) — a bug; the framework
    uses the intended sparse loss.
    """
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)))
