"""The jitted train/eval step — the framework owns it explicitly.

In the reference the step function is hidden inside `model.fit` and
MirroredStrategy (forward+backward per replica, NCCL allreduce, mirrored
update — SURVEY.md §3.1 "HOT LOOP"). Here it is one pure function:

    loss -> grad -> (XLA-inserted allreduce over the "data" mesh axis) ->
    optax update -> new TrainState

Data parallelism uses the modern jit-with-shardings style: the global batch
is sharded over the mesh's "data" axis, parameters are replicated, and XLA
lowers the gradient reduction onto ICI automatically — there is no pmap and
no hand-written collective in the hot path. (The explicit-collective style
still exists in this framework where per-device control genuinely matters:
federated and secure aggregation use `shard_map` + `collectives`.)
"""

from __future__ import annotations

from collections.abc import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from idc_models_tpu import mesh as meshlib
from idc_models_tpu.models import core
from idc_models_tpu.train import metrics as metrics_lib
from idc_models_tpu.train.state import TrainState

LossFn = Callable[[jax.Array, jax.Array], jax.Array]


def make_train_step(model: core.Module, optimizer: optax.GradientTransformation,
                    loss_fn: LossFn, *, compute_dtype=jnp.float32):
    """Returns train_step(state, images, labels, rng) -> (state, metrics)."""

    def train_step(state: TrainState, images, labels, rng):
        # integer inputs (LM token ids) skip the compute-dtype cast: a
        # bf16 round-trip would silently corrupt ids > 256 before the
        # model's int32 cast-back (attention_lm), and integer inputs
        # never benefit from a low-precision matmul dtype anyway
        if not jnp.issubdtype(jnp.asarray(images).dtype, jnp.integer):
            images = images.astype(compute_dtype)

        def loss_of(params):
            logits, new_model_state = model.apply(
                params, state.model_state, images, train=True, rng=rng)
            logits = logits.astype(jnp.float32)
            return loss_fn(logits, labels), (logits, new_model_state)

        (loss, (logits, new_model_state)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(state.params)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        out = state.replace(
            step=state.step + 1,
            params=new_params,
            model_state=new_model_state,
            opt_state=new_opt_state,
        )
        m = {"loss": loss, "accuracy": metrics_lib.auto_accuracy(logits, labels)}
        return out, m

    return train_step


def make_eval_step(model: core.Module, loss_fn: LossFn, *,
                   compute_dtype=jnp.float32):
    """Returns eval_step(state, images, labels) -> metrics (loss/acc/logits)."""

    def eval_step(state: TrainState, images, labels):
        if not jnp.issubdtype(jnp.asarray(images).dtype, jnp.integer):
            images = images.astype(compute_dtype)  # ids stay exact
        logits, _ = model.apply(state.params, state.model_state, images,
                                train=False)
        logits = logits.astype(jnp.float32)
        return {
            "loss": loss_fn(logits, labels),
            "accuracy": metrics_lib.auto_accuracy(logits, labels),
            "logits": logits,
        }

    return eval_step



# ---------------------------------------------------------------------------
# data-parallel jit wrappers
# ---------------------------------------------------------------------------

#: sentinel for `jit_data_parallel(state_shardings=...)`: leave the
#: state's shardings unpinned so the step follows whatever layout
#: `place_state` installed (the eval path under partition rules).
FOLLOW = "follow"


def jit_data_parallel(step_fn, mesh: Mesh, *, donate_state: bool = True,
                      extra_batch_args: int = 0, axis: str | None = None,
                      state_shardings=None):
    """Jit `step_fn(state, images, labels, *rest)` with DP shardings.

    State replicated; images/labels (and `extra_batch_args` further
    positional args) sharded on their leading axis over `axis` (default:
    the mesh's "data" axis, or its only axis when 1-D — so eval works on
    a "client" mesh too). This is the whole MirroredStrategy replacement
    for D1.

    `state_shardings` overrides the state pin: a NamedSharding pytree
    (from `partition.PartitionRules.shardings`, resolved over the full
    TrainState so optimizer moments shard with their params) pins the
    state in AND out — FSDP/TP layouts stay stable across donated
    steps; the `FOLLOW` sentinel leaves the state unpinned to follow
    its placement. On a 2-D ("data", "model") mesh without an explicit
    override the state follows its `place_state` channel layout
    (tp.py), as before.
    """
    from idc_models_tpu import tp

    repl = meshlib.replicated(mesh)
    if state_shardings is None:
        state_sh = None if tp.has_model_axis(mesh) else repl
    else:
        state_sh = (None if isinstance(state_shardings, str)
                    and state_shardings == FOLLOW else state_shardings)
    batch = meshlib.sharding(mesh, _batch_axis(mesh, axis))
    n_batch = 2 + extra_batch_args
    in_shardings = (state_sh,) + (batch,) * n_batch
    # Pin the RETURNED state to the same layout as the input state:
    # without this, GSPMD may shard an updated param over whatever axis
    # its gradient arrived on (e.g. a positional embedding over "seq"
    # when the model runs ring attention in-step), and the next call
    # rejects the now-mismatched donated input. Only train-shaped steps
    # ((state, metrics) returns) donate state; eval-shaped steps return
    # arbitrary pytrees and stay unconstrained.
    return jax.jit(
        step_fn,
        in_shardings=in_shardings + (repl,) if _wants_rng(step_fn) else in_shardings,
        out_shardings=(state_sh, None) if donate_state else None,
        donate_argnums=(0,) if donate_state else (),
    )


def _wants_rng(fn) -> bool:
    import inspect

    try:
        return "rng" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def shard_batch(mesh: Mesh, *arrays, axis: str | None = None):
    """Put host arrays on `mesh` sharded over the batch axis."""
    sh = meshlib.sharding(mesh, _batch_axis(mesh, axis))
    out = tuple(meshlib.put_with_sharding(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]


_batch_axis = meshlib.batch_axis


def replicate(mesh: Mesh, tree):
    """Put a pytree on `mesh` fully replicated (multi-process safe)."""
    sh = meshlib.replicated(mesh)
    if sh.is_fully_addressable:
        return jax.device_put(tree, sh)
    return jax.tree.map(lambda a: meshlib.put_with_sharding(a, sh), tree)


def place_state(mesh: Mesh, tree, rules=None):
    """Put a TrainState (or any param-shaped tree) on `mesh` in the
    layout the jitted step expects: under `rules`
    (partition.PartitionRules — the FSDP/TP path) when given, else
    channel-wise model-sharded on a ("data", "model") mesh (tp.py),
    else replicated (DP/client meshes)."""
    from idc_models_tpu import partition, tp

    if rules is not None:
        return partition.shard_tree(mesh, rules, tree)
    if tp.has_model_axis(mesh):
        return tp.place(mesh, tree)
    return replicate(mesh, tree)
