"""Checkpoint / resume via orbax.

Parity target (SURVEY.md C8, §5): the reference checkpoints only the
pretrainer's weights with Keras `ModelCheckpoint(save_weights_only=True)`
to `<path>/pretrained/cp.ckpt` (fed_model.py:100-105), reloads them on
restart (fed_model.py:136-138), and gates on existence — with the
`sys.path.exists` crash bug Q5 (fed_model.py:175; `os.path` intended).
Nothing checkpoints the distributed or federated loops.

Here every loop state is one pytree (TrainState / ServerState), so a
single orbax save/restore covers params, BatchNorm statistics, optimizer
state, and the step/round counter — checkpoint-resume is uniform across
plain DP training, the two-phase schedule, and federated rounds. The
existence gate is implemented correctly (fixing Q5).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from pathlib import Path
from typing import Any

import jax
import numpy as np

# written INTO the checkpoint directory as the last step of a save;
# its presence is the completion contract checkpoint_exists enforces
_COMPLETE_MARKER = "_IDC_COMPLETE"
# content digest over the saved leaves: bit-rot/truncation DETECTION on
# restore — the marker proves the save finished, the digest proves the
# bytes read back are the bytes written
_DIGEST_FILE = "_IDC_DIGEST.json"


def _tree_digest(state: Any) -> str:
    """sha256 over every leaf's shape + raw bytes in flatten order — a
    content fingerprint a flipped bit or truncated chunk cannot
    survive. Leaves are fetched ONE AT A TIME (per-leaf device_get, so
    digesting an N-GB tree needs one leaf of host memory, not N GB —
    the formula is unchanged and digests recorded before this fix
    still verify) and viewed as numpy; non-array leaves hash their
    repr."""
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        if hasattr(leaf, "shape"):
            a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
            h.update(str((a.shape, a.dtype.str)).encode())
            h.update(a.tobytes())
            del a                       # one leaf resident at a time
        else:
            h.update(repr(jax.device_get(leaf)).encode())
    return h.hexdigest()


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def checkpoint_exists(path: str | os.PathLike) -> bool:
    """The reference's intent at fed_model.py:175 (`os.path.exists`, not
    the buggy `sys.path.exists`) — hardened: a checkpoint directory
    WITHOUT the completion marker is a torn partial left by a crash
    mid-save and is refused (the restore gate, `load_or_train`, then
    retrains instead of crashing into half-written arrays)."""
    path = Path(path)
    if not path.exists():
        return False
    if path.is_dir():
        return (path / _COMPLETE_MARKER).exists()
    # non-directory artifacts (e.g. single-file handlers) have no
    # marker to check; existence is the best signal available
    return True


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True) -> str:
    """Save a pytree (TrainState, ServerState, bare params...) to
    `path`, ATOMICALLY: the tree is written to `<path>.tmp`, stamped
    with a completion marker, and renamed into place with `os.replace`.
    A crash at ANY point leaves either the old complete checkpoint or a
    markerless partial that `checkpoint_exists` refuses — never a
    half-written tree that restores garbage. Multi-host safe: orbax
    coordinates the array writes itself, and the marker + rename
    commit runs on process 0 ONLY, fenced by barriers, so N hosts
    never race the same rename dance (every process returns after the
    commit is visible)."""
    from idc_models_tpu.checkpoint import barrier

    path = Path(path).absolute()
    if jax.process_index() == 0:
        path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    if tmp.exists() and jax.process_index() == 0:
        shutil.rmtree(tmp)              # leftover from a prior crash
    barrier("train-ckpt-clean")
    _checkpointer().save(tmp, state, force=force)
    # COMMIT is process 0's alone: every process touching the marker
    # and racing the same os.replace rename dance was the multi-host
    # corruption bug — N processes renaming <path> -> <path>.old ->
    # gone concurrently can destroy BOTH copies. Orbax's save above is
    # itself multi-host coordinated; the barrier then holds everyone
    # until process 0 has stamped + renamed, so no process returns
    # while <path> is mid-commit.
    barrier("train-ckpt-save")
    if jax.process_index() == 0:
        if tmp.is_dir():
            (tmp / _DIGEST_FILE).write_text(
                json.dumps({"sha256": _tree_digest(state)}))
        (tmp / _COMPLETE_MARKER).touch()
        if path.exists():
            # os.replace cannot overwrite a non-empty directory:
            # retire the old checkpoint first. The unprotected window
            # is between these two renames (metadata ops,
            # microseconds) and a crash inside it still leaves the
            # COMPLETE tree at <path>.old for manual recovery — never
            # a torn <path>.
            old = path.with_name(path.name + ".old")
            if old.exists():
                shutil.rmtree(old)
            os.replace(path, old)
            os.replace(tmp, path)
            shutil.rmtree(old)
        else:
            os.replace(tmp, path)
    barrier("train-ckpt-commit")
    return str(path)


def restore_checkpoint(path: str | os.PathLike, target: Any) -> Any:
    """Restore into the structure/shardings of `target` (an abstract or
    concrete pytree of the same shape as what was saved). Refuses torn
    partial checkpoints (no completion marker) and CORRUPT ones: any
    restore-time failure (truncated chunk, unreadable metadata) is
    re-raised as a ValueError naming the checkpoint, and when the save
    recorded a content digest the restored leaves are verified against
    it — a bit-flip that slips past the storage layer raises here
    instead of returning a silently-garbage TrainState."""
    path = Path(path).absolute()
    if path.is_dir() and not (path / _COMPLETE_MARKER).exists():
        raise ValueError(
            f"checkpoint {path} has no completion marker — either a "
            f"torn partial left by a crash mid-save (delete it, or let "
            f"load_or_train retrain) or a checkpoint from before the "
            f"atomic-save change (touch {path / _COMPLETE_MARKER} to "
            f"accept one you trust)")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
            x, "sharding", None)) if hasattr(x, "shape") else x,
        target)
    try:
        restored = _checkpointer().restore(path, abstract)
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(
            f"checkpoint {path} failed to restore ({type(e).__name__}) "
            f"— corrupt or incompatible on-disk state; delete it (or "
            f"let load_or_train retrain over it)") from e
    digest_file = path / _DIGEST_FILE
    if path.is_dir() and digest_file.exists():
        want = json.loads(digest_file.read_text()).get("sha256")
        got = _tree_digest(restored)
        if want != got:
            raise ValueError(
                f"checkpoint {path} is CORRUPT: restored content digest "
                f"{got[:12]}... does not match the digest recorded at "
                f"save time {str(want)[:12]}... (bit rot, truncation, or "
                f"a partial overwrite) — refusing to hand back garbage "
                f"state; delete it or let load_or_train retrain")
    return restored


def load_or_train(path: str | os.PathLike, target: Any, train_fn):
    """The pretrainer gate (C8): restore `path` if it exists, else run
    `train_fn() -> state`, save it, and return it. A markerless
    directory at `path` (torn partial — or a checkpoint from before the
    atomic-save change) is retrained over, with a loud warning naming
    the migration escape hatch first. A checkpoint that LOOKS complete
    but fails to restore (truncated/bit-flipped after the save) falls
    back to retraining too — corruption costs a retrain, never a run
    on garbage weights."""
    import warnings

    if checkpoint_exists(path):
        try:
            return restore_checkpoint(path, target), True
        except ValueError as e:
            warnings.warn(
                f"checkpoint {path} is unrestorable ({e}) — RETRAINING "
                f"and overwriting it", stacklevel=2)
    elif Path(path).is_dir():
        warnings.warn(
            f"checkpoint {path} exists but has no completion marker "
            f"(torn partial, or saved before the atomic-save change) — "
            f"RETRAINING over it; to restore a pre-existing checkpoint "
            f"you trust, touch {Path(path) / _COMPLETE_MARKER} first",
            stacklevel=2)
    state = train_fn()
    save_checkpoint(path, state)
    return state, False
