"""Checkpoint / resume via orbax.

Parity target (SURVEY.md C8, §5): the reference checkpoints only the
pretrainer's weights with Keras `ModelCheckpoint(save_weights_only=True)`
to `<path>/pretrained/cp.ckpt` (fed_model.py:100-105), reloads them on
restart (fed_model.py:136-138), and gates on existence — with the
`sys.path.exists` crash bug Q5 (fed_model.py:175; `os.path` intended).
Nothing checkpoints the distributed or federated loops.

Here every loop state is one pytree (TrainState / ServerState), so a
single orbax save/restore covers params, BatchNorm statistics, optimizer
state, and the step/round counter — checkpoint-resume is uniform across
plain DP training, the two-phase schedule, and federated rounds. The
existence gate is implemented correctly (fixing Q5).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def checkpoint_exists(path: str | os.PathLike) -> bool:
    """The reference's intent at fed_model.py:175 (`os.path.exists`, not
    the buggy `sys.path.exists`)."""
    return Path(path).exists()


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True) -> str:
    """Save a pytree (TrainState, ServerState, bare params...) to `path`."""
    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    _checkpointer().save(path, state, force=force)
    return str(path)


def restore_checkpoint(path: str | os.PathLike, target: Any) -> Any:
    """Restore into the structure/shardings of `target` (an abstract or
    concrete pytree of the same shape as what was saved)."""
    path = Path(path).absolute()
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
            x, "sharding", None)) if hasattr(x, "shape") else x,
        target)
    return _checkpointer().restore(path, abstract)


def load_or_train(path: str | os.PathLike, target: Any, train_fn):
    """The pretrainer gate (C8): restore `path` if it exists, else run
    `train_fn() -> state`, save it, and return it."""
    if checkpoint_exists(path):
        return restore_checkpoint(path, target), True
    state = train_fn()
    save_checkpoint(path, state)
    return state, False
