"""Checkpoint / resume via orbax.

Parity target (SURVEY.md C8, §5): the reference checkpoints only the
pretrainer's weights with Keras `ModelCheckpoint(save_weights_only=True)`
to `<path>/pretrained/cp.ckpt` (fed_model.py:100-105), reloads them on
restart (fed_model.py:136-138), and gates on existence — with the
`sys.path.exists` crash bug Q5 (fed_model.py:175; `os.path` intended).
Nothing checkpoints the distributed or federated loops.

Here every loop state is one pytree (TrainState / ServerState), so a
single orbax save/restore covers params, BatchNorm statistics, optimizer
state, and the step/round counter — checkpoint-resume is uniform across
plain DP training, the two-phase schedule, and federated rounds. The
existence gate is implemented correctly (fixing Q5).
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Any

import jax

# written INTO the checkpoint directory as the last step of a save;
# its presence is the completion contract checkpoint_exists enforces
_COMPLETE_MARKER = "_IDC_COMPLETE"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def checkpoint_exists(path: str | os.PathLike) -> bool:
    """The reference's intent at fed_model.py:175 (`os.path.exists`, not
    the buggy `sys.path.exists`) — hardened: a checkpoint directory
    WITHOUT the completion marker is a torn partial left by a crash
    mid-save and is refused (the restore gate, `load_or_train`, then
    retrains instead of crashing into half-written arrays)."""
    path = Path(path)
    if not path.exists():
        return False
    if path.is_dir():
        return (path / _COMPLETE_MARKER).exists()
    # non-directory artifacts (e.g. single-file handlers) have no
    # marker to check; existence is the best signal available
    return True


def save_checkpoint(path: str | os.PathLike, state: Any, *,
                    force: bool = True) -> str:
    """Save a pytree (TrainState, ServerState, bare params...) to
    `path`, ATOMICALLY: the tree is written to `<path>.tmp`, stamped
    with a completion marker, and renamed into place with `os.replace`.
    A crash at ANY point leaves either the old complete checkpoint or a
    markerless partial that `checkpoint_exists` refuses — never a
    half-written tree that restores garbage."""
    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    if tmp.exists():
        shutil.rmtree(tmp)              # leftover from a prior crash
    _checkpointer().save(tmp, state, force=force)
    (tmp / _COMPLETE_MARKER).touch()
    if path.exists():
        # os.replace cannot overwrite a non-empty directory: retire the
        # old checkpoint first. The unprotected window is between these
        # two renames (metadata ops, microseconds) and a crash inside it
        # still leaves the COMPLETE tree at <path>.old for manual
        # recovery — never a torn <path>.
        old = path.with_name(path.name + ".old")
        if old.exists():
            shutil.rmtree(old)
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old)
    else:
        os.replace(tmp, path)
    return str(path)


def restore_checkpoint(path: str | os.PathLike, target: Any) -> Any:
    """Restore into the structure/shardings of `target` (an abstract or
    concrete pytree of the same shape as what was saved). Refuses torn
    partial checkpoints (no completion marker)."""
    path = Path(path).absolute()
    if path.is_dir() and not (path / _COMPLETE_MARKER).exists():
        raise ValueError(
            f"checkpoint {path} has no completion marker — either a "
            f"torn partial left by a crash mid-save (delete it, or let "
            f"load_or_train retrain) or a checkpoint from before the "
            f"atomic-save change (touch {path / _COMPLETE_MARKER} to "
            f"accept one you trust)")
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(
            x, "sharding", None)) if hasattr(x, "shape") else x,
        target)
    return _checkpointer().restore(path, abstract)


def load_or_train(path: str | os.PathLike, target: Any, train_fn):
    """The pretrainer gate (C8): restore `path` if it exists, else run
    `train_fn() -> state`, save it, and return it. A markerless
    directory at `path` (torn partial — or a checkpoint from before the
    atomic-save change) is retrained over, with a loud warning naming
    the migration escape hatch first."""
    if checkpoint_exists(path):
        return restore_checkpoint(path, target), True
    if Path(path).is_dir():
        import warnings

        warnings.warn(
            f"checkpoint {path} exists but has no completion marker "
            f"(torn partial, or saved before the atomic-save change) — "
            f"RETRAINING over it; to restore a pre-existing checkpoint "
            f"you trust, touch {Path(path) / _COMPLETE_MARKER} first",
            stacklevel=2)
    state = train_fn()
    save_checkpoint(path, state)
    return state, False
