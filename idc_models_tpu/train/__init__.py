from idc_models_tpu.train import losses, metrics, state, step  # noqa: F401
from idc_models_tpu.train.state import TrainState, create_train_state, rmsprop  # noqa: F401
from idc_models_tpu.train.step import (  # noqa: F401
    jit_data_parallel,
    make_eval_step,
    make_train_step,
    replicate,
    shard_batch,
)
