from idc_models_tpu.train import losses, metrics, state, step  # noqa: F401
from idc_models_tpu.train.state import TrainState, create_train_state, rmsprop  # noqa: F401
from idc_models_tpu.train.step import (  # noqa: F401
    jit_data_parallel,
    make_eval_step,
    make_train_step,
    replicate,
    shard_batch,
)
from idc_models_tpu.train.loop import (  # noqa: F401
    Evaluator,
    TwoPhaseConfig,
    TwoPhaseResult,
    evaluate,
    fit,
    predict,
    two_phase_fit,
)
from idc_models_tpu.train.checkpoint import (  # noqa: F401
    checkpoint_exists,
    load_or_train,
    restore_checkpoint,
    save_checkpoint,
)
