"""Ring-sharded KV-cache decoding: serve the long contexts the ring trains.

The training side (`ring_attention.py`) shards the sequence over a
"seq" mesh axis and never materializes it on one device; this module
gives inference the same property. The KV cache lives sharded over the
ring — device i owns cache slots [i*T/n, (i+1)*T/n) — and a decode step
for ONE new token is:

1. append: the slot owner (pos // t_shard) writes the new k/v into its
   resident shard; every other device's shard is untouched — no
   collective, the cache never moves;
2. local attend: every device scores the (replicated, [B, 1, H, D])
   query against its OWN K/V shard, masked to global positions <= pos —
   a [B, H, t_shard] score row, never [T, T] anything;
3. merge: one numerically-stable distributed softmax combine over the
   "seq" axis — `pmax` of the local maxima, then a single `psum` of the
   corrected (l, acc) partials. Two collectives per token, both riding
   ICI; O(T/n) memory per device, exactly like training.

This is flash-attention's (m, l, acc) algebra applied ACROSS devices
instead of across ring steps: where training's ring rotates K/V blocks
through a fixed schedule, decode holds K/V still and reduces the
per-shard partials — the right shape for one-token queries, where a
rotating ring would serialize n hops for no reuse.

The cache layout IS the training layout (contiguous "seq" sharding of
[B, T, H, D]), so a trained model's prompt K/V can be placed directly:
pad to t_max, `jax.device_put` under `cache_sharding`, and decode
continues from there — `prefill` does exactly this and is pinned
bit-identical to decoding the prompt token by token. The zigzag layout
is a TRAINING optimization (balancing a causal ring schedule that
decode does not run) and deliberately has no decode counterpart.

Exactness: every step equals the last row of full causal attention over
the sequence so far, fp tolerance, pinned by tests/test_ring_decode.py.
The reference has no serving path at all (SURVEY.md §2 ends at training
+ eval), so this is beyond-parity capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from idc_models_tpu import collectives
from idc_models_tpu import mesh as meshlib

from idc_models_tpu.compat import shard_map

_MASKED = -1e30  # same finite sentinel as ring_attention._MASKED


def cache_sharding(mesh: Mesh, axis: str = meshlib.SEQ_AXIS) -> NamedSharding:
    """[B, T_max, H, D] cache layout — identical to the training-side
    q/k/v sharding (`mesh.batch_seq_sharding`, the one construction
    site), so trained K/V drops in with no relayout."""
    return meshlib.batch_seq_sharding(mesh, axis, trailing=2)


def init_cache(mesh: Mesh, batch: int, t_max: int, heads: int, dim: int,
               *, dtype=jnp.bfloat16, axis: str = meshlib.SEQ_AXIS):
    """Zero-initialized (k, v) caches, sharded over the ring."""
    n = mesh.shape[axis]
    if t_max % n:
        raise ValueError(f"t_max {t_max} not divisible by the ring size "
                         f"{n} over mesh axis {axis!r}")
    sh = cache_sharding(mesh, axis)
    # put_with_sharding, not device_put: on a multi-host mesh each
    # process materializes only its addressable shards (mesh.py)
    mk = functools.partial(np.zeros, (batch, t_max, heads, dim),
                           jnp.dtype(dtype))
    return (meshlib.put_with_sharding(mk(), sh),
            meshlib.put_with_sharding(mk(), sh))


def make_ring_decode(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                     scale: float | None = None, jit: bool = True):
    """Build ``fn(k_cache, v_cache, q_t, k_t, v_t, pos) ->
    (out_t, k_cache, v_cache)``.

    q_t/k_t/v_t are the ONE new token's projections, [B, 1, H, D]
    (replicated over `axis`); `pos` is its global position (int32
    scalar; cache slots > pos must still be zero/garbage-masked). The
    returned function is jitted with both caches donated — the decode
    loop updates in place, O(1) HBM traffic per step beyond the shard
    writes.

    ``jit=False`` returns the same function un-jitted, for callers that
    trace it into a LARGER jitted program (the LM's fused scan decode
    loop, models/lm.py) — a nested jit would discard the donation with
    a warning, and the caller's top-level jit owns donation anyway.
    Traced callers also own the `pos` bound (see below)."""
    n = mesh.shape[axis]

    def per_device(kc, vc, q, kt, vt, pos):
        b, t_shard, h, d = kc.shape
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        pos = jnp.asarray(pos, jnp.int32)
        owner = pos // t_shard
        slot = pos % t_shard
        # 1. append — O(1) traffic: read the ONE slot, select the new
        # token on the owner (non-owners write their existing value
        # back), one single-slot update that donation lowers in place —
        # never a whole-shard copy
        mine = (owner == i)
        old_k = lax.dynamic_slice(kc, (0, slot, 0, 0), kt.shape)
        old_v = lax.dynamic_slice(vc, (0, slot, 0, 0), vt.shape)
        kc = lax.dynamic_update_slice(
            kc, jnp.where(mine, kt.astype(kc.dtype), old_k),
            (0, slot, 0, 0))
        vc = lax.dynamic_update_slice(
            vc, jnp.where(mine, vt.astype(vc.dtype), old_v),
            (0, slot, 0, 0))
        # 2. local attend against the resident shard, f32 accumulation
        # (preferred_element_type, NOT astype: upcasting a 64k-slot bf16
        # cache would materialize a 2x-size f32 copy per step — the MXU
        # accumulates in f32 natively, same as ring_attention's blocks)
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0], kc,
                       preferred_element_type=jnp.float32) * scale_
        visible = (i * t_shard + jnp.arange(t_shard)) <= pos
        s = jnp.where(visible[None, None, :], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)                       # [B, H]
        p = jnp.exp(s - m_loc[..., None])
        # a fully-masked shard (all slots beyond pos) contributes
        # p = exp(0) = 1 garbage — zero it explicitly so the psum is
        # exact rather than relying on the corr ~ exp(_MASKED - m) == 0
        # underflow
        p = jnp.where(visible[None, None, :], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)                       # [B, H]
        acc_loc = jnp.einsum("bhk,bkhd->bhd", p, vc,
                             preferred_element_type=jnp.float32)
        # 3. one stable softmax merge across the ring
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]  # [B,H,D]
        return out[:, None].astype(q.dtype), kc, vc  # [B,1,H,D]

    bo = meshlib.batch_axes(mesh, axis)   # "model" stays weight-only
    cache_spec = P(bo, axis, None, None)
    tok_spec = P(bo, None, None, None)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(cache_spec, cache_spec, tok_spec, tok_spec, tok_spec,
                  P()),
        out_specs=(tok_spec, cache_spec, cache_spec),
        check_vma=False,
    )

    def checked(kc, vc, q_t, k_t, v_t, pos):
        if q_t.shape[1] != 1:
            raise ValueError(
                f"ring decode takes ONE token per step: q_t has "
                f"sequence length {q_t.shape[1]} (batch prefill goes "
                f"through `prefill` / the training ring)")
        if kc.shape[1] % n:
            raise ValueError(
                f"cache length {kc.shape[1]} not divisible by the ring "
                f"size {n} over mesh axis {axis!r}")
        return mapped(kc, vc, q_t, k_t, v_t, pos)

    if not jit:
        return checked

    jitted = jax.jit(checked, donate_argnums=(0, 1))

    def fn(kc, vc, q_t, k_t, v_t, pos):
        # pos >= t_max would silently drop the append (no shard owns
        # the slot) and return attention that excludes the new token —
        # reject ANY concrete out-of-range position here: python and
        # numpy ints, numpy scalars, and already-materialized jax
        # scalars (a jnp.int32(t_max) must fail the same way, not
        # silently vanish). Callers tracing pos (their own jit/scan
        # loop) own the bound as a contract.
        import numpy as _np

        concrete = None
        if isinstance(pos, (int, _np.integer)):
            concrete = int(pos)
        elif (isinstance(pos, (jax.Array, _np.ndarray))
              and jnp.ndim(pos) == 0):
            try:
                concrete = int(pos)
            except jax.errors.ConcretizationTypeError:
                pass   # traced: the caller's jit/scan owns the bound
        if concrete is not None and not (0 <= concrete < kc.shape[1]):
            raise ValueError(
                f"pos {concrete} outside the cache (t_max {kc.shape[1]})"
                f" — grow the cache at init/prefill time; decode cannot "
                f"append past it")
        return jitted(kc, vc, q_t, k_t, v_t, pos)

    return fn


def make_batched_ring_decode(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                             scale: float | None = None,
                             jit: bool = False,
                             quantized: bool = False):
    """Per-slot decode fold for the continuous-batching engine
    (serve/engine.py): ``fn(k_cache, v_cache, q_t, k_t, v_t, pos, live)
    -> (out_t, k_cache, v_cache)`` where every batch row is an
    INDEPENDENT sequence at its OWN position.

    With ``quantized=True`` the caches hold int8 K/V and the signature
    grows per-(row, head) dequantization scales: ``fn(kc, vc, q_t, k_t,
    v_t, pos, live, k_scale, v_scale)`` with both scales float32 [B, H].
    Because a scale is constant over the slot dimension and head_dim,
    dequantization FACTORS OUT of both einsums — scores multiply by
    k_scale and the value accumulator by v_scale AFTER the contraction —
    so the int8 cache is never materialized as a float copy (the whole
    point: the HBM win is capacity AND bandwidth). Appends quantize the
    new token's K/V with the row's existing scale (clipped to ±127):
    scales are set once at insert from the prefill content, so decode
    tokens whose activations outgrow the prompt's range clip — the
    documented int8 accuracy caveat (docs/LONG_CONTEXT.md).

    `pos` is int32 [B] (row b's new token sits at global position
    pos[b]) and `live` is bool [B]: rows with live=False append NOTHING
    — their cache shard is bit-untouched, which is what lets a finished
    serving slot idle through decode windows without corrupting the
    cache a recycled request will overwrite. The attend/merge algebra is
    the scalar `make_ring_decode` fold applied row-wise (same einsums,
    same masking, same two-collective softmax merge), so a live row's
    output is bit-identical to the scalar path at the same position.

    Rows where live=False may carry pos == t_max (one past the end, the
    natural "finished" frontier); positions are clamped internally for
    the attend and the masked append never fires for them. Defaults to
    ``jit=False`` because the intended caller is the engine's fused
    decode window, whose top-level jit owns donation."""
    n = mesh.shape[axis]

    def per_device(kc, vc, q, kt, vt, pos, live, k_scale=None,
                   v_scale=None):
        b, t_shard, h, d = kc.shape
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        pos = jnp.asarray(pos, jnp.int32)
        live = jnp.asarray(live, jnp.bool_)
        # finished rows legitimately sit at pos == t_max; clamp so the
        # owner/slot arithmetic and visibility mask stay in range (the
        # append is gated on `live`, never on the clamp)
        posc = jnp.clip(pos, 0, n * t_shard - 1)
        owner = posc // t_shard
        slot = posc % t_shard
        mine = (owner == i) & live

        if quantized:
            # quantize the incoming token with the ROW's frozen scale
            # (insert-time absmax); a dead row's zero scale divides to
            # inf but clips finitely and the live gate discards it
            kt = jnp.clip(jnp.round(
                kt.astype(jnp.float32) / k_scale[:, None, :, None]),
                -127, 127)
            vt = jnp.clip(jnp.round(
                vt.astype(jnp.float32) / v_scale[:, None, :, None]),
                -127, 127)

        # per-row O(1) append: each row reads its ONE slot and writes the
        # new token back only when this shard owns the row's position AND
        # the row is live — a dead row's shard is bit-untouched
        def row_append(c, t, s, m):
            old = lax.dynamic_slice(c, (s, 0, 0), t.shape)
            return lax.dynamic_update_slice(
                c, jnp.where(m, t.astype(c.dtype), old), (s, 0, 0))

        kc = jax.vmap(row_append)(kc, kt, slot, mine)
        vc = jax.vmap(row_append)(vc, vt, slot, mine)
        # row-wise local attend + the same stable merge as the scalar
        # fold (see make_ring_decode); visibility is per ROW now
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0], kc,
                       preferred_element_type=jnp.float32) * scale_
        if quantized:
            # dequantize by FACTORING the per-(row, head) scale out of
            # the contraction — no float copy of the cache exists
            s = s * k_scale[:, :, None]
        visible = ((i * t_shard + jnp.arange(t_shard))[None, :]
                   <= posc[:, None])                       # [B, t_shard]
        s = jnp.where(visible[:, None, :], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)                        # [B, H]
        p = jnp.exp(s - m_loc[..., None])
        p = jnp.where(visible[:, None, :], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)
        acc_loc = jnp.einsum("bhk,bkhd->bhd", p, vc,
                             preferred_element_type=jnp.float32)
        if quantized:
            acc_loc = acc_loc * v_scale[..., None]
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]
        return out[:, None].astype(q.dtype), kc, vc

    bo = meshlib.batch_axes(mesh, axis)   # "model" stays weight-only
    cache_spec = P(bo, axis, None, None)
    tok_spec = P(bo, None, None, None)
    # scales are per (row, head): the batch dim shards with the caches'
    # over the non-seq axes (P() would mis-shape the per-device divide
    # on any mesh with a non-trivial non-seq axis)
    scale_specs = (P(bo, None), P(bo, None)) if quantized else ()
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(cache_spec, cache_spec, tok_spec, tok_spec, tok_spec,
                  P(), P()) + scale_specs,
        out_specs=(tok_spec, cache_spec, cache_spec),
        check_vma=False,
    )

    def checked(kc, vc, q_t, k_t, v_t, pos, live, *scales):
        if quantized and len(scales) != 2:
            raise ValueError("quantized fold needs (k_scale, v_scale)")
        if not quantized and scales:
            raise ValueError("scales passed to a non-quantized fold")
        if q_t.shape[1] != 1:
            raise ValueError(
                f"batched ring decode takes ONE token per row per step: "
                f"q_t has sequence length {q_t.shape[1]}")
        if kc.shape[1] % n:
            raise ValueError(
                f"cache length {kc.shape[1]} not divisible by the ring "
                f"size {n} over mesh axis {axis!r}")
        if jnp.shape(pos) != (kc.shape[0],):
            raise ValueError(
                f"pos must be one position per row, shape "
                f"({kc.shape[0]},); got {jnp.shape(pos)}")
        # reject concrete out-of-range LIVE positions, same contract as
        # the scalar path (a silently dropped append is the failure mode)
        if (isinstance(pos, (np.ndarray, list, tuple))
                and isinstance(live, (np.ndarray, list, tuple))):
            p_arr = np.asarray(pos)
            bad = p_arr[(np.asarray(live)) & ((p_arr < 0)
                                              | (p_arr >= kc.shape[1]))]
            if bad.size:
                raise ValueError(
                    f"live pos {bad.tolist()} outside the cache "
                    f"(t_max {kc.shape[1]})")
        return mapped(kc, vc, q_t, k_t, v_t, pos, live, *scales)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def make_batched_chunk_ring_decode(mesh: Mesh, *,
                                   axis: str = meshlib.SEQ_AXIS,
                                   scale: float | None = None,
                                   jit: bool = False,
                                   quantized: bool = False):
    """Per-slot chunk fold for SPECULATIVE VERIFICATION
    (serve/engine.py): ``fn(k_cache, v_cache, q, k, v, pos, live)
    -> (out, k_cache, v_cache)`` runs C draft tokens per batch row
    against the row's ring cache in ONE dispatch, each row an
    independent sequence at its OWN position — the chunk-query algebra
    of `make_chunk_ring_decode` crossed with the per-row masking of
    `make_batched_ring_decode`.

    q/k/v are [B, C, H, D] (replicated over `axis`); `pos` is int32 [B]
    (row b's chunk occupies global positions [pos[b], pos[b] + C)) and
    `live` is bool [B]: rows with live=False append NOTHING — their
    cache shard is bit-untouched, exactly like the one-token batched
    fold's dead rows, which is what lets non-speculating slots ride
    through a verify dispatch as bit-level no-ops. Per live row:

    1. splice the chunk's K/V into the row's resident shard slots
       (positions outside [pos_b, pos_b + C), and every slot of a dead
       row, keep their stored value);
    2. attend every chunk query against the row's WHOLE updated shard
       with per-query causal visibility (cache position <= query
       position — covers the cached history AND causality inside the
       chunk, since the chunk's own K/V landed in step 1);
    3. merge across the ring with the same stable (m, l, acc) softmax
       algebra as every other fold — two collectives per CHUNK.

    A live row's per-query outputs are therefore exactly what C
    successive one-token decode folds would produce IF every query's
    preceding chunk tokens were the tokens actually decoded — which is
    precisely the speculative accept rule's job to check. Callers own
    the bound pos[b] + C <= t_max for live rows (an out-of-range splice
    slot silently drops, the same contract as the scalar fold's traced
    positions).

    With ``quantized=True`` the caches hold int8 K/V and the signature
    grows per-(row, head) float32 [B, H] dequant scales, factored out
    of the contractions exactly as in `make_batched_ring_decode`;
    appends quantize with the row's frozen insert-time scale. Defaults
    to ``jit=False`` for tracing into the engine's verify program,
    whose top-level jit owns donation."""
    n = mesh.shape[axis]

    def per_device(kc, vc, q, kt, vt, pos, live, k_scale=None,
                   v_scale=None):
        b, t_shard, h, d = kc.shape
        c = q.shape[1]
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        pos = jnp.asarray(pos, jnp.int32)
        live = jnp.asarray(live, jnp.bool_)
        # finished/riding rows may sit at pos == t_max; clamp keeps the
        # slot arithmetic in range (the splice is gated on `live`)
        posc = jnp.clip(pos, 0, n * t_shard - 1)
        g = i * t_shard + jnp.arange(t_shard, dtype=jnp.int32)

        if quantized:
            kt = jnp.clip(jnp.round(
                kt.astype(jnp.float32) / k_scale[:, None, :, None]),
                -127, 127)
            vt = jnp.clip(jnp.round(
                vt.astype(jnp.float32) / v_scale[:, None, :, None]),
                -127, 127)

        # 1. per-row splice: this shard's slots inside the row's
        # [pos_b, pos_b + C) span take the chunk row at (g - pos_b);
        # everything else — including every slot of a dead row —
        # rewrites itself with itself, bit-untouched
        take_new = ((g[None, :] >= posc[:, None])
                    & (g[None, :] < posc[:, None] + c)
                    & live[:, None])                      # [B, t_shard]
        src = jnp.clip(g[None, :] - posc[:, None], 0, c - 1)

        def splice(cache, tok):
            gathered = jnp.take_along_axis(
                tok, src[:, :, None, None], axis=1).astype(cache.dtype)
            return jnp.where(take_new[:, :, None, None], gathered,
                             cache)

        kc = splice(kc, kt)
        vc = splice(vc, vt)
        # 2. per-row, per-query local attend against the resident shard
        qpos = posc[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
        s = jnp.einsum("bchd,bkhd->bhck", q, kc,
                       preferred_element_type=jnp.float32) * scale_
        if quantized:
            s = s * k_scale[:, :, None, None]
        visible = g[None, None, :] <= qpos[:, :, None]  # [B, C, t_shard]
        s = jnp.where(visible[:, None], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)                       # [B, H, C]
        p = jnp.exp(s - m_loc[..., None])
        p = jnp.where(visible[:, None], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)
        acc_loc = jnp.einsum("bhck,bkhd->bhcd", p, vc,
                             preferred_element_type=jnp.float32)
        if quantized:
            acc_loc = acc_loc * v_scale[:, :, None, None]
        # 3. one stable softmax merge across the ring (per chunk)
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]
        return jnp.moveaxis(out, 1, 2).astype(q.dtype), kc, vc

    bo = meshlib.batch_axes(mesh, axis)   # "model" stays weight-only
    cache_spec = P(bo, axis, None, None)
    tok_spec = P(bo, None, None, None)
    scale_specs = (P(bo, None), P(bo, None)) if quantized else ()
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(cache_spec, cache_spec, tok_spec, tok_spec, tok_spec,
                  P(), P()) + scale_specs,
        out_specs=(tok_spec, cache_spec, cache_spec),
        check_vma=False,
    )

    def checked(kc, vc, q, k, v, pos, live, *scales):
        if quantized and len(scales) != 2:
            raise ValueError("quantized fold needs (k_scale, v_scale)")
        if not quantized and scales:
            raise ValueError("scales passed to a non-quantized fold")
        if q.ndim != 4 or q.shape[1] < 1:
            raise ValueError(f"batched chunk fold expects [B, C, H, D] "
                             f"queries, got shape {jnp.shape(q)}")
        if kc.shape[1] % n:
            raise ValueError(
                f"cache length {kc.shape[1]} not divisible by the ring "
                f"size {n} over mesh axis {axis!r}")
        if jnp.shape(pos) != (kc.shape[0],):
            raise ValueError(
                f"pos must be one position per row, shape "
                f"({kc.shape[0]},); got {jnp.shape(pos)}")
        # reject concrete out-of-range LIVE chunk spans, same contract
        # as every other fold (a silent dropped splice is the failure)
        if (isinstance(pos, (np.ndarray, list, tuple))
                and isinstance(live, (np.ndarray, list, tuple))):
            p_arr = np.asarray(pos)
            bad = p_arr[(np.asarray(live))
                        & ((p_arr < 0)
                           | (p_arr + q.shape[1] > kc.shape[1]))]
            if bad.size:
                raise ValueError(
                    f"live chunk start {bad.tolist()} + chunk "
                    f"{q.shape[1]} outside the cache "
                    f"(t_max {kc.shape[1]})")
        return mapped(kc, vc, q, k, v, pos, live, *scales)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def _paged_specs(mesh, axis, quantized):
    """shard_map specs shared by the paged folds: pools shard over the
    PHYSICAL page dim (device i owns pages [i*P/n, (i+1)*P/n)), page
    tables and dequant scales replicate — a slot's logical pages may
    land on any device, so the table must be readable everywhere and
    the per-(page, head) scales are tiny."""
    pool_spec = P(axis, None, None, None)
    rep = P()
    scale_specs = (rep, rep) if quantized else ()
    return pool_spec, rep, scale_specs


def _page_view(pool, pt, i, p_loc):
    """Gather a pool shard into each slot's LOGICAL view: pt [S, L]
    physical page ids (-1 = unallocated) -> [S, L*ps, H, D] laid out in
    logical position order, plus the [S, L] this-shard ownership mask.
    Rows gathered through a clamped foreign/unallocated id hold garbage
    the caller's visibility mask discards — exactly like the contiguous
    folds' beyond-pos cache slots."""
    local = jnp.clip(pt - i * p_loc, 0, p_loc - 1)         # [S, L]
    mine = (pt >= i * p_loc) & (pt < (i + 1) * p_loc)      # [S, L]
    view = pool[local]                                     # [S,L,ps,H,D]
    s, l, ps, h, d = view.shape
    return view.reshape(s, l * ps, h, d), mine


def make_paged_batched_ring_decode(mesh: Mesh, *, page_size: int,
                                   axis: str = meshlib.SEQ_AXIS,
                                   scale: float | None = None,
                                   jit: bool = False,
                                   quantized: bool = False):
    """Page-table-indirect variant of `make_batched_ring_decode` — the
    one-token-per-row fold of the PAGED serving engine:
    ``fn(k_pool, v_pool, page_table, q_t, k_t, v_t, pos, live)
    -> (out_t, k_pool, v_pool)``.

    The caches are a POOL of fixed-size pages `[n_pages, page_size, H,
    D]` shared by every slot (sharded over the page dim across the
    ring) plus an int32 page table `[S, L]` mapping slot b's logical
    page j to a physical page (-1 = unallocated). Per live row the fold

    1. appends the new token into the ONE physical page owning its
       position — a unique-index scatter; rows whose target page lives
       on another device (or that are dead) are dropped outright, so a
       dead row's pages are bit-untouched;
    2. gathers the row's logical view from the resident shard and runs
       the SAME per-row attend as the contiguous fold, with visibility
       = (position <= pos) AND the page is physically here — pages on
       other devices (and unallocated -1 entries) contribute nothing;
    3. merges across the ring with the identical two-collective
       (m, l, acc) softmax algebra.

    On a 1-device mesh the gathered view presents exactly the
    contiguous cache's values in the same reduction order, so a live
    row's output is BIT-IDENTICAL to the contiguous batched fold
    (gated by test); on a multi-device ring the per-device partition
    differs (pages vs position ranges), so parity is fp-close +
    argmax-equal — the same contract chunked prefill already carries.

    With ``quantized=True`` pools hold int8 pages and the signature
    grows PER-(PAGE, HEAD) float32 ``[n_pages, H]`` dequant scales
    (replicated): scores and value accumulations dequantize through a
    per-page gather of the scales (a scale varies along the position
    axis here, so it multiplies the per-page score/probability blocks
    instead of factoring fully out); appends quantize with the target
    page's existing scale. Callers own the bound pos[b] < L*page_size
    AND that the owning page is allocated for live rows — an
    unallocated append drops silently, the same traced-position
    contract as every other fold."""
    n = mesh.shape[axis]

    def per_device(kp, vp, pt, q, kt, vt, pos, live, k_scale=None,
                   v_scale=None):
        p_loc, ps, h, d = kp.shape
        s_rows, l_pages = pt.shape
        n_pages = p_loc * n
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        pos = jnp.asarray(pos, jnp.int32)
        live = jnp.asarray(live, jnp.bool_)
        posc = jnp.clip(pos, 0, l_pages * ps - 1)
        lpage = posc // ps
        slot_in = posc % ps
        phys = jnp.take_along_axis(pt, lpage[:, None], axis=1)[:, 0]
        writer = (phys >= i * p_loc) & (phys < (i + 1) * p_loc) & live
        if quantized:
            ksr = k_scale[jnp.clip(phys, 0, n_pages - 1)]    # [S, H]
            vsr = v_scale[jnp.clip(phys, 0, n_pages - 1)]
            kt = jnp.clip(jnp.round(
                kt.astype(jnp.float32) / ksr[:, None, :, None]),
                -127, 127)
            vt = jnp.clip(jnp.round(
                vt.astype(jnp.float32) / vsr[:, None, :, None]),
                -127, 127)
        # append: one (page, slot) cell per live row. Non-writers are
        # redirected past the shard and DROPPED — never a masked
        # rewrite, so collisions with real writers are impossible and
        # dead rows leave the pool bit-untouched. Pages are exclusively
        # owned by one slot, hence unique indices.
        pl = jnp.where(writer, phys - i * p_loc, p_loc)
        kp = kp.at[pl, slot_in].set(kt[:, 0].astype(kp.dtype),
                                    mode="drop", unique_indices=True)
        vp = vp.at[pl, slot_in].set(vt[:, 0].astype(vp.dtype),
                                    mode="drop", unique_indices=True)
        # per-row attend over the gathered logical view — the same
        # einsums/masking/merge as the contiguous batched fold
        kv_view, mine = _page_view(kp, pt, i, p_loc)
        vv_view, _ = _page_view(vp, pt, i, p_loc)
        s = jnp.einsum("bhd,bkhd->bhk", q[:, 0], kv_view,
                       preferred_element_type=jnp.float32) * scale_
        if quantized:
            ptc = jnp.clip(pt, 0, n_pages - 1)
            ks_view = k_scale[ptc]                       # [S, L, H]
            s = (s.reshape(s_rows, h, l_pages, ps)
                 * jnp.moveaxis(ks_view, 2, 1)[..., None]
                 ).reshape(s_rows, h, l_pages * ps)
        g = (jnp.arange(l_pages, dtype=jnp.int32)[:, None] * ps
             + jnp.arange(ps, dtype=jnp.int32)[None, :]).reshape(-1)
        visible = (jnp.repeat(mine, ps, axis=1)
                   & (g[None, :] <= posc[:, None]))       # [S, L*ps]
        s = jnp.where(visible[:, None, :], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)
        p = jnp.exp(s - m_loc[..., None])
        p = jnp.where(visible[:, None, :], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)
        if quantized:
            vs_view = v_scale[jnp.clip(pt, 0, n_pages - 1)]
            p_v = (p.reshape(s_rows, h, l_pages, ps)
                   * jnp.moveaxis(vs_view, 2, 1)[..., None]
                   ).reshape(s_rows, h, l_pages * ps)
        else:
            p_v = p
        acc_loc = jnp.einsum("bhk,bkhd->bhd", p_v, vv_view,
                             preferred_element_type=jnp.float32)
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]
        return out[:, None].astype(q.dtype), kp, vp

    pool_spec, rep, scale_specs = _paged_specs(mesh, axis, quantized)
    tok_spec = P(meshlib.batch_axes(mesh, axis),
                 None, None, None)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(pool_spec, pool_spec, rep, tok_spec, tok_spec,
                  tok_spec, rep, rep) + scale_specs,
        out_specs=(tok_spec, pool_spec, pool_spec),
        check_vma=False,
    )

    def checked(kp, vp, pt, q_t, k_t, v_t, pos, live, *scales):
        _check_paged_pool(kp, pt, n, page_size, quantized, scales)
        if q_t.shape[1] != 1:
            raise ValueError(
                f"paged batched decode takes ONE token per row per "
                f"step: q_t has sequence length {q_t.shape[1]}")
        if jnp.shape(pos) != (pt.shape[0],):
            raise ValueError(
                f"pos must be one position per page-table row, shape "
                f"({pt.shape[0]},); got {jnp.shape(pos)}")
        return mapped(kp, vp, pt, q_t, k_t, v_t, pos, live, *scales)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def _check_paged_pool(kp, pt, n, page_size, quantized, scales):
    """The one pool/table contract shared by every paged fold."""
    if quantized and len(scales) != 2:
        raise ValueError("quantized paged fold needs (k_scale, v_scale)")
    if not quantized and scales:
        raise ValueError("scales passed to a non-quantized paged fold")
    if kp.shape[1] != page_size:
        raise ValueError(f"pool page dim {kp.shape[1]} != the fold's "
                         f"page_size {page_size}")
    if kp.shape[0] % n:
        raise ValueError(
            f"page pool size {kp.shape[0]} not divisible by the ring "
            f"size {n}")
    if pt.ndim != 2:
        raise ValueError(f"page table must be [S, L] int32, got shape "
                         f"{jnp.shape(pt)}")


def make_paged_chunk_ring_decode(mesh: Mesh, *, page_size: int,
                                 axis: str = meshlib.SEQ_AXIS,
                                 scale: float | None = None,
                                 jit: bool = False,
                                 quantized: bool = False):
    """Page-table-indirect variant of `make_chunk_ring_decode` — the
    chunked-prefill fold of the paged engine: ``fn(k_pool, v_pool,
    page_table, q, k, v, start, p_end) -> (out, k_pool, v_pool)``
    runs C prompt tokens against the request's OWN pages, writing
    positions [start, p_end) straight into the pool (no contiguous
    single-request cache ever exists on the paged path).

    `page_table` is the request's row(s), [B, L]; callers align chunks
    to the page grid (page_size | chunk, enforced by the engine) so a
    chunk fills whole pages and a completed chunk boundary's pages are
    NEVER written again — the invariant that lets prefix-cache
    snapshots share pages with live slots zero-copy.

    With ``quantized=True`` the signature grows [n_pages, H] per-page
    scale arrays which the fold UPDATES and returns: ``fn(..., start,
    p_end, k_scale, v_scale) -> (out, k_pool, v_pool, k_scale,
    v_scale)``. Each page this chunk fills gets a fresh per-head scale
    (absmax of its REAL tokens / 127, floor 1e-8) before its content
    quantizes with it — per-page scales are FINER than the contiguous
    engine's per-slot ones, so int8 paged output is gated on bounded
    drift + determinism rather than bit parity (docs/LONG_CONTEXT.md).
    """
    n = mesh.shape[axis]

    def per_device(kp, vp, pt, q, kt, vt, start, p_end, k_scale=None,
                   v_scale=None):
        p_loc, ps, h, d = kp.shape
        b, c = q.shape[:2]
        l_pages = pt.shape[1]
        n_pages = p_loc * n
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        start = jnp.asarray(start, jnp.int32)
        p_end = jnp.asarray(p_end, jnp.int32)
        cpos = start + jnp.arange(c, dtype=jnp.int32)       # [C]
        real = cpos < p_end                                  # [C]
        lpage = jnp.clip(cpos // ps, 0, l_pages - 1)
        phys = jnp.take_along_axis(
            pt, jnp.broadcast_to(lpage[None, :], (b, c)), axis=1)

        if quantized:
            # fresh per-(page, head) scales for the pages this chunk
            # fills: absmax over the page's REAL tokens. The update is
            # identical on every device (the chunk K/V is replicated),
            # so the replicated scale arrays stay consistent.
            cpp = c // ps                                    # chunks are
            #                       page-aligned: whole pages per chunk

            def page_scales(t):
                tf = jnp.abs(t.astype(jnp.float32))
                tf = jnp.where(real[None, :, None, None], tf, 0.0)
                m = jnp.max(tf.reshape(b, cpp, ps, h, d), axis=(0, 2, 4))
                return jnp.maximum(m, 1e-8) / 127.0          # [cpp, H]

            k_new, v_new = page_scales(kt), page_scales(vt)
            page_real = jnp.max(real.reshape(cpp, ps), axis=1)
            dst = jnp.take_along_axis(
                pt[0], jnp.clip(start // ps, 0, l_pages - 1)
                + jnp.arange(cpp, dtype=jnp.int32), axis=0)
            dst = jnp.where(page_real & (dst >= 0), dst, n_pages)
            k_scale = k_scale.at[dst].set(k_new, mode="drop",
                                          unique_indices=True)
            v_scale = v_scale.at[dst].set(v_new, mode="drop",
                                          unique_indices=True)
            ksc = jnp.repeat(k_new, ps, axis=0)              # [C, H]
            vsc = jnp.repeat(v_new, ps, axis=0)
            kt = jnp.clip(jnp.round(
                kt.astype(jnp.float32) / ksc[None, :, :, None]),
                -127, 127)
            vt = jnp.clip(jnp.round(
                vt.astype(jnp.float32) / vsc[None, :, :, None]),
                -127, 127)

        # splice: scatter each REAL chunk position into its page cell;
        # non-real / not-resident positions redirect past the shard
        # and DROP. Unique: one owner per (page, slot-in-page).
        writer = real[None, :] & (phys >= i * p_loc) & (phys
                                                        < (i + 1) * p_loc)
        pl = jnp.where(writer, phys - i * p_loc, p_loc).reshape(-1)
        sl = jnp.broadcast_to((cpos % ps)[None, :], (b, c)).reshape(-1)
        kp = kp.at[pl, sl].set(
            kt.reshape(-1, h, d).astype(kp.dtype), mode="drop",
            unique_indices=True)
        vp = vp.at[pl, sl].set(
            vt.reshape(-1, h, d).astype(vp.dtype), mode="drop",
            unique_indices=True)
        # per-query attend over the gathered logical view(s)
        out_rows = []
        for rb in range(b):          # prefill runs B=1; keep it general
            kv_view, mine = _page_view(kp, pt[rb:rb + 1], i, p_loc)
            vv_view, _ = _page_view(vp, pt[rb:rb + 1], i, p_loc)
            s = jnp.einsum("bchd,bkhd->bhck", q[rb:rb + 1], kv_view,
                           preferred_element_type=jnp.float32) * scale_
            if quantized:
                ptc = jnp.clip(pt[rb:rb + 1], 0, n_pages - 1)
                ks_view = k_scale[ptc]                    # [1, L, H]
                s = (s.reshape(1, h, c, l_pages, ps)
                     * jnp.moveaxis(ks_view, 2, 1)[:, :, None, :, None]
                     ).reshape(1, h, c, l_pages * ps)
            g = (jnp.arange(l_pages, dtype=jnp.int32)[:, None] * ps
                 + jnp.arange(ps, dtype=jnp.int32)[None, :]).reshape(-1)
            visible = (jnp.repeat(mine, ps, axis=1)[:, None, :]
                       & (g[None, None, :] <= cpos[None, :, None]))
            s = jnp.where(visible[:, None], s, _MASKED)
            m_loc = jnp.max(s, axis=-1)                   # [1, H, C]
            p = jnp.exp(s - m_loc[..., None])
            p = jnp.where(visible[:, None], p, 0.0)
            l_loc = jnp.sum(p, axis=-1)
            if quantized:
                vs_view = v_scale[jnp.clip(pt[rb:rb + 1], 0,
                                           n_pages - 1)]
                p_v = (p.reshape(1, h, c, l_pages, ps)
                       * jnp.moveaxis(vs_view, 2, 1)[:, :, None, :,
                                                     None]
                       ).reshape(1, h, c, l_pages * ps)
            else:
                p_v = p
            acc_loc = jnp.einsum("bhck,bkhd->bhcd", p_v, vv_view,
                                 preferred_element_type=jnp.float32)
            m_glob = lax.pmax(m_loc, axis)
            corr = jnp.exp(m_loc - m_glob)
            l_glob = collectives.psum(l_loc * corr, axis)
            acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
            out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]
            out_rows.append(jnp.moveaxis(out, 1, 2))
        out = jnp.concatenate(out_rows, axis=0).astype(q.dtype)
        if quantized:
            return out, kp, vp, k_scale, v_scale
        return out, kp, vp

    pool_spec, rep, scale_specs = _paged_specs(mesh, axis, quantized)
    tok_spec = P(meshlib.batch_axes(mesh, axis),
                 None, None, None)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(pool_spec, pool_spec, rep, tok_spec, tok_spec,
                  tok_spec, rep, rep) + scale_specs,
        out_specs=((tok_spec, pool_spec, pool_spec) + scale_specs),
        check_vma=False,
    )

    def checked(kp, vp, pt, q, k, v, start, p_end, *scales):
        _check_paged_pool(kp, pt, n, page_size, quantized, scales)
        if q.ndim != 4 or q.shape[1] < 1:
            raise ValueError(f"paged chunk fold expects [B, C, H, D] "
                             f"queries, got shape {jnp.shape(q)}")
        if q.shape[1] % page_size:
            raise ValueError(
                f"chunk {q.shape[1]} must be a multiple of the page "
                f"size {page_size} — chunk boundaries must land on the "
                f"page grid so completed pages are never rewritten")
        return mapped(kp, vp, pt, q, k, v, start, p_end, *scales)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def make_paged_batched_chunk_ring_decode(mesh: Mesh, *, page_size: int,
                                         axis: str = meshlib.SEQ_AXIS,
                                         scale: float | None = None,
                                         jit: bool = False,
                                         quantized: bool = False):
    """Page-table-indirect variant of `make_batched_chunk_ring_decode`
    — the SPECULATIVE-VERIFY fold of the paged engine: ``fn(k_pool,
    v_pool, page_table, q, k, v, pos, live) -> (out, k_pool,
    v_pool)`` runs C draft tokens per slot against the slot's pages,
    each row at its OWN position; rows with live=False append nothing
    and their pages are bit-untouched. Callers (the engine's room
    check) own the bound that live rows' pages cover [pos_b, pos_b+C).
    With ``quantized=True`` appends quantize with the target pages'
    EXISTING scales (decode-region pages are stamped at grant time)
    and the signature grows the two replicated [n_pages, H] scale
    reads — scales are NOT updated here."""
    n = mesh.shape[axis]

    def per_device(kp, vp, pt, q, kt, vt, pos, live, k_scale=None,
                   v_scale=None):
        p_loc, ps, h, d = kp.shape
        s_rows, c = q.shape[:2]
        l_pages = pt.shape[1]
        n_pages = p_loc * n
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        pos = jnp.asarray(pos, jnp.int32)
        live = jnp.asarray(live, jnp.bool_)
        posc = jnp.clip(pos, 0, l_pages * ps - 1)
        qpos = jnp.clip(posc[:, None]
                        + jnp.arange(c, dtype=jnp.int32)[None, :],
                        0, l_pages * ps - 1)               # [S, C]
        lpage = qpos // ps
        phys = jnp.take_along_axis(pt, lpage, axis=1)      # [S, C]
        if quantized:
            ksr = k_scale[jnp.clip(phys, 0, n_pages - 1)]  # [S, C, H]
            vsr = v_scale[jnp.clip(phys, 0, n_pages - 1)]
            kt = jnp.clip(jnp.round(
                kt.astype(jnp.float32) / ksr[..., None]), -127, 127)
            vt = jnp.clip(jnp.round(
                vt.astype(jnp.float32) / vsr[..., None]), -127, 127)
        writer = (live[:, None] & (phys >= i * p_loc)
                  & (phys < (i + 1) * p_loc))
        pl = jnp.where(writer, phys - i * p_loc, p_loc).reshape(-1)
        sl = (qpos % ps).reshape(-1)
        kp = kp.at[pl, sl].set(
            kt.reshape(-1, h, d).astype(kp.dtype), mode="drop",
            unique_indices=True)
        vp = vp.at[pl, sl].set(
            vt.reshape(-1, h, d).astype(vp.dtype), mode="drop",
            unique_indices=True)
        kv_view, mine = _page_view(kp, pt, i, p_loc)
        vv_view, _ = _page_view(vp, pt, i, p_loc)
        s = jnp.einsum("bchd,bkhd->bhck", q, kv_view,
                       preferred_element_type=jnp.float32) * scale_
        if quantized:
            ptc = jnp.clip(pt, 0, n_pages - 1)
            ks_view = k_scale[ptc]                         # [S, L, H]
            s = (s.reshape(s_rows, h, c, l_pages, ps)
                 * jnp.moveaxis(ks_view, 2, 1)[:, :, None, :, None]
                 ).reshape(s_rows, h, c, l_pages * ps)
        g = (jnp.arange(l_pages, dtype=jnp.int32)[:, None] * ps
             + jnp.arange(ps, dtype=jnp.int32)[None, :]).reshape(-1)
        visible = (jnp.repeat(mine, ps, axis=1)[:, None, :]
                   & (g[None, None, :] <= qpos[:, :, None]))
        s = jnp.where(visible[:, None], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)                        # [S, H, C]
        p = jnp.exp(s - m_loc[..., None])
        p = jnp.where(visible[:, None], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)
        if quantized:
            vs_view = v_scale[jnp.clip(pt, 0, n_pages - 1)]
            p_v = (p.reshape(s_rows, h, c, l_pages, ps)
                   * jnp.moveaxis(vs_view, 2, 1)[:, :, None, :, None]
                   ).reshape(s_rows, h, c, l_pages * ps)
        else:
            p_v = p
        acc_loc = jnp.einsum("bhck,bkhd->bhcd", p_v, vv_view,
                             preferred_element_type=jnp.float32)
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]
        return jnp.moveaxis(out, 1, 2).astype(q.dtype), kp, vp

    pool_spec, rep, scale_specs = _paged_specs(mesh, axis, quantized)
    tok_spec = P(meshlib.batch_axes(mesh, axis),
                 None, None, None)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(pool_spec, pool_spec, rep, tok_spec, tok_spec,
                  tok_spec, rep, rep) + scale_specs,
        out_specs=(tok_spec, pool_spec, pool_spec),
        check_vma=False,
    )

    def checked(kp, vp, pt, q, k, v, pos, live, *scales):
        _check_paged_pool(kp, pt, n, page_size, quantized, scales)
        if q.ndim != 4 or q.shape[1] < 1:
            raise ValueError(f"paged batched chunk fold expects "
                             f"[S, C, H, D] queries, got shape "
                             f"{jnp.shape(q)}")
        if jnp.shape(pos) != (pt.shape[0],):
            raise ValueError(
                f"pos must be one position per page-table row, shape "
                f"({pt.shape[0]},); got {jnp.shape(pos)}")
        return mapped(kp, vp, pt, q, k, v, pos, live, *scales)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def make_chunk_ring_decode(mesh: Mesh, *, axis: str = meshlib.SEQ_AXIS,
                           scale: float | None = None,
                           jit: bool = False):
    """Chunked-prefill fold (Sarathi-style): ``fn(k_cache, v_cache, q, k,
    v, start, p_end) -> (out, k_cache, v_cache)`` runs C prompt tokens
    at once against an EXISTING ring cache — the middle ground between
    the one-token decode fold and the whole-prompt training ring.

    q/k/v are the chunk's projections, [B, C, H, D] (replicated over
    `axis`); the chunk occupies global positions [start, start + C) and
    only positions < `p_end` are REAL (both int32 scalars, traced — so
    one compiled program serves every chunk of a prompt AND the ragged
    final chunk). The fold:

    1. appends the chunk's real K/V into the cache — each device
       rewrites its resident shard through a gather + where (positions
       outside [start, p_end) keep their stored value). This is
       O(t_shard) traffic per chunk rather than the decode fold's O(1)
       per token, but it runs once per C tokens and XLA keeps the
       rewrite in place under donation;
    2. attends every chunk query against the WHOLE updated cache with a
       per-query causal visibility mask (cache position <= query
       position — which covers both the already-cached prefix and
       causality INSIDE the chunk, since the chunk's own K/V are in the
       cache by step 1);
    3. merges across the ring with the same stable (m, l, acc) softmax
       algebra as the decode folds — two collectives per CHUNK instead
       of per token.

    Query rows at positions >= p_end (the ragged tail's padding) append
    nothing and their outputs are garbage the caller discards; they
    cannot NaN (their visibility set is non-empty). Requires
    start + C <= t_max (the caller sizes chunks so a chunk never hangs
    past the cache). Defaults to ``jit=False`` for tracing into the
    chunk-prefill program (models/lm.py), whose top-level jit owns
    donation."""
    n = mesh.shape[axis]

    def per_device(kc, vc, q, kt, vt, start, p_end):
        b, t_shard, h, d = kc.shape
        c = q.shape[1]
        i = collectives.axis_index(axis)
        scale_ = scale if scale is not None else d ** -0.5
        start = jnp.asarray(start, jnp.int32)
        p_end = jnp.asarray(p_end, jnp.int32)
        g = i * t_shard + jnp.arange(t_shard, dtype=jnp.int32)  # [t_shard]
        # 1. append: this shard's slots that fall inside [start, p_end)
        # take the chunk row at (g - start); everything else keeps its
        # stored value. A shard fully outside the chunk's span rewrites
        # itself with itself — bit-untouched.
        take_new = (g >= start) & (g < p_end)                 # [t_shard]
        src = jnp.clip(g - start, 0, c - 1)                   # [t_shard]

        def splice(cache, tok):
            gathered = jnp.take(tok, src, axis=1).astype(cache.dtype)
            return jnp.where(take_new[None, :, None, None], gathered,
                             cache)

        kc = splice(kc, kt)
        vc = splice(vc, vt)
        # 2. per-query local attend against the resident shard
        qpos = start + jnp.arange(c, dtype=jnp.int32)         # [C]
        s = jnp.einsum("bchd,bkhd->bhck", q, kc,
                       preferred_element_type=jnp.float32) * scale_
        visible = g[None, :] <= qpos[:, None]                 # [C, t_shard]
        s = jnp.where(visible[None, None], s, _MASKED)
        m_loc = jnp.max(s, axis=-1)                           # [B, H, C]
        p = jnp.exp(s - m_loc[..., None])
        p = jnp.where(visible[None, None], p, 0.0)
        l_loc = jnp.sum(p, axis=-1)                           # [B, H, C]
        acc_loc = jnp.einsum("bhck,bkhd->bhcd", p, vc,
                             preferred_element_type=jnp.float32)
        # 3. one stable softmax merge across the ring (per chunk, not
        # per token)
        m_glob = lax.pmax(m_loc, axis)
        corr = jnp.exp(m_loc - m_glob)
        l_glob = collectives.psum(l_loc * corr, axis)
        acc_glob = collectives.psum(acc_loc * corr[..., None], axis)
        out = acc_glob / jnp.maximum(l_glob, 1e-37)[..., None]  # [B,H,C,D]
        return jnp.moveaxis(out, 1, 2).astype(q.dtype), kc, vc  # [B,C,H,D]

    bo = meshlib.batch_axes(mesh, axis)   # "model" stays weight-only
    cache_spec = P(bo, axis, None, None)
    tok_spec = P(bo, None, None, None)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(cache_spec, cache_spec, tok_spec, tok_spec, tok_spec,
                  P(), P()),
        out_specs=(tok_spec, cache_spec, cache_spec),
        check_vma=False,
    )

    def checked(kc, vc, q, k, v, start, p_end):
        if q.ndim != 4 or q.shape[1] < 1:
            raise ValueError(f"chunk fold expects [B, C, H, D] queries, "
                             f"got shape {jnp.shape(q)}")
        if kc.shape[1] % n:
            raise ValueError(
                f"cache length {kc.shape[1]} not divisible by the ring "
                f"size {n} over mesh axis {axis!r}")
        # concrete out-of-range starts are caller bugs, same contract as
        # the scalar fold (a chunk hanging past t_max would silently
        # drop its tail's append)
        if isinstance(start, (int, np.integer)):
            if not 0 <= int(start) <= kc.shape[1] - q.shape[1]:
                raise ValueError(
                    f"chunk start {int(start)} + chunk {q.shape[1]} "
                    f"outside the cache (t_max {kc.shape[1]})")
        return mapped(kc, vc, q, k, v, start, p_end)

    if not jit:
        return checked
    return jax.jit(checked, donate_argnums=(0, 1))


def prefill(mesh: Mesh, k_prompt, v_prompt, t_max: int, *,
            axis: str = meshlib.SEQ_AXIS, dtype=jnp.bfloat16):
    """Place a prompt's [B, P, H, D] K/V directly into a fresh ring
    cache (pad to t_max, shard) — bit-identical to decoding the prompt
    token by token (pinned by test), without the O(P) python loop.
    Returns (k_cache, v_cache); attention outputs for the prompt itself
    come from the training ring (`make_ring_attention`), which shares
    this layout."""
    b, p_len, h, d = k_prompt.shape
    if p_len > t_max:
        raise ValueError(f"prompt length {p_len} exceeds t_max {t_max}")
    sh = cache_sharding(mesh, axis)
    n = mesh.shape[axis]
    if t_max % n:
        raise ValueError(f"t_max {t_max} not divisible by the ring size "
                         f"{n} over mesh axis {axis!r}")
    pad = ((0, 0), (0, t_max - p_len), (0, 0), (0, 0))
    kc = jnp.pad(jnp.asarray(k_prompt, dtype), pad)
    vc = jnp.pad(jnp.asarray(v_prompt, dtype), pad)
    return (meshlib.put_with_sharding(kc, sh),
            meshlib.put_with_sharding(vc, sh))
