"""Zero-downtime weight rollout: stage -> canary -> promote | rollback.

The only way to change serving weights used to be killing the server.
`RolloutController` replaces that with a state machine over one live
`LMServer`:

1. **staging** — the candidate (a params tree, or a sharded-checkpoint
   path restored against the live engine's mesh + partition rules) is
   spot-checked on the engine's ALREADY-COMPILED programs
   (`SlotEngine.spot_check_params`): NaN/inf or magnitude-blown logits
   roll back HERE, before a single client request ever routes onto the
   new weights — the forced-bad-candidate gate.
2. **canary** — a config-identical second server over the candidate
   (`LMServer.canary_clone`; zero new compiles, the process-wide jit
   cache serves both — and when the live server carries a persistent
   `CompileCache`, the clone config carries it too, so a canary in a
   FRESH process spins warm off the serialized executables instead of
   re-running XLA) takes a controlled fraction of submits. Routing
   is TENANT-AFFINE (the PR 14 placement idea): a tenant's whole
   traffic hashes onto one side, so its prefix locality and quota
   accounting never straddle the split; tenant-less requests hash
   per-id to approximate the fraction. Canary requests FINISH on the
   canary — never dropped, never re-run — so the client sees exactly
   one Result per id whichever way the rollout ends.
3. **decide** — after `canary_requests` canary finishes, SLO burn is
   compared: canary error statuses against `error_budget`, canary TTFT
   p95 against live p95 x `ttft_slack` (the same signals a cluster
   replica's health document carries). Healthy -> **promote**:
   `swap_params` on the live engine (in-flight slots keep decoding
   their old window, zero recompiles), canary drained and closed.
   Unhealthy -> **rollback**: canary drained (its outputs passed the
   staging spot-check — they are valid results, not casualties) and
   closed; the live weights were never touched.

Every transition lands a frozen-schema `serve_rollout` jsonl event and
moves the `serve_rollout_stage_code` gauge (serve/metrics.py).

`run_with_rollout` replays a trace through the controller — the
LMServer.run loop with rollout routing — starting the rollout a
configurable fraction into the trace so the live baseline has real
TTFT samples to compare against. It is the acceptance drill (zero
dropped or duplicated requests, NaN candidate auto-rolled-back with no
client-visible error) in one call; bench.py asserts all of it.
"""

from __future__ import annotations

import hashlib
import time
from pathlib import Path

_STAGES = ("idle", "staging", "canary", "promoted", "rolled_back")


class RolloutError(RuntimeError):
    """Rollout API misuse (wrong stage, re-used controller) — the
    message teaches the correct sequence."""


class RolloutController:
    """Drives ONE candidate-weights rollout over a live LMServer.

    `candidate` is a params pytree, or a sharded-checkpoint directory
    (checkpoint/sharded.py) restored against the live engine's mesh
    and partition rules — a checkpoint saved from an FSDP training
    mesh canaries straight onto a TP serving mesh, re-sharded by rule
    re-resolution, never materialized on one host.

    `canary_fraction` is the traffic share routed onto the candidate
    while the canary stage is open (tenant-affine: whole tenants land
    on one side). `canary_requests` finishes are required before the
    promote/rollback comparison; a trace that ends earlier ROLLS BACK
    — insufficient evidence is not health. `ttft_slack` bounds canary
    TTFT p95 at slack x live p95; `error_budget` is the tolerated
    canary error-status fraction (default 0: any canary error rolls
    back)."""

    def __init__(self, server, candidate, *,
                 canary_fraction: float = 0.25, canary_requests: int = 4,
                 ttft_slack: float = 2.0, error_budget: float = 0.0,
                 logger=None):
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(
                f"canary_fraction must be in (0, 1], got "
                f"{canary_fraction!r} — a zero fraction starves the "
                f"canary of evidence forever, and promoting without "
                f"evidence is not a rollout")
        if canary_requests < 1:
            raise ValueError(f"canary_requests must be >= 1, got "
                             f"{canary_requests!r}")
        self.live = server
        self.canary = None
        self.canary_fraction = float(canary_fraction)
        self.canary_requests = int(canary_requests)
        self.ttft_slack = float(ttft_slack)
        self.error_budget = float(error_budget)
        self.stage = "idle"
        self.reason: str | None = None
        self._canary_done: list = []
        if isinstance(candidate, (str, Path)):
            from idc_models_tpu.checkpoint.sharded import restore_sharded

            engine = server.engine
            rules = engine._partition_rules
            candidate = restore_sharded(
                candidate,
                mesh=engine._cfg.mesh if rules is not None else None,
                rules=rules, logger=logger)
        self.candidate = candidate

    @property
    def canary_finishes(self) -> int:
        """Canary results banked toward the verdict so far."""
        return len(self._canary_done)

    # -- state machine ---------------------------------------------------

    def _transition(self, stage: str, *, outcome=None,
                    reason=None) -> None:
        self.stage = stage
        self.reason = reason
        self.live.metrics.on_rollout(
            stage=stage, outcome=outcome,
            canary_requests=len(self._canary_done), reason=reason)

    def start(self) -> bool:
        """Stage the candidate: spot-check it on the live engine's
        compiled programs, then open the canary. False = the candidate
        failed staging and the rollout is already rolled_back — the
        live server never stopped serving and no client request ever
        touched the bad weights."""
        if self.stage != "idle":
            raise RolloutError(
                f"start() in stage {self.stage!r} — a controller "
                f"drives ONE rollout; build a fresh one for the next "
                f"candidate")
        self._transition("staging")
        engine = self.live.engine
        if engine.paged and engine._pending is not None:
            # the paged spot-check replays through the pool caches,
            # which an in-flight window owns — collect it first
            self.live.quiesce()
        check = engine.spot_check_params(self.candidate)
        if not check["ok"]:
            detail = {1: "non-finite logits",
                      2: (f"magnitude-blown logits "
                          f"(max |x| = {check['max_abs']:.3g})")}
            self._transition(
                "rolled_back", outcome="rolled_back",
                reason=f"staging spot-check failed: "
                       f"{detail[check['code']]}")
            return False
        self.canary = self.live.canary_clone(self.candidate)
        self._transition("canary")
        return True

    def routes_to_canary(self, request) -> bool:
        """The tenant-affine split: deterministic in the tenant name
        (or the request id when tenant-less), so a tenant's traffic
        never straddles the two prefix caches / quota ledgers."""
        if self.canary is None or self.stage != "canary":
            return False
        key = (request.tenant if request.tenant is not None
               else request.id)
        h = int(hashlib.sha256(key.encode()).hexdigest()[:8], 16)
        return h / 0x100000000 < self.canary_fraction

    def _target(self, request):
        return self.canary if self.routes_to_canary(request) else self.live

    def submit(self, request) -> bool:
        """Route one submit: the canary fraction onto the candidate
        while the canary stage is open, everything else — and
        everything before staging or after the decision — onto the
        live server. Same False-on-backpressure contract as
        LMServer.submit."""
        return self._target(request).submit(request)

    def step(self) -> list:
        """One cycle of both sides, merged; runs the promote/rollback
        decision as soon as the canary has `canary_requests`
        finishes."""
        out = self.live.step()
        if self.canary is not None and self.stage == "canary":
            done = self.canary.step()
            self._canary_done.extend(done)
            out.extend(done)
            if len(self._canary_done) >= self.canary_requests:
                self._decide()
        return out

    def poll(self, rid: str):
        r = self.live.poll(rid)
        if r is None and self.canary is not None:
            r = self.canary.poll(rid)
        return r

    def idle(self) -> bool:
        return (self.live.scheduler.idle()
                and (self.canary is None
                     or self.canary.scheduler.idle()))

    def finish(self) -> str:
        """End-of-trace settlement: a canary still open decides NOW.
        With `canary_requests` finishes banked the normal comparison
        runs; with fewer, the rollout ROLLS BACK — a trace that ended
        before the canary earned its evidence does not get promoted on
        vibes. Returns the terminal stage."""
        if self.stage == "canary":
            if len(self._canary_done) >= self.canary_requests:
                self._decide()
            else:
                self._rollback(
                    f"trace ended with {len(self._canary_done)} canary "
                    f"finishes < canary_requests="
                    f"{self.canary_requests} — not enough evidence to "
                    f"promote")
        return self.stage

    def results(self) -> list:
        """Every finished Result from both sides — exactly one per
        request id (the router sends each id to exactly one side)."""
        merged = {r.id: r for r in self.live.results()}
        if self.canary is not None:
            for r in self.canary.results():
                merged.setdefault(r.id, r)
        return list(merged.values())

    # -- decision --------------------------------------------------------

    def _decide(self) -> None:
        bad = [r for r in self._canary_done
               if r.status not in ("ok", "timeout")]
        if len(bad) > self.error_budget * len(self._canary_done):
            first = f"{bad[0].status} {bad[0].error or ''}".strip()
            self._rollback(
                f"canary error burn: {len(bad)}/"
                f"{len(self._canary_done)} finishes errored (budget "
                f"{self.error_budget:.0%}); first: {first}")
            return
        lp95 = self.live.summary().get("serve_ttft_ms_p95")
        cp95 = self.canary.summary().get("serve_ttft_ms_p95")
        if (lp95 is not None and cp95 is not None and lp95 > 0
                and cp95 > self.ttft_slack * lp95):
            self._rollback(
                f"canary SLO burn: TTFT p95 {cp95:.1f} ms > "
                f"{self.ttft_slack:.1f}x live {lp95:.1f} ms")
            return
        self._promote()

    def _drain_canary(self) -> None:
        # finish every in-flight canary request ON the canary — its
        # weights passed the spot-check, so the outputs are valid
        # results, not casualties. Zero drops on either verdict.
        if self.canary is None:
            return
        while not self.canary.scheduler.idle():
            self._canary_done.extend(self.canary.step())
        self.canary.close()

    def _promote(self) -> None:
        self._drain_canary()
        self.live.swap_params(self.candidate)
        self._transition("promoted", outcome="promoted")

    def _rollback(self, reason: str) -> None:
        self._drain_canary()
        self._transition("rolled_back", outcome="rolled_back",
                         reason=reason)


def run_with_rollout(server, trace, candidate, *,
                     start_after: float = 0.25, realtime: bool = False,
                     on_full: str = "block", **controller_kw):
    """Replay `[(arrival_s, Request), ...]` while rolling `candidate`
    out mid-trace — LMServer.run with the controller in the submit
    path. The rollout starts once `start_after` of the trace has been
    offered (the live baseline needs real TTFT samples to judge the
    canary against); the trace then drains through promote or rollback
    either way. Returns `(results, controller)`; results carry exactly
    one Result per trace id — zero dropped, zero duplicated."""
    from idc_models_tpu.serve.api import Result

    if on_full not in ("block", "reject"):
        raise ValueError(f"on_full must be 'block' or 'reject', got "
                         f"{on_full!r}")
    if not 0.0 <= start_after < 1.0:
        raise ValueError(f"start_after must be in [0, 1), got "
                         f"{start_after!r} — starting at/after the end "
                         f"of the trace means the canary never sees a "
                         f"request")
    ctl = RolloutController(server, candidate, **controller_kw)
    trace = sorted(trace, key=lambda tr: tr[0])
    start_idx = int(len(trace) * start_after)
    clock = server.scheduler.clock
    t0 = clock()
    out, i = [], 0
    while i < len(trace) or not ctl.idle():
        now = clock() - t0
        while i < len(trace) and (not realtime or trace[i][0] <= now):
            # open the rollout the moment the trace position crosses
            # start_after — INSIDE the offer loop, because a burst
            # trace (all arrivals at 0) submits everything in one tick
            if ctl.stage == "idle" and i >= start_idx:
                ctl.start()
            req = trace[i][1]
            target = ctl._target(req)
            # same block-mode etiquette as LMServer.run: don't OFFER a
            # request the target queue cannot take (a refused submit
            # counts as a rejection in its metrics)
            shedding = (target.brownout is not None
                        and target.brownout.shedding)
            if (on_full == "block" and not shedding
                    and len(target.scheduler.queue)
                    >= target.scheduler.queue.max_depth):
                break                   # blocked: re-offer next tick
            if ctl.submit(req):
                i += 1
                continue
            shed = ctl.poll(req.id)
            if shed is not None and shed.status == "shed":
                out.append(shed)
                i += 1
            elif on_full == "reject":
                r = Result(id=req.id, tokens=[], status="rejected")
                target._results[r.id] = r
                out.append(r)
                i += 1
            else:
                break                   # blocked: re-offer next tick
        if realtime and ctl.idle() and i < len(trace):
            time.sleep(min(max(trace[i][0] - (clock() - t0), 0.0),
                           0.005))
            continue
        out.extend(ctl.step())
    ctl.finish()
    # canary requests that finished inside the promote/rollback drain
    # never came back through step() — reconcile so the return carries
    # exactly one Result per trace id
    have = {r.id for r in out}
    out.extend(r for r in ctl.results() if r.id not in have)
    return out, ctl
