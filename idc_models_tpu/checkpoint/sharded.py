"""Sharded checkpointing keyed on `partition.py` rules (ROADMAP item 4).

`train/checkpoint.py` durably saves a REPLICATED tree: orbax gathers,
one host writes, and the digest pass used to `jax.device_get` the whole
state — O(model) host memory, exactly what PR 15's FSDP/TP sharding
exists to avoid. This module is the sharded successor:

- **Save** walks `partition.tree_paths(tree)` and writes each leaf's
  *addressable* shards (`replica_id == 0` only, so every unique block
  is written exactly once across hosts AND replicas), one shard in
  host memory at a time. Each shard file carries its own sha256,
  committed tmp-then-`os.replace` so a torn write is never readable
  under the final name.
- **Manifest as the completion contract**: shard files alone mean
  nothing. Every process writes a `_SHARDS.p<i>.json` fragment listing
  the shards it committed; after a cross-host barrier, process 0 merges
  the fragments into `MANIFEST.json` (itself tmp-then-rename). A
  directory without a manifest IS a torn checkpoint and restore
  refuses it — the same marker discipline as `train/checkpoint.py`,
  with the digest riding per shard instead of per tree.
- **Restore re-resolves rules against the TARGET mesh**: the manifest
  stores shapes/dtypes, `rules.spec_for` + mesh adaptation decide the
  target layout, and each device's block is assembled via
  `jax.make_array_from_callback` from only the saved shards that
  OVERLAP it — an FSDP-mesh checkpoint loads bit-identically onto a TP
  mesh or a different device count without ever materializing the full
  tree on one host (peak host bytes ~ one target block + one saved
  shard, reported in `stats`).
- **Async**: `save_sharded(..., wait=False)` returns a `SaveHandle`
  whose background thread fetches/writes/commits; `.wait()` is the
  durability point. The caller must not donate or mutate the tree
  before `.wait()` returns (the thread reads the live buffers).

Writes under a checkpoint directory are allowed ONLY through
`_write_bytes`/`_commit_json` here (and orbax inside
`train/checkpoint.py`) — a static AST scan in
tests/test_static_robustness.py bans raw `open(...,"w")`/`np.save`/
`shutil` writes outside that allowlist, so every byte that lands in a
checkpoint went through an atomic tmp-then-rename commit.

Events (frozen schemas, tests/test_observability.py): `ckpt_save` and
`ckpt_restore`, one per completed operation; registry instruments
`ckpt_saves_total` / `ckpt_restores_total` / `ckpt_bytes_written_total`
/ `ckpt_bytes_read_total` and second histograms from day one.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from pathlib import Path

import numpy as np

MANIFEST_NAME = "MANIFEST.json"
FORMAT_VERSION = 1
_FRAGMENT = "_SHARDS.p{}.json"


class CheckpointError(ValueError):
    """A torn/corrupt/mismatched checkpoint, with a teaching message."""


def barrier(tag: str) -> None:
    """Cross-host sync point (no-op in a single-process run) — the
    fence between "every host committed its shards" and "process 0
    commits the manifest", and between "manifest committed" and "any
    host returns". Shared with train/checkpoint.py's rename dance."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"idc-ckpt-{tag}")


def _registry(registry):
    if registry is not None:
        return registry
    from idc_models_tpu.observe.metrics_registry import REGISTRY

    return REGISTRY


def _instruments(reg):
    return {
        "saves": reg.counter(
            "ckpt_saves_total", "completed sharded checkpoint saves"),
        "restores": reg.counter(
            "ckpt_restores_total",
            "completed sharded checkpoint restores"),
        "bytes_written": reg.counter(
            "ckpt_bytes_written_total",
            "shard bytes committed by sharded saves"),
        "bytes_read": reg.counter(
            "ckpt_bytes_read_total",
            "shard bytes read by sharded restores"),
        "save_s": reg.histogram(
            "ckpt_save_seconds", "wall seconds per sharded save"),
        "restore_s": reg.histogram(
            "ckpt_restore_seconds", "wall seconds per sharded restore"),
    }


def _dtype_str(dt) -> str:
    return str(np.dtype(dt))


def _dtype_from_str(s: str):
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def _shard_file(name: str, spans) -> str:
    """Deterministic shard filename: leaf path + the block's start
    offsets (unique per block — two shards of one leaf never share a
    start corner)."""
    corner = "_".join(str(lo) for lo, _ in spans) or "scalar"
    return f"{name.replace('/', '.')}@{corner}"


def _norm_index(index, shape) -> tuple:
    """A jax shard `index` (tuple of slices, Nones for full dims) ->
    ((start, stop), ...) resolved against the leaf shape."""
    index = tuple(index)
    out = []
    for dim, sl in zip(shape, index):
        lo = 0 if sl.start is None else int(sl.start)
        hi = dim if sl.stop is None else int(sl.stop)
        out.append((lo, hi))
    # jax omits trailing full dims for rank-0 indices; pad explicit
    for dim in shape[len(index):]:
        out.append((0, dim))
    return tuple(out)


def _write_bytes(dirpath: Path, relfile: str, buf: bytes) -> str:
    """THE atomic byte commit (static-scan allowlisted): write to a
    tmp name, fsync, rename into place. Returns the sha256 hex."""
    h = hashlib.sha256(buf).hexdigest()
    tmp = dirpath / (relfile + ".tmp")
    with open(tmp, "wb") as f:
        f.write(buf)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dirpath / relfile)
    return h


def _commit_json(dirpath: Path, relfile: str, doc: dict) -> None:
    """Atomic JSON commit (static-scan allowlisted) — the manifest and
    fragment writer."""
    _write_bytes(dirpath, relfile,
                 json.dumps(doc, sort_keys=True).encode())


def _leaf_shards(leaf):
    """[(spans, host_fetch)] for THIS process's unique blocks of one
    leaf. jax arrays yield their addressable replica-0 shards (each
    distinct block written exactly once across replicas/hosts); host
    leaves yield one full-leaf block on process 0 only."""
    import jax

    shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
    if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
        out = []
        for sh in leaf.addressable_shards:
            if sh.replica_id != 0:
                continue
            spans = _norm_index(sh.index, shape)
            out.append((spans, (lambda s=sh: np.asarray(s.data))))
        return out
    if jax.process_index() != 0:
        return []
    spans = tuple((0, d) for d in shape)
    return [(spans, (lambda a=leaf: np.asarray(a)))]


class SaveHandle:
    """An in-flight (or finished) sharded save. `.wait()` is the
    durability point: it joins the writer, re-raises its failure, and
    returns the committed manifest."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._manifest: dict | None = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def wait(self, timeout: float | None = None) -> dict:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("sharded save still writing")
        if self._error is not None:
            raise self._error
        assert self._manifest is not None
        return self._manifest

    @property
    def manifest(self) -> dict:
        return self.wait()


def save_sharded(path, tree, *, step: int | None = None,
                 wait: bool = True, logger=None,
                 registry=None) -> SaveHandle:
    """Write `tree` as a sharded checkpoint under `path`.

    Every process writes only its own addressable replica-0 shards
    (one shard resident in host memory at a time — peak host bytes is
    O(largest shard), never O(model)), then process 0 commits
    `MANIFEST.json` behind a barrier: the manifest IS the completion
    contract, and a directory without one is a torn save `restore_
    sharded` refuses.

    `wait=False` runs the fetch/write/commit on a background thread
    and returns immediately; call `.wait()` before donating or
    mutating the tree (the writer reads the live buffers). The handle
    from `wait=True` is already finished."""
    import jax

    from idc_models_tpu import partition

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    proc = jax.process_index()
    plan = []                      # (name, spans, fetch)
    leaves = {}
    for name, leaf in partition.tree_paths(tree):
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = getattr(leaf, "dtype", np.asarray(leaf).dtype)
        leaves[name] = {"shape": list(shape),
                        "dtype": _dtype_str(dtype), "shards": []}
        for spans, fetch in _leaf_shards(leaf):
            plan.append((name, spans, fetch))
    reg = _instruments(_registry(registry))
    handle = SaveHandle()

    def _run() -> None:
        t0 = time.perf_counter()
        frag: dict[str, list] = {}
        total = 0
        for name, spans, fetch in plan:
            arr = np.ascontiguousarray(fetch())   # ONE shard resident
            buf = arr.tobytes()
            relfile = _shard_file(name, spans)
            digest = _write_bytes(path, relfile, buf)
            frag.setdefault(name, []).append({
                "file": relfile, "index": [list(s) for s in spans],
                "sha256": digest, "bytes": len(buf)})
            total += len(buf)
            del arr, buf
        _commit_json(path, _FRAGMENT.format(proc), frag)
        barrier("save-shards")
        if proc == 0:
            n_shards = 0
            for fp in sorted(path.glob(_FRAGMENT.format("*"))):
                for name, shards in json.loads(fp.read_text()).items():
                    leaves[name]["shards"].extend(shards)
                    n_shards += len(shards)
            manifest = {
                "format": FORMAT_VERSION, "step": step,
                "leaves": leaves, "n_shards": n_shards,
                "nbytes": sum(s["bytes"] for rec in leaves.values()
                              for s in rec["shards"])}
            for name, rec in leaves.items():
                if not rec["shards"]:
                    raise CheckpointError(
                        f"no process wrote any shard of leaf {name!r} "
                        f"— the manifest would commit a hole")
                rec["shards"].sort(key=lambda s: s["file"])
            _commit_json(path, MANIFEST_NAME, manifest)
            for fp in path.glob(_FRAGMENT.format("*")):
                fp.unlink()
        else:
            manifest = None
        barrier("save-manifest")
        if manifest is None:
            manifest = json.loads((path / MANIFEST_NAME).read_text())
        dt = time.perf_counter() - t0
        reg["saves"].inc()
        reg["bytes_written"].inc(total)
        reg["save_s"].observe(dt)
        if logger is not None:
            logger.log(event="ckpt_save", path=str(path), step=step,
                       leaves=len(leaves), shards=len(plan),
                       bytes=total, seconds=round(dt, 6),
                       background=not wait)
        handle._manifest = manifest

    if wait:
        _run()
        return handle

    def _guarded() -> None:
        try:
            _run()
        except BaseException as e:          # surfaced at .wait()
            handle._error = e

    handle._thread = threading.Thread(target=_guarded,
                                      name="ckpt-save", daemon=True)
    handle._thread.start()
    return handle


def checkpoint_info(path) -> dict:
    """The committed manifest, or a CheckpointError teaching why the
    directory is not a restorable checkpoint (missing = torn save)."""
    path = Path(path)
    mf = path / MANIFEST_NAME
    if not mf.exists():
        raise CheckpointError(
            f"{path} has no {MANIFEST_NAME} — not a completed sharded "
            f"checkpoint. The manifest is the atomic completion "
            f"contract (committed last, behind a barrier): its absence "
            f"means the save was interrupted or this directory never "
            f"held a checkpoint. Re-save, or point at a directory "
            f"containing {MANIFEST_NAME}")
    try:
        manifest = json.loads(mf.read_text())
    except ValueError as e:
        raise CheckpointError(
            f"{mf} is not valid JSON ({e}) — the manifest commit is "
            f"atomic (tmp + rename), so this is disk corruption, not "
            f"a torn write; the checkpoint cannot be trusted") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointError(
            f"{mf} is format {manifest.get('format')!r}, this reader "
            f"speaks {FORMAT_VERSION}")
    return manifest


def _read_shard(path: Path, shard: dict, dtype, verified: set,
                stats: dict) -> np.ndarray:
    """One saved shard back as an array, sha256-verified the first
    time this restore touches its file."""
    fp = path / shard["file"]
    if not fp.exists():
        raise CheckpointError(
            f"manifest names shard {shard['file']!r} but the file is "
            f"missing from {path} — the checkpoint directory was "
            f"partially deleted; restore refuses to fabricate the "
            f"block")
    buf = fp.read_bytes()
    if shard["file"] not in verified:
        if hashlib.sha256(buf).hexdigest() != shard["sha256"]:
            raise CheckpointError(
                f"shard {shard['file']!r} fails its manifest sha256 — "
                f"bytes on disk are not the bytes the save committed "
                f"(bit rot or tampering); refusing to restore a "
                f"corrupt block")
        verified.add(shard["file"])
    stats["bytes_read"] = stats.get("bytes_read", 0) + len(buf)
    stats["shards_read"] = stats.get("shards_read", 0) + 1
    spans = shard["index"]
    shape = tuple(hi - lo for lo, hi in spans)
    arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
    if arr.nbytes != shard["bytes"]:
        raise CheckpointError(
            f"shard {shard['file']!r} holds {arr.nbytes} bytes but the "
            f"manifest promised {shard['bytes']}")
    return arr


def _assemble(path: Path, rec: dict, spans, verified: set,
              stats: dict) -> np.ndarray:
    """The requested block of one leaf, assembled from only the saved
    shards that OVERLAP it — one saved shard resident at a time, so
    peak host bytes is the block plus one shard, never the leaf set."""
    dtype = _dtype_from_str(rec["dtype"])
    out = np.empty(tuple(hi - lo for lo, hi in spans), dtype)
    filled = 0
    for shard in rec["shards"]:
        inter = [(max(lo, slo), min(hi, shi))
                 for (lo, hi), (slo, shi) in zip(spans, shard["index"])]
        if any(lo >= hi for lo, hi in inter):
            continue
        data = _read_shard(path, shard, dtype, verified, stats)
        src = tuple(slice(lo - slo, hi - slo) for (lo, hi), (slo, _)
                    in zip(inter, shard["index"]))
        dst = tuple(slice(lo - rlo, hi - rlo) for (lo, hi), (rlo, _)
                    in zip(inter, spans))
        out[dst] = data[src]
        peak = out.nbytes + data.nbytes
        stats["peak_host_bytes"] = max(stats.get("peak_host_bytes", 0),
                                       peak)
        filled += int(np.prod([hi - lo for lo, hi in inter]))
        del data
    if filled != out.size:
        raise CheckpointError(
            f"saved shards cover {filled} of {out.size} elements of a "
            f"requested block — the manifest's shards do not tile the "
            f"leaf (a save bug, not a mesh mismatch: restore handles "
            f"any target layout)")
    stats["peak_host_bytes"] = max(stats.get("peak_host_bytes", 0),
                                   out.nbytes)
    return out


def _nest(name: str, value) -> dict:
    """A single-leaf nested dict whose `tree_paths` name is exactly
    `name` — so rule regexes see the same "a/b/c" path the save
    recorded, not a mangled flat key."""
    out: dict = {}
    node, parts = out, name.split("/")
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value
    return out


def restore_sharded(path, *, mesh=None, rules=None, template=None,
                    check_dead: bool = True, stats: dict | None = None,
                    logger=None, registry=None):
    """Load a sharded checkpoint back as a pytree.

    With `mesh` + `rules`, specs are re-resolved against the TARGET
    mesh (`rules.spec_for` + the same adaptation `shard_tree` applies)
    and every device block is built via `jax.make_array_from_callback`
    from only the overlapping saved shards — the save-time mesh shape
    and device count are irrelevant, and the full tree never exists on
    one host. Without a mesh the tree comes back as host numpy arrays
    (the caller opted into O(model) host memory).

    `template` (any pytree with the same leaf names) fixes the tree
    STRUCTURE for non-dict containers; by default the manifest's
    "a/b/c" names rebuild nested dicts. `stats`, if a dict, is filled
    with bytes_read / shards_read / peak_host_bytes — the numbers the
    per-device-peak gate asserts."""
    import jax

    from idc_models_tpu import partition

    if (mesh is None) != (rules is None):
        raise CheckpointError(
            "pass BOTH mesh and rules (sharded restore re-resolves the "
            "rules against the target mesh) or neither (host restore)")
    t0 = time.perf_counter()
    manifest = checkpoint_info(path)
    path = Path(path)
    recs = manifest["leaves"]
    stats = stats if stats is not None else {}
    verified: set[str] = set()

    def build(name: str, rec: dict):
        shape = tuple(rec["shape"])
        dtype = _dtype_from_str(rec["dtype"])
        if mesh is None:
            spans = tuple((0, d) for d in shape)
            return _assemble(path, rec, spans, verified, stats)
        struct = jax.ShapeDtypeStruct(shape, dtype)
        sharding = jax.tree.leaves(rules.shardings(
            mesh, _nest(name, struct), check_dead=False))[0]

        def cb(index):
            return _assemble(path, rec, _norm_index(index, shape),
                             verified, stats)

        return jax.make_array_from_callback(shape, sharding, cb)

    if rules is not None and check_dead:
        # dead-rule discipline against the CHECKPOINT's leaf names —
        # a rule matching nothing saved is the same silent-sharding
        # loss shard_tree refuses
        live = {i for n in recs
                for i in [rules._match(n)[0]] if i is not None}
        dead = [rules.patterns[i] for i in range(len(rules.patterns))
                if i not in live]
        if dead:
            raise partition.PartitionError(
                f"dead partition rule(s) {dead}: they match none of "
                f"the {len(recs)} checkpointed leaves — the rule set "
                f"and this checkpoint describe different models "
                f"(restore with check_dead=False for a deliberately "
                f"partial rule set)")

    built = {name: build(name, rec) for name, rec in recs.items()}
    if template is not None:
        t_names = [n for n, _ in partition.tree_paths(template)]
        missing = [n for n in t_names if n not in built]
        extra = [n for n in built if n not in t_names]
        if missing or extra:
            raise CheckpointError(
                f"template/checkpoint leaf mismatch: template-only "
                f"{missing}, checkpoint-only {extra} — the template "
                f"must name exactly the saved leaves")
        tree = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template),
            [built[n] for n in t_names])
    else:
        tree = {}
        for name, leaf in built.items():
            node, parts = tree, name.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = leaf
    dt = time.perf_counter() - t0
    reg = _instruments(_registry(registry))
    reg["restores"].inc()
    reg["bytes_read"].inc(stats.get("bytes_read", 0))
    reg["restore_s"].observe(dt)
    if logger is not None:
        logger.log(event="ckpt_restore", path=str(path),
                   leaves=len(recs),
                   shards_read=stats.get("shards_read", 0),
                   bytes_read=stats.get("bytes_read", 0),
                   peak_host_bytes=stats.get("peak_host_bytes", 0),
                   seconds=round(dt, 6), sharded=mesh is not None)
    return tree
