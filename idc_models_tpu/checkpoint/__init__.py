"""Sharded checkpointing + zero-downtime weight rollout (ROADMAP 4).

`sharded` — per-shard async save/restore keyed on partition.py rules:
each device writes only its own blocks, an atomic MANIFEST.json is the
completion contract, and restore re-resolves rules against the TARGET
mesh (an FSDP checkpoint loads bit-identically onto a TP mesh or a
different device count) without materializing the tree on one host.

`rollout` — serve-side hot weight swap: stage a candidate behind the
live params, spot-check it on the engine's already-compiled programs,
canary a controlled fraction of live traffic, compare SLO burn, then
promote (`SlotEngine.swap_params`) or roll back with zero dropped or
duplicated requests.
"""

from idc_models_tpu.checkpoint.rollout import (
    RolloutController, run_with_rollout,
)
from idc_models_tpu.checkpoint.sharded import (
    MANIFEST_NAME, CheckpointError, SaveHandle, barrier,
    checkpoint_info, restore_sharded, save_sharded,
)

__all__ = [
    "MANIFEST_NAME", "CheckpointError", "SaveHandle", "barrier",
    "checkpoint_info", "restore_sharded", "save_sharded",
    "RolloutController", "run_with_rollout",
]
